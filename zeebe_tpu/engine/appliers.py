"""Event appliers: the only code allowed to mutate engine state.

Reference: engine/src/main/java/io/camunda/zeebe/engine/state/appliers/ (65
files; EventAppliers.java:48 registers one TypedEventApplier per intent).
``apply`` is called both during processing (via the StateWriter, immediately
after the event is appended to the result) and during replay — by construction
the same code path, which is what makes replay ≡ processing.

Every applier also feeds the key generator (``observe_key``) so replay
restores the highest assigned key (reference: ReplayStateMachine key restore).
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.engine.engine_state import (
    EI_ACTIVATED,
    EI_ACTIVATING,
    EI_COMPLETED,
    EI_COMPLETING,
    EI_TERMINATED,
    EI_TERMINATING,
    EngineState,
)
from zeebe_tpu.protocol import DEFAULT_TENANT, Record, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType
from zeebe_tpu.protocol.intent import (
    DeploymentIntent,
    IncidentIntent,
    JobBatchIntent,
    JobIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    ProcessIntent,
    SignalIntent,
    TimerIntent,
    VariableIntent,
)


class EventAppliers:
    on_checkpoint_applied = None  # set by the owning partition (cache hook)

    def __init__(self, state: EngineState) -> None:
        self.state = state
        self._appliers: dict[tuple[ValueType, int], Callable[[Record], None]] = {}
        self._register()
    def _register(self) -> None:
        reg = self._appliers
        reg[(ValueType.PROCESS, int(ProcessIntent.CREATED))] = self._process_created
        from zeebe_tpu.protocol.intent import FormIntent

        reg[(ValueType.FORM, int(FormIntent.CREATED))] = self._form_created
        reg[(ValueType.FORM, int(FormIntent.DELETED))] = self._form_deleted
        from zeebe_tpu.protocol.intent import ProcessInstanceBatchIntent

        reg[(ValueType.PROCESS_INSTANCE_BATCH,
             int(ProcessInstanceBatchIntent.ACTIVATED))] = self._pi_batch_activated
        reg[(ValueType.PROCESS_INSTANCE_BATCH,
             int(ProcessInstanceBatchIntent.TERMINATED))] = self._noop
        reg[(ValueType.DEPLOYMENT, int(DeploymentIntent.CREATED))] = self._noop
        reg[(ValueType.DEPLOYMENT, int(DeploymentIntent.FULLY_DISTRIBUTED))] = self._noop
        reg[(ValueType.PROCESS_INSTANCE_CREATION, int(ProcessInstanceCreationIntent.CREATED))] = self._noop
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_ACTIVATING))] = self._element_activating
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_ACTIVATED))] = self._element_activated
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_COMPLETING))] = self._element_completing
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_COMPLETED))] = self._element_completed
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_TERMINATING))] = self._element_terminating
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.ELEMENT_TERMINATED))] = self._element_terminated
        reg[(ValueType.PROCESS_INSTANCE, int(ProcessInstanceIntent.SEQUENCE_FLOW_TAKEN))] = self._sequence_flow_taken
        reg[(ValueType.JOB, int(JobIntent.CREATED))] = self._job_created
        reg[(ValueType.JOB, int(JobIntent.COMPLETED))] = self._job_completed
        reg[(ValueType.JOB, int(JobIntent.FAILED))] = self._job_failed
        reg[(ValueType.JOB, int(JobIntent.TIMED_OUT))] = self._job_timed_out
        reg[(ValueType.JOB, int(JobIntent.RETRIES_UPDATED))] = self._job_retries_updated
        reg[(ValueType.JOB, int(JobIntent.CANCELED))] = self._job_canceled
        reg[(ValueType.JOB, int(JobIntent.RECURRED_AFTER_BACKOFF))] = self._job_recurred
        reg[(ValueType.JOB, int(JobIntent.YIELDED))] = self._job_yielded
        reg[(ValueType.JOB, int(JobIntent.TIMEOUT_UPDATED))] = self._job_timeout_updated
        reg[(ValueType.JOB, int(JobIntent.ERROR_THROWN))] = self._job_error_thrown
        reg[(ValueType.JOB_BATCH, int(JobBatchIntent.ACTIVATED))] = self._job_batch_activated
        reg[(ValueType.VARIABLE, int(VariableIntent.CREATED))] = self._variable_set
        reg[(ValueType.VARIABLE, int(VariableIntent.UPDATED))] = self._variable_set
        reg[(ValueType.INCIDENT, int(IncidentIntent.CREATED))] = self._incident_created
        reg[(ValueType.INCIDENT, int(IncidentIntent.RESOLVED))] = self._incident_resolved
        from zeebe_tpu.protocol.intent import (
            MessageBatchIntent,
            MessageIntent,
            MessageStartEventSubscriptionIntent,
            MessageSubscriptionIntent,
            ProcessMessageSubscriptionIntent,
            VariableDocumentIntent,
        )

        reg[(ValueType.VARIABLE_DOCUMENT, int(VariableDocumentIntent.UPDATED))] = self._noop
        reg[(ValueType.TIMER, int(TimerIntent.CREATED))] = self._timer_created
        reg[(ValueType.TIMER, int(TimerIntent.TRIGGERED))] = self._timer_removed
        reg[(ValueType.TIMER, int(TimerIntent.CANCELED))] = self._timer_removed
        reg[(ValueType.MESSAGE, int(MessageIntent.PUBLISHED))] = self._message_published
        reg[(ValueType.MESSAGE, int(MessageIntent.EXPIRED))] = self._message_removed
        reg[(ValueType.MESSAGE_BATCH, int(MessageBatchIntent.EXPIRED))] = self._message_batch_expired
        reg[(ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.CREATED))] = self._msg_sub_created
        reg[(ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.CORRELATING))] = self._msg_sub_correlating
        reg[(ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.CORRELATED))] = self._msg_sub_correlated
        reg[(ValueType.MESSAGE_SUBSCRIPTION, int(MessageSubscriptionIntent.DELETED))] = self._msg_sub_deleted
        reg[(ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.CREATING))] = self._pms_creating
        reg[(ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.CREATED))] = self._pms_created
        reg[(ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.CORRELATED))] = self._pms_correlated
        reg[(ValueType.PROCESS_MESSAGE_SUBSCRIPTION, int(ProcessMessageSubscriptionIntent.DELETED))] = self._pms_deleted
        reg[(ValueType.MESSAGE_START_EVENT_SUBSCRIPTION, int(MessageStartEventSubscriptionIntent.CREATED))] = self._msg_start_created
        reg[(ValueType.MESSAGE_START_EVENT_SUBSCRIPTION, int(MessageStartEventSubscriptionIntent.CORRELATED))] = self._noop
        reg[(ValueType.MESSAGE_START_EVENT_SUBSCRIPTION, int(MessageStartEventSubscriptionIntent.DELETED))] = self._msg_start_deleted
        from zeebe_tpu.protocol.intent import (
            EscalationIntent,
            SignalIntent,
            SignalSubscriptionIntent,
        )

        reg[(ValueType.SIGNAL, int(SignalIntent.BROADCASTED))] = self._noop
        reg[(ValueType.SIGNAL_SUBSCRIPTION, int(SignalSubscriptionIntent.CREATED))] = self._signal_sub_created
        reg[(ValueType.SIGNAL_SUBSCRIPTION, int(SignalSubscriptionIntent.DELETED))] = self._signal_sub_deleted
        reg[(ValueType.ESCALATION, int(EscalationIntent.ESCALATED))] = self._noop
        reg[(ValueType.ESCALATION, int(EscalationIntent.NOT_ESCALATED))] = self._noop
        from zeebe_tpu.protocol.intent import CommandDistributionIntent

        reg[(ValueType.COMMAND_DISTRIBUTION, int(CommandDistributionIntent.STARTED))] = self._distribution_started
        reg[(ValueType.COMMAND_DISTRIBUTION, int(CommandDistributionIntent.DISTRIBUTING))] = self._distribution_distributing
        reg[(ValueType.COMMAND_DISTRIBUTION, int(CommandDistributionIntent.ACKNOWLEDGED))] = self._distribution_acknowledged
        reg[(ValueType.COMMAND_DISTRIBUTION, int(CommandDistributionIntent.FINISHED))] = self._distribution_finished
        reg[(ValueType.DEPLOYMENT, int(DeploymentIntent.DISTRIBUTED))] = self._noop
        from zeebe_tpu.protocol.intent import ProcessInstanceResultIntent

        reg[(ValueType.PROCESS_INSTANCE_RESULT, int(ProcessInstanceResultIntent.COMPLETED))] = self._noop
        from zeebe_tpu.protocol.intent import (
            DecisionEvaluationIntent,
            DecisionIntent,
            DecisionRequirementsIntent,
        )

        reg[(ValueType.DECISION_REQUIREMENTS, int(DecisionRequirementsIntent.CREATED))] = self._drg_created
        reg[(ValueType.DECISION, int(DecisionIntent.CREATED))] = self._decision_created
        reg[(ValueType.DECISION_EVALUATION, int(DecisionEvaluationIntent.EVALUATED))] = self._noop
        reg[(ValueType.DECISION_EVALUATION, int(DecisionEvaluationIntent.FAILED))] = self._noop
        from zeebe_tpu.protocol.intent import CheckpointIntent

        reg[(ValueType.CHECKPOINT, int(CheckpointIntent.CREATED))] = self._checkpoint_created
        reg[(ValueType.CHECKPOINT, int(CheckpointIntent.IGNORED))] = self._noop
        from zeebe_tpu.protocol.intent import (
            ProcessInstanceMigrationIntent,
            ProcessInstanceModificationIntent,
            ResourceDeletionIntent,
        )

        reg[(ValueType.PROCESS_INSTANCE_MODIFICATION, int(ProcessInstanceModificationIntent.MODIFIED))] = self._noop
        reg[(ValueType.PROCESS_INSTANCE_MIGRATION, int(ProcessInstanceMigrationIntent.MIGRATED))] = self._migrated
        reg[(ValueType.RESOURCE_DELETION, int(ResourceDeletionIntent.DELETING))] = self._noop
        reg[(ValueType.RESOURCE_DELETION, int(ResourceDeletionIntent.DELETED))] = self._resource_deleted
        from zeebe_tpu.protocol.intent import UserTaskIntent

        reg[(ValueType.USER_TASK, int(UserTaskIntent.CREATING))] = self._noop
        reg[(ValueType.USER_TASK, int(UserTaskIntent.CREATED))] = self._user_task_created
        reg[(ValueType.USER_TASK, int(UserTaskIntent.COMPLETING))] = self._noop
        reg[(ValueType.USER_TASK, int(UserTaskIntent.COMPLETED))] = self._user_task_removed
        reg[(ValueType.USER_TASK, int(UserTaskIntent.CANCELING))] = self._noop
        reg[(ValueType.USER_TASK, int(UserTaskIntent.CANCELED))] = self._user_task_removed
        reg[(ValueType.USER_TASK, int(UserTaskIntent.ASSIGNING))] = self._noop
        reg[(ValueType.USER_TASK, int(UserTaskIntent.ASSIGNED))] = self._user_task_updated
        reg[(ValueType.USER_TASK, int(UserTaskIntent.UPDATED))] = self._user_task_updated

    def can_apply(self, record: Record) -> bool:
        return (record.value_type, int(record.intent)) in self._appliers

    def apply(self, record: Record) -> None:
        applier = self._appliers.get((record.value_type, int(record.intent)))
        if applier is None:
            raise KeyError(
                f"no event applier for {record.value_type.name} {record.intent.name}"
            )
        if record.key >= 0:
            self.state.observe_key(record.key)
        applier(record)

    # -- appliers ------------------------------------------------------------

    def _noop(self, record: Record) -> None:
        pass

    def _user_task_created(self, record: Record) -> None:
        self.state.user_tasks.create(record.key, record.value)

    def _user_task_updated(self, record: Record) -> None:
        self.state.user_tasks.update(record.key, record.value)

    def _user_task_removed(self, record: Record) -> None:
        self.state.user_tasks.remove(record.key)

    def _migrated(self, record: Record) -> None:
        from zeebe_tpu.engine.modification import apply_migrated

        apply_migrated(self.state, record)

    def _resource_deleted(self, record: Record) -> None:
        resource_key = record.value["resourceKey"]
        self.state.processes.delete(resource_key)
        self.state.decisions.delete_drg(resource_key)

    def _checkpoint_created(self, record: Record) -> None:
        self.state.checkpoints.put(
            record.value["checkpointId"], record.value["checkpointPosition"]
        )
        # cache hook: partitions keep a lock-free latest-checkpoint-id for
        # the cross-thread inter-partition send path; the applier is the one
        # place both leader processing AND follower replay pass through
        if self.on_checkpoint_applied is not None:
            self.on_checkpoint_applied(record.value["checkpointId"])

    def _drg_created(self, record: Record) -> None:
        self.state.decisions.put_drg(record.key, record.value)

    def _decision_created(self, record: Record) -> None:
        self.state.decisions.put_decision(record.key, record.value)

    # command distribution (reference: state/appliers/CommandDistribution*Applier)

    def _distribution_started(self, record: Record) -> None:
        self.state.distribution.start(record.key, record.value)

    def _distribution_distributing(self, record: Record) -> None:
        self.state.distribution.add_pending(record.key, record.value["partitionId"])

    def _distribution_acknowledged(self, record: Record) -> None:
        if record.value.get("received"):
            # receiver-side marker: dedups retried distribution sends
            self.state.distribution.mark_received(
                record.key, record.value.get("receivedAt", 0)
            )
        else:
            self.state.distribution.remove_pending(record.key, record.value["partitionId"])

    def _distribution_finished(self, record: Record) -> None:
        self.state.distribution.finish(record.key)

    def _pi_batch_activated(self, record: Record) -> None:
        """Track chunked multi-instance activation progress on the body
        instance: completion of the body must wait for the final chunk.
        Monotonic: the index never rewinds and the total is pinned by the
        first chunk (guards against collection mutation between chunks)."""
        v = record.value
        body_key = v.get("batchElementInstanceKey", -1)
        body = self.state.element_instances.get(body_key)
        if body is None:
            return
        index = max(v.get("index", 0), body.get("miActivationIndex", 0))
        # the total only ever DECREASES after the pin (the processor lowers it
        # exactly once, when a shrunken collection terminates the chain) — a
        # later chunk can never re-raise it and re-block completion
        stored = body.get("miTotal")
        total = min(stored, v.get("count", 0)) if stored else v.get("count", 0)
        self.state.element_instances.update(
            body_key, miActivationIndex=index, miTotal=total,
        )

    def _form_created(self, record: Record) -> None:
        self.state.forms.put(record.value)

    def _form_deleted(self, record: Record) -> None:
        self.state.forms.delete(record.key)

    def _process_created(self, record: Record) -> None:
        v = record.value
        self.state.processes.put_process(
            key=v["processDefinitionKey"],
            bpmn_process_id=v["bpmnProcessId"],
            version=v["version"],
            resource_name=v["resourceName"],
            resource_xml=v["resource"],
            digest=v["checksum"],
            tenant=v.get("tenantId", DEFAULT_TENANT),
        )

    # element lifecycle

    def _element_activating(self, record: Record) -> None:
        v = record.value
        ei = self.state.element_instances
        ei.create(record.key, v, EI_ACTIVATING)
        # a child process of a call activity back-links itself so terminate can
        # reach it (reference: ElementInstance.calledChildInstanceKey)
        parent_ei = v.get("parentElementInstanceKey", -1)
        if parent_ei >= 0 and ei.get(parent_ei) is not None:
            ei.update(parent_ei, calledChildInstanceKey=record.key)
        scope_key = v.get("flowScopeKey", -1)
        if scope_key >= 0:
            ei.add_child(scope_key)
            # token accounting is derived from the process model, like the
            # reference's appliers (they consult ProcessState): a parallel
            # gateway join consumes one token per incoming flow; elements
            # activated via a flow consume one; elements activated directly
            # (start events, boundary events, scopes, multi-instance inner
            # instances) consume none.
            exe = self.state.processes.executable(v["processDefinitionKey"])
            element = exe.element(v["elementId"])
            is_mi_inner = (
                element.multi_instance is not None
                and v.get("bpmnElementType") != BpmnElementType.MULTI_INSTANCE_BODY.name
            )
            if is_mi_inner or v.get("directActivation"):
                # modification-activated: no token was in transit
                pass
            elif element.element_type == BpmnElementType.PARALLEL_GATEWAY:
                ei.consume_active_flows(scope_key, element.incoming_count)
                ei.decrement_taken_flows_for_join(scope_key, element.idx)
            elif element.element_type in (
                BpmnElementType.START_EVENT,
                BpmnElementType.BOUNDARY_EVENT,
                BpmnElementType.EVENT_SUB_PROCESS,
            ):
                pass
            elif element.element_type == BpmnElementType.INTERMEDIATE_CATCH_EVENT and all(
                exe.elements[f.source_idx].element_type == BpmnElementType.EVENT_BASED_GATEWAY
                for f in exe.flows
                if f.target_idx == element.idx
            ):
                # a catch event after an event-based gateway activates directly —
                # the flow gateway→event is never taken (BPMN spec), so there is
                # no in-transit token to consume
                pass
            else:
                ei.consume_active_flows(scope_key, min(1, element.incoming_count))

    def _element_activated(self, record: Record) -> None:
        self.state.element_instances.set_state(record.key, EI_ACTIVATED)

    def _element_completing(self, record: Record) -> None:
        self.state.element_instances.set_state(record.key, EI_COMPLETING)

    def _element_finished(self, record: Record, state: int) -> None:
        """Shared completed/terminated epilogue: stamp the terminal state,
        release the parent scope's child slot, drop the variable scope,
        remove the instance. One body on purpose — the two intents differed
        only in the terminal state constant and had started to drift."""
        v = record.value
        ei = self.state.element_instances
        ei.set_state(record.key, state)
        scope_key = v.get("flowScopeKey", -1)
        if scope_key >= 0:
            ei.remove_child(scope_key)
        self.state.variables.remove_scope(record.key)
        ei.remove(record.key)

    def _element_completed(self, record: Record) -> None:
        self._element_finished(record, EI_COMPLETED)

    def _element_terminating(self, record: Record) -> None:
        self.state.element_instances.set_state(record.key, EI_TERMINATING)

    def _element_terminated(self, record: Record) -> None:
        self._element_finished(record, EI_TERMINATED)

    def _sequence_flow_taken(self, record: Record) -> None:
        v = record.value
        ei = self.state.element_instances
        scope_key = v["flowScopeKey"]
        # a token is now in transit on this flow
        ei.add_active_flow(scope_key)
        # parallel-gateway joins count taken incoming flows
        exe = self.state.processes.executable(v["processDefinitionKey"])
        flow = exe.flow(v["elementId"])
        target = exe.elements[flow.target_idx]
        if target.element_type == BpmnElementType.PARALLEL_GATEWAY:
            ei.increment_taken_flow(scope_key, target.idx, flow.idx)

    # jobs

    def _job_created(self, record: Record) -> None:
        self.state.jobs.create(record.key, record.value)
        element_key = record.value.get("elementInstanceKey", -1)
        if element_key >= 0 and self.state.element_instances.get(element_key) is not None:
            self.state.element_instances.update(element_key, jobKey=record.key)

    def _job_completed(self, record: Record) -> None:
        self.state.jobs.complete(record.key)
        element_key = record.value.get("elementInstanceKey", -1)
        if element_key >= 0 and self.state.element_instances.get(element_key) is not None:
            self.state.element_instances.update(element_key, jobKey=-1)

    def _job_failed(self, record: Record) -> None:
        self.state.jobs.fail(
            record.key, record.value["retries"], record.value.get("retryBackoff", -1)
        )

    def _job_timed_out(self, record: Record) -> None:
        self.state.jobs.timeout(record.key)

    def _job_retries_updated(self, record: Record) -> None:
        self.state.jobs.update_retries(record.key, record.value["retries"])

    def _job_canceled(self, record: Record) -> None:
        self.state.jobs.cancel(record.key)

    def _job_recurred(self, record: Record) -> None:
        self.state.jobs.recur_after_backoff(record.key, record.value.get("recurAt", -1))

    def _job_yielded(self, record: Record) -> None:
        # pushed to a dead client stream: activated → activatable again
        # (reference: JobYieldedApplier)
        self.state.jobs.timeout(record.key)

    def _job_timeout_updated(self, record: Record) -> None:
        self.state.jobs.update_deadline(record.key, record.value["deadline"])

    def _job_batch_activated(self, record: Record) -> None:
        v = record.value
        deadline = v["deadline"]
        for job_key in v["jobKeys"]:
            self.state.jobs.activate(job_key, v.get("worker", ""), deadline)

    # variables

    def _variable_set(self, record: Record) -> None:
        v = record.value
        self.state.variables.set_variable(v["scopeKey"], v["name"], v["value"])

    # incidents

    def _incident_created(self, record: Record) -> None:
        self.state.incidents.create(record.key, record.value)

    def _incident_resolved(self, record: Record) -> None:
        self.state.incidents.resolve(record.key)

    # timers

    def _timer_created(self, record: Record) -> None:
        self.state.timers.create(record.key, record.value)

    def _timer_removed(self, record: Record) -> None:
        self.state.timers.remove(record.key)

    # messages

    def _message_published(self, record: Record) -> None:
        self.state.messages.put(record.key, record.value, record.value.get("deadline", -1))

    def _message_removed(self, record: Record) -> None:
        self.state.messages.remove(record.key, record.value.get("deadline", -1))

    def _message_batch_expired(self, record: Record) -> None:
        """One MESSAGE_BATCH EXPIRED record removes every named message —
        the O(batches) expiry path (reference: MessageBatchExpireProcessor)."""
        for key in record.value.get("messageKeys", []):
            msg = self.state.messages.get(key)
            if msg is not None:
                self.state.messages.remove(key, msg.get("deadline", -1))

    def _msg_sub_created(self, record: Record) -> None:
        self.state.message_subscriptions.put(record.key, record.value)

    def _msg_sub_correlating(self, record: Record) -> None:
        v = record.value
        self.state.messages.mark_correlated(v["messageKey"], v.get("processInstanceKey", -1))

    def _msg_sub_correlated(self, record: Record) -> None:
        # catch-event subscriptions close on correlation
        if record.value.get("interrupting", True):
            self.state.message_subscriptions.remove(record.key)

    def _msg_sub_deleted(self, record: Record) -> None:
        self.state.message_subscriptions.remove(record.key)

    def _pms_creating(self, record: Record) -> None:
        v = record.value
        self.state.process_message_subscriptions.put(
            v["elementInstanceKey"], v["messageName"], v
        )

    def _pms_created(self, record: Record) -> None:
        v = record.value
        self.state.process_message_subscriptions.put(
            v["elementInstanceKey"], v["messageName"], v
        )

    def _pms_correlated(self, record: Record) -> None:
        v = record.value
        if v.get("interrupting", True):
            self.state.process_message_subscriptions.remove(
                v["elementInstanceKey"], v["messageName"]
            )

    def _pms_deleted(self, record: Record) -> None:
        v = record.value
        self.state.process_message_subscriptions.remove(
            v["elementInstanceKey"], v["messageName"]
        )

    def _msg_start_created(self, record: Record) -> None:
        v = record.value
        self.state.message_start_subscriptions.put(
            v["messageName"], v["processDefinitionKey"], v
        )

    def _msg_start_deleted(self, record: Record) -> None:
        self.state.message_start_subscriptions.remove_for_process(
            record.value["processDefinitionKey"]
        )

    # signals

    def _signal_sub_key(self, v: dict) -> int:
        element_key = v.get("catchEventInstanceKey", -1)
        return element_key if element_key >= 0 else v.get("processDefinitionKey", -1)

    def _signal_sub_created(self, record: Record) -> None:
        v = record.value
        self.state.signal_subscriptions.put(v["signalName"], self._signal_sub_key(v), v)

    def _signal_sub_deleted(self, record: Record) -> None:
        v = record.value
        self.state.signal_subscriptions.remove(v["signalName"], self._signal_sub_key(v))

    def _job_error_thrown(self, record: Record) -> None:
        self.state.jobs.error_thrown(record.key)
