"""DbMigrator: one-shot state migrations run at partition transition.

Reference: engine/src/main/java/io/camunda/zeebe/engine/state/migration/
DbMigratorImpl.java:29 — an ordered list of ``MigrationTask``s runs when a
partition transitions (before the stream processor opens); each task executes
at most once per partition, recorded in the MIGRATIONS_STATE column family
(the reference's MigrationsState). The shipped tasks mirror the reference's
to_8_3/ multi-tenancy backfills: they rewrite pre-tenancy key shapes from
older snapshots into the tenant-aware shapes the current state code reads.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable

from zeebe_tpu.protocol import DEFAULT_TENANT
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF
from zeebe_tpu.state.db import decode_key, encode_key

logger = logging.getLogger("zeebe_tpu.engine.migration")


@dataclasses.dataclass(frozen=True)
class MigrationTask:
    """One idempotent migration; ``run`` returns how many entries changed."""

    identifier: str
    run: Callable[[ZbDb], int]


def _retenant_index(db: ZbDb, code: CF, old_arity: int) -> int:
    """Rewrite pre-tenancy keys (old_arity parts) to tenant-prefixed keys
    ((DEFAULT_TENANT, *old_parts)); newer keys are left untouched."""
    txn = db.require_transaction()
    cf = db.column_family(code)
    moves: list[tuple[bytes, bytes, object]] = []
    for enc_key, value in cf.items():
        _, parts = decode_key(enc_key)
        if len(parts) == old_arity and not (
            parts and isinstance(parts[0], str) and parts[0] == DEFAULT_TENANT
        ):
            moves.append(
                (enc_key, encode_key(code, (DEFAULT_TENANT, *parts)), value)
            )
    for old, new, value in moves:
        txn.delete(old)
        txn.put(new, value)
    return len(moves)


def _migrate_process_version_tenancy(db: ZbDb) -> int:
    """Process id/version indexes gained a leading tenant component; backfill
    entries from pre-tenancy snapshots under the default tenant (reference:
    to_8_3 ProcessDefinitionVersionMigration)."""
    changed = _retenant_index(db, CF.PROCESS_CACHE_BY_ID_AND_VERSION, 2)
    changed += _retenant_index(db, CF.PROCESS_VERSION, 1)
    changed += _retenant_index(db, CF.PROCESS_CACHE_DIGEST_BY_ID, 1)
    return changed


def _migrate_message_id_tenancy(db: ZbDb) -> int:
    """Message-id dedup keys gained a trailing tenant component (reference:
    to_8_3 MessageStateMigration)."""
    txn = db.require_transaction()
    cf = db.column_family(CF.MESSAGE_IDS)
    moves = []
    for enc_key, value in cf.items():
        code, parts = decode_key(enc_key)
        if len(parts) == 3:
            moves.append(
                (enc_key, encode_key(code, (*parts, DEFAULT_TENANT)), value)
            )
    for old, new, value in moves:
        txn.delete(old)
        txn.put(new, value)
    return len(moves)


def _migrate_job_activatable_tenancy(db: ZbDb) -> int:
    """Activatable-job index keys gained a middle tenant component:
    (type, key) → (type, tenant, key)."""
    txn = db.require_transaction()
    cf = db.column_family(CF.JOB_ACTIVATABLE)
    moves = []
    for enc_key, value in cf.items():
        code, parts = decode_key(enc_key)
        if len(parts) == 2:
            moves.append(
                (enc_key,
                 encode_key(code, (parts[0], DEFAULT_TENANT, parts[1])), value)
            )
    for old, new, value in moves:
        txn.delete(old)
        txn.put(new, value)
    return len(moves)


def _migrate_dmn_latest_tenancy(db: ZbDb) -> int:
    """DMN latest-by-id indexes gained a leading tenant component."""
    changed = _retenant_index(db, CF.DMN_LATEST_DECISION_BY_ID, 1)
    changed += _retenant_index(db, CF.DMN_LATEST_DRG_BY_ID, 1)
    return changed


MIGRATION_TASKS: list[MigrationTask] = [
    MigrationTask("process-version-tenancy", _migrate_process_version_tenancy),
    MigrationTask("message-id-tenancy", _migrate_message_id_tenancy),
    MigrationTask("job-activatable-tenancy", _migrate_job_activatable_tenancy),
    MigrationTask("dmn-latest-tenancy", _migrate_dmn_latest_tenancy),
]


class DbMigrator:
    """Runs the migration task list once per partition lifetime."""

    def __init__(self, db: ZbDb,
                 tasks: list[MigrationTask] | None = None) -> None:
        self.db = db
        self.tasks = tasks if tasks is not None else MIGRATION_TASKS

    def run_migrations(self) -> list[str]:
        """Execute not-yet-run tasks in order; returns their identifiers.
        All tasks commit in one transaction: a crash mid-migration reruns
        them wholesale on the next transition (each task is idempotent)."""
        executed: list[str] = []
        with self.db.transaction():
            markers = self.db.column_family(CF.MIGRATIONS_STATE)
            for task in self.tasks:
                if markers.get((task.identifier,)) is not None:
                    continue
                changed = task.run(self.db)
                markers.put((task.identifier,), {"entriesChanged": changed})
                executed.append(task.identifier)
                if changed:
                    logger.info("migration %s rewrote %d entries",
                                task.identifier, changed)
        return executed
