"""BPMN element lifecycle processing — the core state machine.

Reference: engine/src/main/java/io/camunda/zeebe/engine/processing/bpmn/
BpmnStreamProcessor.java:36 (processRecord :75 → guard → processEvent :133
switching on ACTIVATE/COMPLETE/TERMINATE_ELEMENT), ProcessInstanceLifecycle
(legal transitions), behavior/BpmnStateTransitionBehavior (lifecycle event
chains + sequence-flow taking), and the per-type element processors under
bpmn/{container,task,event,gateway}/.

Lifecycle chains produced by one command (identical in shape to the
reference's event streams):

  ACTIVATE_ELEMENT →  ELEMENT_ACTIVATING, ELEMENT_ACTIVATED
                      [wait states stop here: tasks with jobs, catch events]
                      [pass-through elements continue:]
                      ELEMENT_COMPLETING, ELEMENT_COMPLETED,
                      SEQUENCE_FLOW_TAKEN*, follow-up ACTIVATE_ELEMENT cmds
  COMPLETE_ELEMENT →  ELEMENT_COMPLETING, ELEMENT_COMPLETED, flows, …
  TERMINATE_ELEMENT → ELEMENT_TERMINATING, [terminate children/cancel job],
                      ELEMENT_TERMINATED, scope follow-ups

Scope completion: when the last token in a scope disappears (no active
children, no tokens in transit), the scope's COMPLETE_ELEMENT command is
written — process completion bubbles up from end events exactly as in the
reference's afterExecutionPathCompleted.
"""

from __future__ import annotations

from typing import Any

from zeebe_tpu.engine.engine_state import (
    EI_ACTIVATED,
    EI_ACTIVATING,
    EI_COMPLETING,
    EI_TERMINATING,
    EngineState,
)
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.feel import FeelEvalError
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.models.bpmn import ExecutableElement, ExecutableProcess
from zeebe_tpu.protocol import DEFAULT_TENANT, RejectionType, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType, ErrorType
from zeebe_tpu.protocol.intent import (
    EscalationIntent,
    IncidentIntent,
    JobIntent,
    UserTaskIntent,
    ProcessInstanceIntent,
    ProcessInstanceResultIntent,
    SignalIntent,
    SignalSubscriptionIntent,
    TimerIntent,
    VariableIntent,
)

PI = ProcessInstanceIntent

# fan-outs/fan-ins larger than this ride PROCESS_INSTANCE_BATCH chunk
# commands instead of one unbounded record batch (reference:
# ProcessInstanceBatch ACTIVATE/TERMINATE, EngineConfiguration batch limits)
PI_BATCH_CHUNK = 100


class BpmnProcessor:
    """Handles PROCESS_INSTANCE ACTIVATE/COMPLETE/TERMINATE_ELEMENT commands."""

    def __init__(self, state: EngineState, clock_millis, sender=None, partition_count: int = 1) -> None:
        self.state = state
        self.clock_millis = clock_millis
        self.sender = sender  # InterPartitionCommandSender (set via Engine.wire)
        self.partition_count = partition_count
        from zeebe_tpu.engine.decision import BpmnDecisionBehavior

        self.decision_behavior = BpmnDecisionBehavior(
            state, self._raise_incident, self._write_variable
        )

    # ------------------------------------------------------------------ entry

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        intent = cmd.record.intent
        value = dict(cmd.record.value)
        key = cmd.record.key

        if intent == PI.ACTIVATE_ELEMENT:
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._activate(key, value, exe, element, writers)
        elif intent == PI.COMPLETE_ELEMENT:
            instance = self.state.element_instances.get(key)
            # COMPLETING is legal here: incident resolution retries a stalled
            # completing transition (condition/output-mapping failures)
            if instance is None or instance["state"] not in (EI_ACTIVATED, EI_ACTIVATING, EI_COMPLETING):
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_STATE,
                    f"expected element instance {key} to be activated, but it is "
                    + ("not present" if instance is None else "not in an activatable state"),
                )
                return
            value = instance["value"]
            # an event-based gateway's COMPLETE command names the catch event
            # that fired (reference: EventBasedGatewayProcessor.onComplete reads
            # the event trigger); pass it through to completion
            if "triggeredElementId" in cmd.record.value:
                value = {**value, "triggeredElementId": cmd.record.value["triggeredElementId"]}
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._complete(key, value, exe, element, writers)
        elif intent == PI.TERMINATE_ELEMENT:
            instance = self.state.element_instances.get(key)
            if instance is None:
                writers.respond_rejection(
                    cmd, RejectionType.NOT_FOUND, f"no element instance {key}"
                )
                return
            value = instance["value"]
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._terminate(key, value, exe, element, writers)
        else:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_ARGUMENT, f"unsupported intent {intent.name}"
            )

    def _executable(self, value: dict) -> ExecutableProcess:
        exe = self.state.processes.executable(value["processDefinitionKey"])
        if exe is None:
            raise KeyError(f"unknown process definition {value['processDefinitionKey']}")
        return exe

    # -------------------------------------------------------------- activation

    def _activate(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        start_override = value.get("startElementId")
        mi_item = value.get("miItem")
        has_mi_item = "miItem" in value
        # set when an event-based gateway's triggered catch event is activated
        # directly (no subscription to open; complete immediately)
        event_triggered = bool(value.get("eventTriggered"))
        is_mi_body = (
            element.multi_instance is not None
            and value.get("bpmnElementType") == BpmnElementType.MULTI_INSTANCE_BODY.name
        )
        is_mi_inner = element.multi_instance is not None and not is_mi_body
        value = _pi_value(value, element)
        # an instance already in ACTIVATING is an incident-resolution retry —
        # don't re-append the lifecycle event (the applier would double-count
        # tokens/children) and don't re-open boundary subscriptions
        instance = self.state.element_instances.get(key)
        retrying = instance is not None and instance["state"] == EI_ACTIVATING
        if not retrying:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATING, value)

        if is_mi_body:
            # boundary events attach to the multi-instance body, not the inner
            # instances (reference: MultiInstanceBodyProcessor)
            if element.boundary_idxs and not retrying:
                self._open_boundary_subscriptions(key, value, exe, element, writers)
            self._activate_mi_body(key, value, exe, element, writers)
            return

        if is_mi_inner and not retrying:
            # inputElement local variable precedes input mappings so they can
            # reference it (reference: MultiInstanceBodyProcessor child setup);
            # a null item still creates the variable with value null
            mi = element.multi_instance
            if mi.input_element and has_mi_item:
                self._write_variable(writers, key, value, mi.input_element, mi_item)

        # input mappings create a local variable scope on the element instance
        if element.inputs and not self._apply_input_mappings(
                key, value, element, writers,
                context_key=key if is_mi_inner else value.get("flowScopeKey", -1)):
            return

        # boundary-event subscriptions attach when the host activity activates
        if element.boundary_idxs and not is_mi_inner and not retrying:
            self._open_boundary_subscriptions(key, value, exe, element, writers)

        et = element.element_type
        if et in (BpmnElementType.PROCESS, BpmnElementType.SUB_PROCESS,
                  BpmnElementType.EVENT_SUB_PROCESS):
            # event sub-process start subscriptions open on the scope instance;
            # pre-validated so a failure leaves the scope ACTIVATING (retryable).
            # No `retrying` guard: any earlier failure happened before a single
            # subscription event was written (pre-validation is all-or-nothing
            # and ACTIVATED follows immediately), so a retry must re-open
            if not self._open_scope_event_subscriptions(key, value, exe, element, writers):
                return
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            if et == BpmnElementType.PROCESS:
                # message/timer/signal start events carry an explicit start element
                start_idx = exe.by_id[start_override] if start_override else exe.none_start_of(0)
            else:
                start_idx = element.child_start_idx
            start = exe.elements[start_idx]
            self._write_activate(writers, exe, start, scope_key=key, value=value)
        elif et == BpmnElementType.START_EVENT:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)
        elif et == BpmnElementType.USER_TASK and element.native_user_task:
            # native user task: lifecycle records instead of a job
            # (reference: zeebe:userTask → UserTaskProcessors)
            form_key = -1
            if element.form_id is not None:
                form_key = self._resolve_form(key, value, element, writers)
                if form_key is None:
                    return  # FORM_NOT_FOUND incident raised; stays ACTIVATING
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            task_key = self.state.next_key()
            task_value = {
                "userTaskKey": task_key,
                "assignee": element.user_task_assignee or "",
                "candidateGroups": element.user_task_candidate_groups or "",
                "candidateUsers": "",
                "dueDate": "",
                "followUpDate": "",
                "elementId": element.id,
                "elementInstanceKey": key,
                "processInstanceKey": value["processInstanceKey"],
                "processDefinitionKey": value["processDefinitionKey"],
                "bpmnProcessId": value["bpmnProcessId"],
                **({"formKey": form_key} if form_key >= 0 else {}),
            }
            writers.append_event(task_key, ValueType.USER_TASK,
                                 UserTaskIntent.CREATING, task_value)
            writers.append_event(task_key, ValueType.USER_TASK,
                                 UserTaskIntent.CREATED, task_value)
            # wait state: completion comes from the USER_TASK COMPLETE command
        elif (et == BpmnElementType.BUSINESS_RULE_TASK
              and element.called_decision_id is not None):
            # zeebe:calledDecision: evaluate BEFORE transitioning to ACTIVATED —
            # an evaluation incident must leave the element ACTIVATING so
            # incident resolution can retry the activation (reference:
            # BusinessRuleTaskProcessor evaluates in onActivate)
            if self.decision_behavior.evaluate_called_decision(key, value, element, writers):
                writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
                self._complete(key, value, exe, element, writers)
        elif et in (BpmnElementType.SERVICE_TASK, BpmnElementType.SEND_TASK,
                    BpmnElementType.BUSINESS_RULE_TASK, BpmnElementType.SCRIPT_TASK,
                    BpmnElementType.USER_TASK) and element.job_type is not None:
            context = self.state.variables.collect(key)
            try:
                job_type = element.job_type.evaluate(context, self.clock_millis)
                retries = int(element.job_retries.evaluate(context, self.clock_millis))
            except (FeelEvalError, TypeError, ValueError) as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return
            headers = element.task_headers
            if element.form_id is not None:
                # linked form rides the job as the reference's formKey header
                # (Protocol.USER_TASK_FORM_KEY_HEADER_NAME)
                form_key = self._resolve_form(key, value, element, writers)
                if form_key is None:
                    return  # FORM_NOT_FOUND incident raised; stays ACTIVATING
                headers = {**headers, "io.camunda.zeebe:formKey": str(form_key)}
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            job_key = self.state.next_key()
            writers.append_event(
                job_key, ValueType.JOB, JobIntent.CREATED,
                {
                    "type": job_type,
                    "retries": retries,
                    "worker": "",
                    "deadline": -1,
                    "variables": {},
                    "customHeaders": headers,
                    "elementId": element.id,
                    "elementInstanceKey": key,
                    "processInstanceKey": value["processInstanceKey"],
                    "processDefinitionKey": value["processDefinitionKey"],
                    "processDefinitionVersion": value["version"],
                    "bpmnProcessId": value["bpmnProcessId"],
                    "errorMessage": "",
                    **({"tenantId": value["tenantId"]} if "tenantId" in value else {}),
                },
            )
            # wait state: completion comes from the job COMPLETE command
        elif et == BpmnElementType.SCRIPT_TASK and element.script_expression is not None:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            context = self.state.variables.collect(key)
            try:
                result = element.script_expression.evaluate(context, self.clock_millis)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return
            if element.script_result_variable:
                self._write_variable(
                    writers, value.get("flowScopeKey", -1), value,
                    element.script_result_variable, result,
                )
            self._complete(key, value, exe, element, writers)
        elif et in (BpmnElementType.INTERMEDIATE_CATCH_EVENT, BpmnElementType.RECEIVE_TASK):
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            if event_triggered:
                # the event already fired at the event-based gateway; pass through
                self._complete(key, value, exe, element, writers)
            elif element.event_type == BpmnEventType.LINK:
                # a catch link is a pass-through: entered by the matching
                # throw, it completes immediately and takes its outgoing
                # flows (reference: IntermediateCatchEventProcessor link)
                self._complete(key, value, exe, element, writers)
            elif element.event_type == BpmnEventType.TIMER or element.timer_duration is not None:
                self._create_timer(key, value, element, element, writers)
            elif element.message_name is not None:
                if not self._open_message_subscription(key, value, element, element, writers):
                    return
            elif element.signal_name is not None:
                self._open_signal_subscription(key, value, element, writers)
            # wait state: timer trigger / message correlation / signal completes it
        elif et == BpmnElementType.EVENT_BASED_GATEWAY:
            # subscribe to every succeeding catch event on the gateway's own
            # element instance; first trigger wins (reference:
            # EventBasedGatewayProcessor.onActivate subscribes BEFORE
            # transitioning to activated). Pre-validate every subscription
            # expression first so a failure writes no subscription events and
            # leaves the gateway ACTIVATING — incident resolution then retries
            # the whole activation without duplicating timers.
            targets = [exe.elements[exe.flows[fidx].target_idx] for fidx in element.outgoing]
            context = self.state.variables.collect(key)
            for target in targets:
                try:
                    if target.event_type == BpmnEventType.TIMER or target.timer_duration is not None:
                        self._eval_duration_millis(target.timer_duration, context)
                    elif target.message_name is not None:
                        ck = target.correlation_key.evaluate(context, self.clock_millis)
                        if ck is None:
                            raise FeelEvalError(
                                f"correlation key of '{target.id}' evaluated to null"
                            )
                except (FeelEvalError, TypeError, ValueError) as exc:
                    self._raise_incident(
                        writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc)
                    )
                    return
            for target in targets:
                if target.event_type == BpmnEventType.TIMER or target.timer_duration is not None:
                    self._create_timer(key, value, target, element, writers)
                elif target.message_name is not None:
                    self._open_message_subscription(key, value, target, element, writers)
                elif target.signal_name is not None:
                    self._open_signal_subscription(key, value, target, writers)
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            # wait state: the first triggered event completes the gateway
        elif et == BpmnElementType.CALL_ACTIVITY:
            self._activate_call_activity(key, value, exe, element, writers)
        elif et == BpmnElementType.END_EVENT and element.event_type == BpmnEventType.ERROR:
            # find the catcher BEFORE activating: an unhandled error leaves the
            # end event ACTIVATING with a retryable incident (reference:
            # EndEventProcessor ErrorEndEventBehavior)
            catcher = self._find_catcher(key, BpmnEventType.ERROR, element.error_code)
            if catcher is None:
                self._raise_incident(
                    writers, key, value, ErrorType.UNHANDLED_ERROR_EVENT,
                    f"Expected to throw an error event with the code "
                    f"'{element.error_code}', but it was not caught. No error events "
                    "are available in the scope.",
                )
                return
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._execute_catch(catcher, writers)
            # the end event never completes: the interruption terminates it
        elif et in (BpmnElementType.END_EVENT, BpmnElementType.INTERMEDIATE_THROW_EVENT) \
                and element.event_type == BpmnEventType.ESCALATION:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            if self._throw_escalation(key, value, element, writers):
                self._complete(key, value, exe, element, writers)
            # else: an interrupting catcher will terminate this throw event
        elif et in (BpmnElementType.END_EVENT, BpmnElementType.INTERMEDIATE_THROW_EVENT) \
                and element.event_type == BpmnEventType.SIGNAL:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            writers.append_command(
                self.state.next_key(), ValueType.SIGNAL, SignalIntent.BROADCAST,
                {"signalName": element.signal_name, "variables": {}},
            )
            self._complete(key, value, exe, element, writers)
        elif et in (BpmnElementType.MANUAL_TASK, BpmnElementType.TASK,
                    BpmnElementType.EXCLUSIVE_GATEWAY, BpmnElementType.PARALLEL_GATEWAY,
                    BpmnElementType.END_EVENT, BpmnElementType.INTERMEDIATE_THROW_EVENT):
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)
        else:
            # elements not yet implemented behave as pass-through tasks
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)

    # ---------------------------------------------------------- multi-instance

    def _eval_input_collection(self, body_key: int, value: dict, element: ExecutableElement,
                               writers: Writers) -> list | None:
        """Evaluate the input collection; incident (and None) if not a list."""
        context = self.state.variables.collect(body_key)
        mi = element.multi_instance
        try:
            items = mi.input_collection.evaluate(context, self.clock_millis)
        except FeelEvalError as exc:
            self._raise_incident(writers, body_key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
            return None
        if not isinstance(items, list):
            self._raise_incident(
                writers, body_key, value, ErrorType.EXTRACT_VALUE_ERROR,
                f"Expected the input collection of '{element.id}' to be an array, "
                f"but it evaluated to {items!r}",
            )
            return None
        return items

    def _activate_mi_body(self, key: int, value: dict, exe: ExecutableProcess,
                          element: ExecutableElement, writers: Writers) -> None:
        """Reference: processing/bpmn/container/MultiInstanceBodyProcessor —
        evaluate inputCollection, spawn inner instances (all for parallel, the
        first for sequential), seed the output collection."""
        mi = element.multi_instance
        items = self._eval_input_collection(key, value, element, writers)
        if items is None:
            return  # incident raised; body stays ACTIVATING
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)

        if mi.output_collection:
            self._write_variable(
                writers, key, value, mi.output_collection, [None] * len(items)
            )

        if not items:
            self._complete(key, value, exe, element, writers)
            return
        if mi.is_sequential:
            self._write_mi_inner_activate(writers, key, value, element, items[0], 1)
        elif len(items) > PI_BATCH_CHUNK:
            # large fan-out rides PROCESS_INSTANCE_BATCH chunking so no single
            # step writes an unbounded record batch (reference:
            # ActivateProcessInstanceBatchProcessor, SURVEY §5.7)
            from zeebe_tpu.protocol.intent import ProcessInstanceBatchIntent

            writers.append_command(
                self.state.next_key(), ValueType.PROCESS_INSTANCE_BATCH,
                ProcessInstanceBatchIntent.ACTIVATE,
                {
                    "processInstanceKey": value["processInstanceKey"],
                    "batchElementInstanceKey": key,
                    "index": 0,
                },
            )
        else:
            for i, item in enumerate(items):
                self._write_mi_inner_activate(writers, key, value, element, item, i + 1)

    def _write_mi_inner_activate(self, writers: Writers, body_key: int, body_value: dict,
                                 element: ExecutableElement, item, loop_counter: int) -> None:
        # the extra's bpmnElementType overrides _write_activate's
        # MULTI_INSTANCE_BODY wrapping: the inner instance IS the element
        exe = self.state.processes.executable(body_value["processDefinitionKey"])
        self._write_activate(
            writers, exe, element, body_key, body_value,
            extra={
                "bpmnElementType": element.element_type.name,
                "loopCounter": loop_counter,
                "miItem": item,
            },
        )

    def _on_mi_inner_completed(self, inner_key: int, inner_value: dict,
                               exe: ExecutableProcess, element: ExecutableElement,
                               writers: Writers) -> None:
        """Collect the output element, advance a sequential loop, and complete
        the body when the last inner instance finishes. Called after the inner
        ELEMENT_COMPLETED event was applied (instance and scope are gone)."""
        mi = element.multi_instance
        body_key = inner_value.get("flowScopeKey", -1)
        body = self.state.element_instances.get(body_key)
        if body is None or body["state"] not in (EI_ACTIVATED, EI_ACTIVATING):
            return  # body interrupted/terminated meanwhile
        body_value = body["value"]
        loop_counter = inner_value.get("loopCounter", 0)

        if mi.is_sequential:
            # re-read the collection per iteration, matching the reference
            # implementation (MultiInstanceBodyProcessor.onChildCompleted
            # re-reads the input collection; mutating it mid-loop is documented
            # as unsupported in both engines)
            items = self._eval_input_collection(body_key, body_value, element, writers)
            if items is None:
                return
            if loop_counter < len(items):
                self._write_mi_inner_activate(
                    writers, body_key, body_value, element, items[loop_counter],
                    loop_counter + 1,
                )
                return
        if body["activeChildren"] == 0:
            # chunked fan-out: more ACTIVATE batches pending → not done yet
            # (miActivationIndex/miTotal maintained by the PI-batch applier)
            mi_index = body.get("miActivationIndex")
            if mi_index is not None and mi_index < body.get("miTotal", 0):
                return
            writers.append_command(
                body_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT, {}
            )

    def _collect_mi_output(self, inner_key: int, inner_value: dict,
                           element: ExecutableElement, writers: Writers) -> bool:
        """Store the evaluated outputElement into the body's output collection
        at position loopCounter-1. Runs before the inner COMPLETED event so the
        inner variable scope is still live. Returns False (after raising an
        incident) when the output element cannot be evaluated — the inner
        instance stays COMPLETING and incident resolution retries."""
        mi = element.multi_instance
        if not mi.output_collection or mi.output_element is None:
            return True
        body_key = inner_value.get("flowScopeKey", -1)
        context = self.state.variables.collect(inner_key)
        try:
            item = mi.output_element.evaluate(context, self.clock_millis)
        except FeelEvalError as exc:
            self._raise_incident(
                writers, inner_key, inner_value, ErrorType.EXTRACT_VALUE_ERROR, str(exc)
            )
            return False
        collection = self.state.variables.get_local(body_key, mi.output_collection)
        if not isinstance(collection, list):
            return True
        idx = inner_value.get("loopCounter", 0) - 1
        if 0 <= idx < len(collection):
            updated = list(collection)
            updated[idx] = item
            body = self.state.element_instances.get(body_key)
            body_value = body["value"] if body else inner_value
            self._write_variable(writers, body_key, body_value, mi.output_collection, updated)
        return True

    # ----------------------------------------------------------- call activity

    def _activate_call_activity(self, key: int, value: dict, exe: ExecutableProcess,
                                element: ExecutableElement, writers: Writers) -> None:
        """Reference: processing/bpmn/container/CallActivityProcessor — resolve
        the called process, create a child instance with the parent back-links,
        and copy the call-activity scope variables into the child root."""
        # the called process resolves within the caller's tenant (reference:
        # CallActivityProcessor + TenantAuthorizationChecker)
        meta = self.state.processes.get_latest_by_id(
            element.called_process_id, value.get("tenantId", DEFAULT_TENANT))
        if meta is None:
            self._raise_incident(
                writers, key, value, ErrorType.CALLED_ELEMENT_ERROR,
                f"Expected process with BPMN process id '{element.called_process_id}' "
                "to be deployed, but not found",
            )
            return  # stays ACTIVATING; resolve retries
        called = self.state.processes.executable(meta["processDefinitionKey"])
        if called.root.child_start_idx < 0:
            self._raise_incident(
                writers, key, value, ErrorType.CALLED_ELEMENT_ERROR,
                f"Expected process '{element.called_process_id}' to have a none start "
                "event, but not found",
            )
            return
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)

        child_key = self.state.next_key()
        child_value = {
            "bpmnProcessId": meta["bpmnProcessId"],
            "version": meta["version"],
            "processDefinitionKey": meta["processDefinitionKey"],
            "processInstanceKey": child_key,
            "elementId": meta["bpmnProcessId"],
            "flowScopeKey": -1,
            "bpmnElementType": BpmnElementType.PROCESS.name,
            "bpmnEventType": BpmnEventType.UNSPECIFIED.name,
            "parentProcessInstanceKey": value.get("processInstanceKey", -1),
            "parentElementInstanceKey": key,
        }
        writers.append_command(
            child_key, ValueType.PROCESS_INSTANCE, PI.ACTIVATE_ELEMENT, child_value
        )
        # propagate all visible variables into the child root scope
        # (reference default: propagateAllParentVariables=true)
        for name, val in self.state.variables.collect(key).items():
            var_key = self.state.next_key()
            writers.append_event(
                var_key, ValueType.VARIABLE, VariableIntent.CREATED,
                {
                    "name": name, "value": val, "scopeKey": child_key,
                    "processInstanceKey": child_key,
                    "processDefinitionKey": meta["processDefinitionKey"],
                    "bpmnProcessId": meta["bpmnProcessId"],
                },
            )

    # ------------------------------------------------- event subscriptions

    def _eval_duration_millis(self, expr, context) -> int:
        millis, _ = self._eval_duration_millis_ex(expr, context)
        return millis

    def _eval_duration_millis_ex(self, expr, context) -> tuple[int, bool]:
        """→ (millis, calendar_dependent). A years-and-months span's
        millisecond delta depends on the current clock DATE (P1M from Jan 31
        is 28d, from Mar 31 is 30d), so it is NOT a pure function of the
        variable context even without now() — burst templates must decline."""
        from zeebe_tpu.feel.temporal import Duration, YearMonthDuration, temporal_add
        from zeebe_tpu.feel.temporal import FeelDateTime
        from zeebe_tpu.utils import parse_duration_millis

        raw = expr.evaluate(context, self.clock_millis)
        if isinstance(raw, Duration):
            return raw.millis, False
        if isinstance(raw, YearMonthDuration):
            now = FeelDateTime.from_epoch_millis(self.clock_millis())
            return temporal_add(now, raw).epoch_millis - now.epoch_millis, True
        if isinstance(raw, (int, float)):
            return int(raw), False
        return parse_duration_millis(str(raw)), False

    def _create_timer(self, host_key: int, value: dict, catching: ExecutableElement,
                      host: ExecutableElement, writers: Writers,
                      repetitions: int = 1, interval: int = -1) -> None:
        from zeebe_tpu.engine.burst_templates import (
            note_clock_poison,
            note_clock_value,
        )

        clock_free = True
        absolute_due: int | None = None
        try:
            if catching.timer_duration is not None:
                context = self.state.variables.collect(host_key)
                duration, calendar_dep = self._eval_duration_millis_ex(
                    catching.timer_duration, context
                )
                # a now()-referencing or calendar-anchored duration makes the
                # due date NOT clock + constant — template captures must decline
                clock_free = (not catching.timer_duration.references_clock()
                              and not calendar_dep)
            elif catching.timer_date is not None:
                # absolute due date (FEEL temporal or ISO string); the due
                # date is a pure function of the variable context, so it is
                # a sound template CONSTANT — unless the expression reads
                # the clock, which poisons the burst
                context = self.state.variables.collect(host_key)
                absolute_due = _eval_date_millis(
                    catching.timer_date, context, self.clock_millis
                )
                duration = 0
                clock_free = not catching.timer_date.references_clock()
            elif catching.timer_cycle is not None:
                # R<n>/<duration> cycle (non-interrupting repeating events);
                # the cycle itself is a FEEL expression (reference: timer
                # definitions are expressions, Timer.java transform)
                from zeebe_tpu.utils import parse_cycle

                context = self.state.variables.collect(host_key)
                cycle_text = catching.timer_cycle.evaluate(context, self.clock_millis)
                repetitions, duration = parse_cycle(str(cycle_text))
                interval = duration
                clock_free = not catching.timer_cycle.references_clock()
            else:
                raise ValueError(f"timer '{catching.id}' has no duration or cycle")
        except Exception as exc:  # noqa: BLE001 — bad timer → incident
            self._raise_incident(writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
            return
        timer_key = self.state.next_key()
        if absolute_due is not None:
            due_date = absolute_due
            if not clock_free:
                note_clock_poison()
        else:
            due_date = self.clock_millis() + duration
            if clock_free:
                note_clock_value(due_date, duration)
            else:
                note_clock_poison()
        writers.append_event(
            timer_key, ValueType.TIMER, TimerIntent.CREATED,
            {
                "elementId": host.id,
                "targetElementId": catching.id,
                "elementInstanceKey": host_key,
                "processInstanceKey": value.get("processInstanceKey", -1),
                "processDefinitionKey": value.get("processDefinitionKey", -1),
                "dueDate": due_date,
                "repetitions": repetitions,
                "interval": interval if interval > 0 else duration,
            },
        )

    def _open_message_subscription(self, host_key: int, value: dict,
                                   catching: ExecutableElement, host: ExecutableElement,
                                   writers: Writers) -> bool:
        from zeebe_tpu.parallel.partitioning import subscription_partition_id
        from zeebe_tpu.protocol import command as make_command
        from zeebe_tpu.protocol.intent import (
            MessageSubscriptionIntent,
            ProcessMessageSubscriptionIntent,
        )

        context = self.state.variables.collect(host_key)
        try:
            correlation_key = catching.correlation_key.evaluate(context, self.clock_millis)
        except FeelEvalError as exc:
            self._raise_incident(writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
            return False
        if correlation_key is None:
            self._raise_incident(
                writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR,
                f"correlation key of '{catching.id}' evaluated to null",
            )
            return False
        correlation_key = str(correlation_key)
        # the process partition allocates the message-side subscription key so
        # both sides can address it (open, correlate-ack, delete)
        msg_sub_key = self.state.next_key()
        sub_value = {
            "processInstanceKey": value.get("processInstanceKey", -1),
            "elementInstanceKey": host_key,
            "messageName": catching.message_name,
            "correlationKey": correlation_key,
            "targetElementId": catching.id,
            "interrupting": catching.interrupting,
            "bpmnProcessId": value.get("bpmnProcessId", ""),
            "subscriptionPartitionId": self.state.partition_id,
            "messageSubscriptionKey": msg_sub_key,
            **({"tenantId": value["tenantId"]} if "tenantId" in value else {}),
        }
        writers.append_event(
            host_key, ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
            ProcessMessageSubscriptionIntent.CREATING, sub_value,
        )
        message_partition = subscription_partition_id(correlation_key, self.partition_count)
        open_cmd = make_command(
            ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CREATE, sub_value,
            key=msg_sub_key,
        )
        sender = self.sender
        writers.after_commit(lambda: sender.send_command(message_partition, open_cmd))
        return True

    def _open_boundary_subscriptions(self, host_key: int, value: dict,
                                     exe: ExecutableProcess, host: ExecutableElement,
                                     writers: Writers) -> None:
        for bidx in host.boundary_idxs:
            boundary = exe.elements[bidx]
            if boundary.event_type == BpmnEventType.TIMER and (
                boundary.timer_duration is not None
                or boundary.timer_cycle is not None
                or boundary.timer_date is not None
            ):
                reps = 1 if boundary.interrupting else -1
                self._create_timer(host_key, value, boundary, host, writers, repetitions=reps)
            elif boundary.event_type == BpmnEventType.MESSAGE and boundary.message_name:
                self._open_message_subscription(host_key, value, boundary, host, writers)
            elif boundary.event_type == BpmnEventType.SIGNAL and boundary.signal_name:
                self._open_signal_subscription(host_key, value, boundary, writers)
            # error/escalation boundaries need no subscription: the throw walk
            # finds them via the model (reference: CatchEventAnalyzer)

    def _open_scope_event_subscriptions(self, key: int, value: dict,
                                        exe: ExecutableProcess, element: ExecutableElement,
                                        writers: Writers) -> bool:
        """Open timer/message/signal subscriptions for the scope's event
        sub-processes (reference: BpmnEventSubscriptionBehavior
        subscribeToEvents for ExecutableFlowElementContainer). Expressions are
        pre-validated; on failure an incident is raised and the scope stays
        ACTIVATING."""
        esps = exe.event_sub_processes_of(element.idx)
        if not esps:
            return True
        # the scope instance's own context — the same one the subscription
        # open evaluates in (input mappings have already written to `key`)
        context = self.state.variables.collect(key)
        problem = self.prevalidate_scope_event_subscriptions(
            (esp.child_start_idx for esp in esps), exe, context)
        if problem is not None:
            self._raise_incident(writers, key, value,
                                 ErrorType.EXTRACT_VALUE_ERROR, problem)
            return False
        for esp in esps:
            start = exe.elements[esp.child_start_idx]
            if start.event_type == BpmnEventType.TIMER and (
                start.timer_duration is not None
                or start.timer_cycle is not None
                or start.timer_date is not None
            ):
                reps = 1 if start.interrupting else -1
                self._create_timer(key, value, start, element, writers, repetitions=reps)
            elif start.event_type == BpmnEventType.MESSAGE and start.message_name:
                if not self._open_message_subscription(key, value, start, element, writers):
                    return False  # defensive: pre-validation should have caught it
            elif start.event_type == BpmnEventType.SIGNAL and start.signal_name:
                self._open_signal_subscription(key, value, start, writers)
        return True

    def prevalidate_scope_event_subscriptions(
        self, start_idxs, exe: ExecutableProcess, context: dict,
    ) -> str | None:
        """Evaluate the event-sub-process start expressions that opening the
        subscriptions will evaluate; the error message or None. ONE
        implementation shared by the sequential open (incident on failure)
        and kernel admission (decline on failure) so the two can never
        diverge on what counts as valid."""
        for sidx in start_idxs:
            start = exe.elements[sidx]
            try:
                if start.event_type == BpmnEventType.TIMER and start.timer_duration is not None:
                    self._eval_duration_millis(start.timer_duration, context)
                elif start.event_type == BpmnEventType.MESSAGE:
                    ck = start.correlation_key.evaluate(context, self.clock_millis)
                    if ck is None:
                        raise FeelEvalError(
                            f"correlation key of '{start.id}' evaluated to null"
                        )
            except (FeelEvalError, TypeError, ValueError) as exc:
                return str(exc)
        return None

    def _open_signal_subscription(self, host_key: int, value: dict,
                                  catching: ExecutableElement, writers: Writers) -> None:
        writers.append_event(
            self.state.next_key(), ValueType.SIGNAL_SUBSCRIPTION,
            SignalSubscriptionIntent.CREATED,
            {
                "signalName": catching.signal_name,
                "catchEventId": catching.id,
                "catchEventInstanceKey": host_key,
                "processDefinitionKey": value.get("processDefinitionKey", -1),
                "bpmnProcessId": value.get("bpmnProcessId", ""),
                "processInstanceKey": value.get("processInstanceKey", -1),
                "interrupting": catching.interrupting,
                **({"tenantId": value["tenantId"]} if "tenantId" in value else {}),
            },
        )

    def _close_subscriptions(self, key: int, value: dict, writers: Writers) -> None:
        """Cancel timers + message subscriptions attached to an element
        instance when it completes or terminates."""
        from zeebe_tpu.parallel.partitioning import subscription_partition_id
        from zeebe_tpu.protocol import command as make_command
        from zeebe_tpu.protocol.intent import (
            MessageSubscriptionIntent,
            ProcessMessageSubscriptionIntent,
            TimerIntent,
        )

        for timer_key, timer in self.state.timers.timers_for_element_instance(key):
            writers.append_event(timer_key, ValueType.TIMER, TimerIntent.CANCELED, timer)
        for sub in self.state.signal_subscriptions.subscriptions_of(key):
            writers.append_event(
                key, ValueType.SIGNAL_SUBSCRIPTION, SignalSubscriptionIntent.DELETED, sub
            )
        for sub in self.state.process_message_subscriptions.subscriptions_of(key):
            writers.append_event(
                key, ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
                ProcessMessageSubscriptionIntent.DELETED, sub,
            )
            message_partition = subscription_partition_id(
                sub["correlationKey"], self.partition_count
            )
            sub_key = sub.get("messageSubscriptionKey", -1)
            if sub_key >= 0:
                delete_cmd = make_command(
                    ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.DELETE,
                    dict(sub), key=sub_key,
                )
                sender = self.sender
                writers.after_commit(
                    lambda mp=message_partition, dc=delete_cmd: sender.send_command(mp, dc)
                )

    # ------------------------------------------------- event throwing/catching

    def _find_catcher(self, from_key: int, event_type: BpmnEventType, code: str | None):
        """Walk the scope hierarchy outward from the throwing element, crossing
        call-activity boundaries, to the closest matching catcher (reference:
        processing/common/CatchEventAnalyzer). Within one level an exact code
        match beats a catch-all (no code). Returns
        (kind, exe, catch_element, host_instance_key, host_value) or None —
        kind is "boundary" or "esp"."""
        ei = self.state.element_instances
        instance_key = from_key
        while instance_key >= 0:
            instance = ei.get(instance_key)
            if instance is None:
                return None
            ivalue = instance["value"]
            exe = self.state.processes.executable(ivalue["processDefinitionKey"])
            element = exe.element(ivalue["elementId"])

            def code_of(el):
                return el.error_code if event_type == BpmnEventType.ERROR else el.escalation_code

            def pick(candidates):
                exact = [c for c in candidates if code_of(c[-1]) == code]
                return exact[0] if exact else (candidates[0] if candidates else None)

            if element.element_type in (
                BpmnElementType.PROCESS, BpmnElementType.SUB_PROCESS,
                BpmnElementType.EVENT_SUB_PROCESS,
            ):
                esp_candidates = []
                for esp in exe.event_sub_processes_of(element.idx):
                    start = exe.elements[esp.child_start_idx]
                    if start.event_type == event_type and (
                        code_of(start) is None or code_of(start) == code
                    ):
                        esp_candidates.append((esp, start))
                chosen = pick([(e, s) for e, s in esp_candidates])
                if chosen:
                    return ("esp", exe, chosen[0], instance_key, ivalue)
            # boundary events on this element — but not for a multi-instance
            # inner instance (boundaries attach to the body, which is the next
            # level out)
            is_mi_inner = (
                element.multi_instance is not None
                and ivalue.get("bpmnElementType") != BpmnElementType.MULTI_INSTANCE_BODY.name
            )
            if not is_mi_inner:
                boundary_candidates = [
                    (exe.elements[bidx],)
                    for bidx in element.boundary_idxs
                    if exe.elements[bidx].event_type == event_type
                    and (
                        code_of(exe.elements[bidx]) is None
                        or code_of(exe.elements[bidx]) == code
                    )
                ]
                chosen = pick(boundary_candidates)
                if chosen:
                    return ("boundary", exe, chosen[0], instance_key, ivalue)
            fsk = ivalue.get("flowScopeKey", -1)
            instance_key = fsk if fsk >= 0 else ivalue.get("parentElementInstanceKey", -1)
        return None

    def _execute_catch(self, catcher, writers: Writers) -> None:
        """Activate the catcher and apply its interruption semantics."""
        kind, exe, catch_element, host_key, host_value = catcher
        if kind == "boundary":
            boundary_value = {
                "bpmnProcessId": host_value["bpmnProcessId"],
                "version": host_value["version"],
                "processDefinitionKey": host_value["processDefinitionKey"],
                "processInstanceKey": host_value["processInstanceKey"],
                "elementId": catch_element.id,
                "flowScopeKey": host_value.get("flowScopeKey", -1),
                "bpmnElementType": BpmnElementType.BOUNDARY_EVENT.name,
                "bpmnEventType": catch_element.event_type.name,
            }
            writers.append_command(
                self.state.next_key(), ValueType.PROCESS_INSTANCE,
                PI.ACTIVATE_ELEMENT, boundary_value,
            )
            if catch_element.interrupting:
                writers.append_command(
                    host_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
                )
        else:  # event sub-process inside scope host_key
            start = exe.elements[catch_element.child_start_idx]
            esp_value = {
                "bpmnProcessId": host_value["bpmnProcessId"],
                "version": host_value["version"],
                "processDefinitionKey": host_value["processDefinitionKey"],
                "processInstanceKey": host_value["processInstanceKey"],
                "elementId": catch_element.id,
                "flowScopeKey": host_key,
                "bpmnElementType": BpmnElementType.EVENT_SUB_PROCESS.name,
                "bpmnEventType": start.event_type.name,
            }
            writers.append_command(
                self.state.next_key(), ValueType.PROCESS_INSTANCE,
                PI.ACTIVATE_ELEMENT, esp_value,
            )
            if catch_element.interrupting:
                # the interrupted scope accepts no further event triggers and
                # every sibling of the event sub-process terminates
                self._close_subscriptions(host_key, host_value, writers)
                for child_key in self.state.element_instances.children_keys(host_key):
                    writers.append_command(
                        child_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
                    )

    def throw_error_from(self, element_key: int, error_code: str, writers: Writers) -> bool:
        """Route a thrown BPMN error (job THROW_ERROR or error end event) to
        the closest catcher. Returns False when unhandled."""
        catcher = self._find_catcher(element_key, BpmnEventType.ERROR, error_code)
        if catcher is None:
            return False
        self._execute_catch(catcher, writers)
        return True

    def _throw_escalation(self, key: int, value: dict, element: ExecutableElement,
                          writers: Writers) -> bool:
        """Throw an escalation; returns True when the throwing element can
        complete (uncaught, or caught non-interrupting — reference:
        BpmnEventPublicationBehavior.throwEscalationEvent)."""
        code = element.escalation_code
        catcher = self._find_catcher(key, BpmnEventType.ESCALATION, code)
        esc_value = {
            "escalationCode": code or "",
            "throwElementId": element.id,
            "catchElementId": catcher[2].id if catcher else "",
            "processInstanceKey": value.get("processInstanceKey", -1),
            "processDefinitionKey": value.get("processDefinitionKey", -1),
            "bpmnProcessId": value.get("bpmnProcessId", ""),
        }
        writers.append_event(
            self.state.next_key(), ValueType.ESCALATION,
            EscalationIntent.ESCALATED if catcher else EscalationIntent.NOT_ESCALATED,
            esc_value,
        )
        if catcher is None:
            return True  # uncaught escalations are not errors; continue
        self._execute_catch(catcher, writers)
        return not catcher[2].interrupting

    def route_trigger(self, host_key: int, target_element_id: str, writers: Writers) -> bool:
        """Route a fired event subscription (timer, message, signal) hosted on
        ``host_key`` toward its target: the waiting catch element itself, an
        event-based gateway, a boundary event, or an event sub-process start.
        Returns False when the host instance is gone."""
        instance = self.state.element_instances.get(host_key)
        if instance is None:
            return False
        pi_value = instance["value"]
        exe = self.state.processes.executable(pi_value["processDefinitionKey"])
        host_element = exe.element(pi_value["elementId"])
        if target_element_id == pi_value["elementId"]:
            writers.append_command(
                host_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT, {}
            )
            return True
        if host_element.element_type == BpmnElementType.EVENT_BASED_GATEWAY:
            writers.append_command(
                host_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT,
                {"triggeredElementId": target_element_id},
            )
            return True
        target = exe.element(target_element_id)
        if (
            target.element_type == BpmnElementType.START_EVENT
            and target.parent_idx >= 0
            and exe.elements[target.parent_idx].element_type == BpmnElementType.EVENT_SUB_PROCESS
        ):
            esp = exe.elements[target.parent_idx]
            self._execute_catch(("esp", exe, esp, host_key, pi_value), writers)
            return True
        # boundary event on the host activity
        self._execute_catch(("boundary", exe, target, host_key, pi_value), writers)
        return True

    def _apply_input_mappings(self, key: int, value: dict,
                              element, writers: Writers,
                              context_key: int) -> bool:
        """Evaluate zeebe:input mappings against the given scope context and
        write them as locals on the element instance. False = IO_MAPPING_ERROR
        incident raised (element stays in its current state). Shared by the
        sequential activate path and the kernel materializer (byte parity by
        construction)."""
        context = self.state.variables.collect(context_key)
        try:
            for expr, target in element.inputs:
                result = expr.evaluate(context, self.clock_millis)
                self._write_variable(writers, key, value, target, result)
        except FeelEvalError as exc:
            self._raise_incident(writers, key, value, ErrorType.IO_MAPPING_ERROR, str(exc))
            return False
        return True

    def _apply_output_mappings(self, key: int, value: dict,
                               element, writers: Writers) -> bool:
        """Evaluate zeebe:output mappings against the element scope and write
        the targets to the flow scope. False = IO_MAPPING_ERROR incident
        raised (element stays COMPLETING). Shared with the kernel
        materializer."""
        context = self.state.variables.collect(key)
        try:
            for expr, target in element.outputs:
                result = expr.evaluate(context, self.clock_millis)
                self._write_variable(
                    writers, value.get("flowScopeKey", -1), value, target, result
                )
        except FeelEvalError as exc:
            self._raise_incident(writers, key, value, ErrorType.IO_MAPPING_ERROR, str(exc))
            return False
        return True

    # -------------------------------------------------------------- completion

    def _complete(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        is_mi_body = (
            element.multi_instance is not None
            and value.get("bpmnElementType") == BpmnElementType.MULTI_INSTANCE_BODY.name
        )
        is_mi_inner = element.multi_instance is not None and not is_mi_body
        triggered_element_id = value.get("triggeredElementId")
        value = _pi_value(value, element)
        instance = self.state.element_instances.get(key)
        if instance is None or instance["state"] != EI_COMPLETING:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETING, value)
        # else: retrying a stalled completing transition after incident resolution

        # output mappings evaluate against the element scope, write to parent.
        # With multi-instance they apply on the body (which sees the output
        # collection), not on each inner instance (reference docs).
        if element.outputs and not is_mi_inner:
            if not self._apply_output_mappings(key, value, element, writers):
                return

        # boundary/catch subscriptions close when the element leaves ACTIVATED
        self._close_subscriptions(key, value, writers)

        if is_mi_inner:
            if not self._collect_mi_output(key, value, element, writers):
                return  # incident raised; stays COMPLETING, resolve retries
            # a sequential loop re-reads the input collection to find the next
            # item; validate it NOW, while this inner is still COMPLETING, so a
            # bad collection raises a retryable incident instead of stalling
            # the ACTIVATED body after COMPLETED is written
            mi = element.multi_instance
            if mi.is_sequential:
                body_key = value.get("flowScopeKey", -1)
                body = self.state.element_instances.get(body_key)
                if body is not None and body["state"] in (EI_ACTIVATED, EI_ACTIVATING):
                    context = self.state.variables.collect(body_key)
                    try:
                        items = mi.input_collection.evaluate(context, self.clock_millis)
                    except FeelEvalError as exc:
                        self._raise_incident(
                            writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc)
                        )
                        return
                    if not isinstance(items, list):
                        self._raise_incident(
                            writers, key, value, ErrorType.EXTRACT_VALUE_ERROR,
                            f"Expected the input collection of '{element.id}' to be an "
                            f"array, but it evaluated to {items!r}",
                        )
                        return
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            self._on_mi_inner_completed(key, value, exe, element, writers)
            return

        if is_mi_body and element.multi_instance.output_collection:
            # propagate the collected output to the body's outer scope before
            # the body scope disappears with ELEMENT_COMPLETED
            collection = self.state.variables.get_local(
                key, element.multi_instance.output_collection
            )
            if collection is not None:
                self._write_variable(
                    writers, value.get("flowScopeKey", -1), value,
                    element.multi_instance.output_collection, collection,
                )

        # child process locals must be captured before COMPLETED removes the
        # scope — a call activity's output mappings read them (reference:
        # CallActivityProcessor.onChildCompleted)
        child_locals: dict | None = None
        if element.element_type == BpmnElementType.PROCESS:
            child_locals = self.state.variables.locals_of(key)

        if element.element_type == BpmnElementType.EXCLUSIVE_GATEWAY and (
            len(element.outgoing) > 1
            or any(exe.flows[f].condition is not None for f in element.outgoing)
        ):
            taken = self._choose_exclusive_flow(key, value, exe, element, writers)
            if taken is None:
                return  # incident raised; stays in COMPLETING
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            self._take_flow(writers, exe, taken, value)
        elif element.element_type == BpmnElementType.INCLUSIVE_GATEWAY and (
            len(element.outgoing) > 1
            or any(exe.flows[f].condition is not None for f in element.outgoing)
        ):
            # fork: take EVERY flow whose condition holds; default only when
            # none hold (reference: InclusiveGatewayProcessor.findSequenceFlowsToTake)
            taken_flows = self._choose_inclusive_flows(key, value, exe, element, writers)
            if taken_flows is None:
                return  # incident raised; stays in COMPLETING
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            for flow in taken_flows:
                self._take_flow(writers, exe, flow, value)
        elif (
            element.element_type == BpmnElementType.INTERMEDIATE_THROW_EVENT
            and element.event_type == BpmnEventType.LINK
            and element.link_target_idx >= 0
        ):
            # link throw: the token jumps to the same-scope catch link — no
            # sequence flow is taken and the scope stays alive through the
            # pending catch activation (reference:
            # IntermediateThrowEventProcessor.java:201-208 link routing)
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            target = exe.elements[element.link_target_idx]
            self._write_activate(writers, exe, target,
                                 value.get("flowScopeKey", -1), value)
            return
        elif element.element_type == BpmnElementType.EVENT_BASED_GATEWAY and triggered_element_id:
            # per the BPMN spec the sequence flow to the triggered event is NOT
            # taken — the event activates directly (reference:
            # EventBasedGatewayProcessor.onComplete :65-76)
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            target = exe.elements[exe.by_id[triggered_element_id]]
            self._write_activate(
                writers, exe, target, value.get("flowScopeKey", -1), value,
                extra={"eventTriggered": True},
            )
        else:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            for fidx in element.outgoing:
                self._take_flow(writers, exe, exe.flows[fidx], value)
            if (
                element.element_type == BpmnElementType.END_EVENT
                and element.event_type == BpmnEventType.TERMINATE
            ):
                # terminate every other active element instance in the flow
                # scope; the scope completes when the last one is gone
                # (reference: EndEventProcessor TerminateEndEventBehavior)
                scope_key = value.get("flowScopeKey", -1)
                for child_key in self.state.element_instances.children_keys(scope_key):
                    if child_key != key:
                        writers.append_command(
                            child_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
                        )

        if element.element_type == BpmnElementType.PROCESS:
            self._on_process_completed(key, value, child_locals or {}, writers)
            return
        if not element.outgoing:
            self._check_scope_completion(value.get("flowScopeKey", -1), writers)

    def _choose_exclusive_flow(self, key, value, exe, element, writers):
        context = self.state.variables.collect(key)
        for fidx in element.outgoing:
            if fidx == element.default_flow_idx:
                continue
            flow = exe.flows[fidx]
            if flow.condition is None:
                continue
            try:
                result = flow.condition.evaluate(context, self.clock_millis)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return None
            if result is True:
                return flow
        if element.default_flow_idx >= 0:
            return exe.flows[element.default_flow_idx]
        self._raise_incident(
            writers, key, value, ErrorType.CONDITION_ERROR,
            f"Expected at least one condition to evaluate to true, or to have a default flow "
            f"at gateway '{element.id}'",
        )
        return None

    def _choose_inclusive_flows(self, key, value, exe, element, writers):
        """All outgoing flows with true conditions; the default flow only when
        no condition holds (reference: InclusiveGatewayProcessor)."""
        context = self.state.variables.collect(key)
        taken = []
        for fidx in element.outgoing:
            if fidx == element.default_flow_idx:
                continue
            flow = exe.flows[fidx]
            if flow.condition is None:
                # unconditional non-default flow on a single-outgoing gateway
                taken.append(flow)
                continue
            try:
                result = flow.condition.evaluate(context, self.clock_millis)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return None
            if result is True:
                taken.append(flow)
        if taken:
            return taken
        if element.default_flow_idx >= 0:
            return [exe.flows[element.default_flow_idx]]
        self._raise_incident(
            writers, key, value, ErrorType.CONDITION_ERROR,
            f"Expected at least one condition to evaluate to true, or to have a default flow "
            f"at gateway '{element.id}'",
        )
        return None

    def _take_flow(self, writers: Writers, exe: ExecutableProcess, flow, scope_value: dict) -> None:
        # the scope containing the flow is the completing element's flow scope
        scope_key = scope_value.get("flowScopeKey", -1)
        flow_value = {
            "bpmnProcessId": scope_value["bpmnProcessId"],
            "version": scope_value["version"],
            "processDefinitionKey": scope_value["processDefinitionKey"],
            "processInstanceKey": scope_value["processInstanceKey"],
            "elementId": flow.id,
            "flowScopeKey": scope_key,
            "bpmnElementType": BpmnElementType.SEQUENCE_FLOW.name,
            "bpmnEventType": BpmnEventType.UNSPECIFIED.name,
        }
        flow_key = self.state.next_key()
        writers.append_event(flow_key, ValueType.PROCESS_INSTANCE, PI.SEQUENCE_FLOW_TAKEN, flow_value)

        target = exe.elements[flow.target_idx]
        if target.element_type == BpmnElementType.PARALLEL_GATEWAY:
            incoming = [f.idx for f in exe.flows if f.target_idx == target.idx]
            if self.state.element_instances.taken_flows_satisfy_join(scope_key, target.idx, incoming):
                self._write_activate(writers, exe, target, scope_key, scope_value)
        else:
            self._write_activate(writers, exe, target, scope_key, scope_value)

    def _write_activate(
        self, writers: Writers, exe: ExecutableProcess, element: ExecutableElement,
        scope_key: int, value: dict, extra: dict | None = None,
    ) -> None:
        new_key = self.state.next_key()
        # an element with loop characteristics is entered through its
        # multi-instance body wrapper (reference: MULTI_INSTANCE_BODY element)
        element_type_name = (
            BpmnElementType.MULTI_INSTANCE_BODY.name
            if element.multi_instance is not None
            else element.element_type.name
        )
        child_value = {
            "bpmnProcessId": value["bpmnProcessId"],
            "version": value["version"],
            "processDefinitionKey": value["processDefinitionKey"],
            "processInstanceKey": value["processInstanceKey"],
            "elementId": element.id,
            "flowScopeKey": scope_key,
            "bpmnElementType": element_type_name,
            "bpmnEventType": element.event_type.name,
        }
        if "tenantId" in value:
            child_value["tenantId"] = value["tenantId"]
        if extra:
            child_value.update(extra)
        writers.append_command(new_key, ValueType.PROCESS_INSTANCE, PI.ACTIVATE_ELEMENT, child_value)

    # -------------------------------------------------------- scope completion

    def _check_scope_completion(self, scope_key: int, writers: Writers) -> None:
        if scope_key < 0:
            return
        scope = self.state.element_instances.get(scope_key)
        if scope is None:
            return
        if scope["state"] not in (EI_ACTIVATED, EI_ACTIVATING):
            return
        if scope["activeChildren"] == 0 and scope["activeFlows"] == 0:
            writers.append_command(
                scope_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT, {}
            )

    def _on_process_completed(self, key: int, value: dict, child_locals: dict,
                              writers: Writers) -> None:
        """A completed child of a call activity propagates its root variables
        and completes the call activity (reference: CallActivityProcessor).

        With output mappings, child variables land in the call activity's
        local scope so the mappings can read them; without, the reference
        default (propagateAllChildVariables=true) merges them upward like job
        completion variables."""
        parent_ei_key = value.get("parentElementInstanceKey", -1)
        if parent_ei_key < 0:
            if self.on_root_completed is not None:
                self.on_root_completed(key, value, child_locals, writers)
            return
        parent = self.state.element_instances.get(parent_ei_key)
        if parent is None or parent["state"] not in (EI_ACTIVATED, EI_ACTIVATING):
            return  # parent terminated/interrupted meanwhile
        parent_value = parent["value"]
        call_element = self._executable(parent_value).element(parent_value["elementId"])
        parent_pi_key = parent_value.get("processInstanceKey", -1)
        for name, val in child_locals.items():
            if call_element.outputs or call_element.multi_instance is not None:
                # with output mappings the mappings read the call activity's
                # local scope; under multi-instance, parallel siblings must not
                # overwrite each other via the shared parent scope — results
                # land locally and flow out through outputElement collection
                # (same invariant as job-completion merge_local)
                target_scope = parent_ei_key
            else:
                target_scope = (
                    self.state.variables.find_scope_with(parent_ei_key, name)
                    or parent_pi_key
                )
            self._write_variable(writers, target_scope, parent_value, name, val)
        writers.append_command(
            parent_ei_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT, {}
        )

    # set by the Engine: root-instance completion/termination hooks
    # (await-result responses + parked-request cleanup)
    on_root_completed = None
    on_root_terminated = None

    # -------------------------------------------------------------- terminate

    def _terminate(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        value = _pi_value(value, element)
        instance = self.state.element_instances.get(key)
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_TERMINATING, value)

        user_task_key = self.state.user_tasks.key_for_element(key)
        if user_task_key is not None:
            task = self.state.user_tasks.get(user_task_key)
            writers.append_event(user_task_key, ValueType.USER_TASK,
                                 UserTaskIntent.CANCELING, task)
            writers.append_event(user_task_key, ValueType.USER_TASK,
                                 UserTaskIntent.CANCELED, task)
        job_key = instance.get("jobKey", -1)
        if job_key >= 0:
            job = self.state.jobs.get(job_key)
            if job is not None:
                writers.append_event(job_key, ValueType.JOB, JobIntent.CANCELED, job)
        self._close_subscriptions(key, value, writers)

        # a call activity first terminates its called child instance; the child
        # root's termination resumes this element (see _finish_terminate)
        child_pi_key = instance.get("calledChildInstanceKey", -1)
        if child_pi_key >= 0 and self.state.element_instances.get(child_pi_key) is not None:
            writers.append_command(
                child_pi_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
            )
            return

        children = self.state.element_instances.children_keys(key)
        if children:
            if len(children) > PI_BATCH_CHUNK:
                # chunked termination of huge scopes (reference:
                # TerminateProcessInstanceBatchProcessor)
                from zeebe_tpu.protocol.intent import ProcessInstanceBatchIntent

                writers.append_command(
                    self.state.next_key(), ValueType.PROCESS_INSTANCE_BATCH,
                    ProcessInstanceBatchIntent.TERMINATE,
                    {
                        "processInstanceKey": value.get("processInstanceKey", -1),
                        "batchElementInstanceKey": key,
                    },
                )
                return
            for child_key in children:
                writers.append_command(
                    child_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
                )
            # stay TERMINATING; the last terminated child finishes this scope
            return

        self._finish_terminate(key, value, writers)

    def _finish_terminate(self, key: int, value: dict, writers: Writers) -> None:
        scope_key = value.get("flowScopeKey", -1)
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_TERMINATED, value)
        if scope_key >= 0:
            scope = self.state.element_instances.get(scope_key)
            if scope is not None and scope["state"] == EI_TERMINATING:
                if self.state.element_instances.get(scope_key)["activeChildren"] == 0:
                    scope_value = scope["value"]
                    exe = self._executable(scope_value)
                    self._finish_terminate(scope_key, _pi_value(scope_value, exe.element(scope_value["elementId"])), writers)
            elif scope is not None:
                # a terminate end event removed its siblings while the scope
                # stays active — the last terminated child completes the scope
                self._check_scope_completion(scope_key, writers)
            return
        # a terminated root answers/cleans parked await-result requests
        if (value.get("bpmnElementType") == BpmnElementType.PROCESS.name
                and value.get("parentElementInstanceKey", -1) < 0
                and self.on_root_terminated is not None):
            self.on_root_terminated(key, value, writers)
        # a terminated child-process root resumes its call activity's terminate
        parent_ei_key = value.get("parentElementInstanceKey", -1)
        if parent_ei_key >= 0:
            parent = self.state.element_instances.get(parent_ei_key)
            if parent is not None and parent["state"] == EI_TERMINATING:
                parent_value = parent["value"]
                exe = self._executable(parent_value)
                self._finish_terminate(
                    parent_ei_key,
                    _pi_value(parent_value, exe.element(parent_value["elementId"])),
                    writers,
                )

    # -------------------------------------------------------------- incidents

    def _resolve_form(self, key: int, value: dict, element, writers) -> int | None:
        """Latest deployed form for the element's formId in the instance's
        tenant; missing → FORM_NOT_FOUND incident and the element stays
        ACTIVATING so incident resolution retries (reference:
        BpmnUserTaskBehavior form resolution)."""
        tenant = value.get("tenantId", DEFAULT_TENANT)
        form = self.state.forms.get_latest_by_id(element.form_id, tenant)
        if form is None:
            self._raise_incident(
                writers, key, value, ErrorType.FORM_NOT_FOUND,
                f"Expected to find a form with id '{element.form_id}', "
                "but no form with this id is found",
            )
            return None
        return form["formKey"]

    def _raise_incident(
        self, writers: Writers, element_key: int, value: dict,
        error_type: ErrorType, message: str,
    ) -> None:
        incident_key = self.state.next_key()
        writers.append_event(
            incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
            {
                "errorType": error_type.name,
                "errorMessage": message,
                "bpmnProcessId": value.get("bpmnProcessId", ""),
                "processDefinitionKey": value.get("processDefinitionKey", -1),
                "processInstanceKey": value.get("processInstanceKey", -1),
                "elementId": value.get("elementId", ""),
                "elementInstanceKey": element_key,
                "jobKey": -1,
                "variableScopeKey": element_key,
            },
        )

    # -------------------------------------------------------------- variables

    def _write_variable(
        self, writers: Writers, scope_key: int, pi_value: dict, name: str, result: Any
    ) -> None:
        from zeebe_tpu.feel.temporal import normalize_value

        result = normalize_value(result)
        exists = self.state.variables.has_local(scope_key, name)
        var_key = self.state.next_key()
        writers.append_event(
            var_key, ValueType.VARIABLE,
            VariableIntent.UPDATED if exists else VariableIntent.CREATED,
            {
                "name": name,
                "value": result,
                "scopeKey": scope_key,
                "processInstanceKey": pi_value.get("processInstanceKey", -1),
                "processDefinitionKey": pi_value.get("processDefinitionKey", -1),
                "bpmnProcessId": pi_value.get("bpmnProcessId", ""),
            },
        )


def _eval_date_millis(expr, context, clock_millis) -> int:
    """Evaluate a timer timeDate expression → absolute epoch millis.
    Accepts FEEL date-and-time / date values, ISO-8601 strings, or raw
    epoch millis (reference: timer timeDate is evaluated via FEEL to a
    zoned date-time)."""
    from zeebe_tpu.feel.temporal import (
        FeelDate,
        FeelDateTime,
        parse_date_time,
    )

    raw = expr.evaluate(context, clock_millis)
    if isinstance(raw, FeelDateTime):
        return raw.epoch_millis
    if isinstance(raw, FeelDate):
        return parse_date_time(str(raw)).epoch_millis
    if isinstance(raw, (int, float)) and not isinstance(raw, bool):
        return int(raw)
    if isinstance(raw, str):
        return parse_date_time(raw).epoch_millis
    raise ValueError(f"timer date evaluated to {type(raw).__name__}")


def _pi_value(value: dict, element: ExecutableElement) -> dict:
    """Canonical PROCESS_INSTANCE record value (camelCase, reference shape)."""
    # the body wrapper of a multi-instance element keeps its own element type
    mi_body = value.get("bpmnElementType") == BpmnElementType.MULTI_INSTANCE_BODY.name
    out = {
        "bpmnProcessId": value["bpmnProcessId"],
        "version": value["version"],
        "processDefinitionKey": value["processDefinitionKey"],
        "processInstanceKey": value["processInstanceKey"],
        "elementId": element.id,
        "flowScopeKey": value.get("flowScopeKey", -1),
        "bpmnElementType": (
            BpmnElementType.MULTI_INSTANCE_BODY.name if mi_body else element.element_type.name
        ),
        "bpmnEventType": element.event_type.name,
        "parentProcessInstanceKey": value.get("parentProcessInstanceKey", -1),
        "parentElementInstanceKey": value.get("parentElementInstanceKey", -1),
    }
    if "tenantId" in value:
        out["tenantId"] = value["tenantId"]
    if "loopCounter" in value:
        out["loopCounter"] = value["loopCounter"]
    if value.get("directActivation"):
        # modification-activated: the applier must not consume a flow token
        out["directActivation"] = True
    return out
