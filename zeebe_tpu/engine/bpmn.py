"""BPMN element lifecycle processing — the core state machine.

Reference: engine/src/main/java/io/camunda/zeebe/engine/processing/bpmn/
BpmnStreamProcessor.java:36 (processRecord :75 → guard → processEvent :133
switching on ACTIVATE/COMPLETE/TERMINATE_ELEMENT), ProcessInstanceLifecycle
(legal transitions), behavior/BpmnStateTransitionBehavior (lifecycle event
chains + sequence-flow taking), and the per-type element processors under
bpmn/{container,task,event,gateway}/.

Lifecycle chains produced by one command (identical in shape to the
reference's event streams):

  ACTIVATE_ELEMENT →  ELEMENT_ACTIVATING, ELEMENT_ACTIVATED
                      [wait states stop here: tasks with jobs, catch events]
                      [pass-through elements continue:]
                      ELEMENT_COMPLETING, ELEMENT_COMPLETED,
                      SEQUENCE_FLOW_TAKEN*, follow-up ACTIVATE_ELEMENT cmds
  COMPLETE_ELEMENT →  ELEMENT_COMPLETING, ELEMENT_COMPLETED, flows, …
  TERMINATE_ELEMENT → ELEMENT_TERMINATING, [terminate children/cancel job],
                      ELEMENT_TERMINATED, scope follow-ups

Scope completion: when the last token in a scope disappears (no active
children, no tokens in transit), the scope's COMPLETE_ELEMENT command is
written — process completion bubbles up from end events exactly as in the
reference's afterExecutionPathCompleted.
"""

from __future__ import annotations

from typing import Any

from zeebe_tpu.engine.engine_state import (
    EI_ACTIVATED,
    EI_ACTIVATING,
    EI_COMPLETING,
    EI_TERMINATING,
    EngineState,
)
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.feel import FeelEvalError
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.models.bpmn import ExecutableElement, ExecutableProcess
from zeebe_tpu.protocol import RejectionType, ValueType
from zeebe_tpu.protocol.enums import BpmnElementType, BpmnEventType, ErrorType
from zeebe_tpu.protocol.intent import (
    IncidentIntent,
    JobIntent,
    ProcessInstanceIntent,
    ProcessInstanceResultIntent,
    TimerIntent,
    VariableIntent,
)

PI = ProcessInstanceIntent


class BpmnProcessor:
    """Handles PROCESS_INSTANCE ACTIVATE/COMPLETE/TERMINATE_ELEMENT commands."""

    def __init__(self, state: EngineState, clock_millis, sender=None, partition_count: int = 1) -> None:
        self.state = state
        self.clock_millis = clock_millis
        self.sender = sender  # InterPartitionCommandSender (set via Engine.wire)
        self.partition_count = partition_count

    # ------------------------------------------------------------------ entry

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        intent = cmd.record.intent
        value = dict(cmd.record.value)
        key = cmd.record.key

        if intent == PI.ACTIVATE_ELEMENT:
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._activate(key, value, exe, element, writers)
        elif intent == PI.COMPLETE_ELEMENT:
            instance = self.state.element_instances.get(key)
            # COMPLETING is legal here: incident resolution retries a stalled
            # completing transition (condition/output-mapping failures)
            if instance is None or instance["state"] not in (EI_ACTIVATED, EI_ACTIVATING, EI_COMPLETING):
                writers.respond_rejection(
                    cmd, RejectionType.INVALID_STATE,
                    f"expected element instance {key} to be activated, but it is "
                    + ("not present" if instance is None else "not in an activatable state"),
                )
                return
            value = instance["value"]
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._complete(key, value, exe, element, writers)
        elif intent == PI.TERMINATE_ELEMENT:
            instance = self.state.element_instances.get(key)
            if instance is None:
                writers.respond_rejection(
                    cmd, RejectionType.NOT_FOUND, f"no element instance {key}"
                )
                return
            value = instance["value"]
            exe = self._executable(value)
            element = exe.element(value["elementId"])
            self._terminate(key, value, exe, element, writers)
        else:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_ARGUMENT, f"unsupported intent {intent.name}"
            )

    def _executable(self, value: dict) -> ExecutableProcess:
        exe = self.state.processes.executable(value["processDefinitionKey"])
        if exe is None:
            raise KeyError(f"unknown process definition {value['processDefinitionKey']}")
        return exe

    # -------------------------------------------------------------- activation

    def _activate(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        start_override = value.get("startElementId")
        value = _pi_value(value, element)
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATING, value)

        # input mappings create a local variable scope on the element instance
        if element.inputs:
            context = self.state.variables.collect(value.get("flowScopeKey", -1))
            try:
                for expr, target in element.inputs:
                    result = expr.evaluate(context, self.clock_millis)
                    self._write_variable(writers, key, value, target, result)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.IO_MAPPING_ERROR, str(exc))
                return

        # boundary-event subscriptions attach when the host activity activates
        if element.boundary_idxs:
            self._open_boundary_subscriptions(key, value, exe, element, writers)

        et = element.element_type
        if et == BpmnElementType.PROCESS or et == BpmnElementType.SUB_PROCESS:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            if et == BpmnElementType.SUB_PROCESS:
                start_idx = element.child_start_idx
            else:
                # message/timer start events carry an explicit start element
                start_idx = exe.by_id[start_override] if start_override else exe.none_start_of(0)
            start = exe.elements[start_idx]
            self._write_activate(writers, exe, start, scope_key=key, value=value)
        elif et == BpmnElementType.START_EVENT:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)
        elif et in (BpmnElementType.SERVICE_TASK, BpmnElementType.SEND_TASK,
                    BpmnElementType.BUSINESS_RULE_TASK, BpmnElementType.SCRIPT_TASK,
                    BpmnElementType.USER_TASK) and element.job_type is not None:
            context = self.state.variables.collect(key)
            try:
                job_type = element.job_type.evaluate(context, self.clock_millis)
                retries = int(element.job_retries.evaluate(context, self.clock_millis))
            except (FeelEvalError, TypeError, ValueError) as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            job_key = self.state.next_key()
            writers.append_event(
                job_key, ValueType.JOB, JobIntent.CREATED,
                {
                    "type": job_type,
                    "retries": retries,
                    "worker": "",
                    "deadline": -1,
                    "variables": {},
                    "customHeaders": element.task_headers,
                    "elementId": element.id,
                    "elementInstanceKey": key,
                    "processInstanceKey": value["processInstanceKey"],
                    "processDefinitionKey": value["processDefinitionKey"],
                    "processDefinitionVersion": value["version"],
                    "bpmnProcessId": value["bpmnProcessId"],
                    "errorMessage": "",
                },
            )
            # wait state: completion comes from the job COMPLETE command
        elif et == BpmnElementType.SCRIPT_TASK and element.script_expression is not None:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            context = self.state.variables.collect(key)
            try:
                result = element.script_expression.evaluate(context, self.clock_millis)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return
            if element.script_result_variable:
                self._write_variable(
                    writers, value.get("flowScopeKey", -1), value,
                    element.script_result_variable, result,
                )
            self._complete(key, value, exe, element, writers)
        elif et in (BpmnElementType.INTERMEDIATE_CATCH_EVENT, BpmnElementType.RECEIVE_TASK):
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            if element.event_type == BpmnEventType.TIMER or element.timer_duration is not None:
                self._create_timer(key, value, element, element, writers)
            elif element.message_name is not None:
                if not self._open_message_subscription(key, value, element, element, writers):
                    return
            # wait state: timer trigger / message correlation completes it
        elif et in (BpmnElementType.MANUAL_TASK, BpmnElementType.TASK,
                    BpmnElementType.EXCLUSIVE_GATEWAY, BpmnElementType.PARALLEL_GATEWAY,
                    BpmnElementType.END_EVENT, BpmnElementType.INTERMEDIATE_THROW_EVENT):
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)
        else:
            # elements not yet implemented behave as pass-through tasks
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_ACTIVATED, value)
            self._complete(key, value, exe, element, writers)

    # ------------------------------------------------- event subscriptions

    def _eval_duration_millis(self, expr, context) -> int:
        from zeebe_tpu.utils import parse_duration_millis

        raw = expr.evaluate(context, self.clock_millis)
        if isinstance(raw, (int, float)):
            return int(raw)
        return parse_duration_millis(str(raw))

    def _create_timer(self, host_key: int, value: dict, catching: ExecutableElement,
                      host: ExecutableElement, writers: Writers,
                      repetitions: int = 1, interval: int = -1) -> None:
        context = self.state.variables.collect(host_key)
        try:
            duration = self._eval_duration_millis(catching.timer_duration, context)
        except Exception as exc:  # noqa: BLE001 — bad timer → incident
            self._raise_incident(writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
            return
        timer_key = self.state.next_key()
        writers.append_event(
            timer_key, ValueType.TIMER, TimerIntent.CREATED,
            {
                "elementId": host.id,
                "targetElementId": catching.id,
                "elementInstanceKey": host_key,
                "processInstanceKey": value.get("processInstanceKey", -1),
                "processDefinitionKey": value.get("processDefinitionKey", -1),
                "dueDate": self.clock_millis() + duration,
                "repetitions": repetitions,
                "interval": interval if interval > 0 else duration,
            },
        )

    def _open_message_subscription(self, host_key: int, value: dict,
                                   catching: ExecutableElement, host: ExecutableElement,
                                   writers: Writers) -> bool:
        from zeebe_tpu.parallel.partitioning import subscription_partition_id
        from zeebe_tpu.protocol import command as make_command
        from zeebe_tpu.protocol.intent import (
            MessageSubscriptionIntent,
            ProcessMessageSubscriptionIntent,
        )

        context = self.state.variables.collect(host_key)
        try:
            correlation_key = catching.correlation_key.evaluate(context, self.clock_millis)
        except FeelEvalError as exc:
            self._raise_incident(writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
            return False
        if correlation_key is None:
            self._raise_incident(
                writers, host_key, value, ErrorType.EXTRACT_VALUE_ERROR,
                f"correlation key of '{catching.id}' evaluated to null",
            )
            return False
        correlation_key = str(correlation_key)
        # the process partition allocates the message-side subscription key so
        # both sides can address it (open, correlate-ack, delete)
        msg_sub_key = self.state.next_key()
        sub_value = {
            "processInstanceKey": value.get("processInstanceKey", -1),
            "elementInstanceKey": host_key,
            "messageName": catching.message_name,
            "correlationKey": correlation_key,
            "targetElementId": catching.id,
            "interrupting": catching.interrupting,
            "bpmnProcessId": value.get("bpmnProcessId", ""),
            "subscriptionPartitionId": self.state.partition_id,
            "messageSubscriptionKey": msg_sub_key,
        }
        writers.append_event(
            host_key, ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
            ProcessMessageSubscriptionIntent.CREATING, sub_value,
        )
        message_partition = subscription_partition_id(correlation_key, self.partition_count)
        open_cmd = make_command(
            ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CREATE, sub_value,
            key=msg_sub_key,
        )
        sender = self.sender
        writers.after_commit(lambda: sender.send_command(message_partition, open_cmd))
        return True

    def _open_boundary_subscriptions(self, host_key: int, value: dict,
                                     exe: ExecutableProcess, host: ExecutableElement,
                                     writers: Writers) -> None:
        for bidx in host.boundary_idxs:
            boundary = exe.elements[bidx]
            if boundary.event_type == BpmnEventType.TIMER and boundary.timer_duration is not None:
                reps = 1 if boundary.interrupting else -1
                self._create_timer(host_key, value, boundary, host, writers, repetitions=reps)
            elif boundary.event_type == BpmnEventType.MESSAGE and boundary.message_name:
                self._open_message_subscription(host_key, value, boundary, host, writers)

    def _close_subscriptions(self, key: int, value: dict, writers: Writers) -> None:
        """Cancel timers + message subscriptions attached to an element
        instance when it completes or terminates."""
        from zeebe_tpu.parallel.partitioning import subscription_partition_id
        from zeebe_tpu.protocol import command as make_command
        from zeebe_tpu.protocol.intent import (
            MessageSubscriptionIntent,
            ProcessMessageSubscriptionIntent,
            TimerIntent,
        )

        for timer_key, timer in self.state.timers.timers_for_element_instance(key):
            writers.append_event(timer_key, ValueType.TIMER, TimerIntent.CANCELED, timer)
        for sub in self.state.process_message_subscriptions.subscriptions_of(key):
            writers.append_event(
                key, ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
                ProcessMessageSubscriptionIntent.DELETED, sub,
            )
            message_partition = subscription_partition_id(
                sub["correlationKey"], self.partition_count
            )
            sub_key = sub.get("messageSubscriptionKey", -1)
            if sub_key >= 0:
                delete_cmd = make_command(
                    ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.DELETE,
                    dict(sub), key=sub_key,
                )
                sender = self.sender
                writers.after_commit(
                    lambda mp=message_partition, dc=delete_cmd: sender.send_command(mp, dc)
                )

    # -------------------------------------------------------------- completion

    def _complete(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        value = _pi_value(value, element)
        instance = self.state.element_instances.get(key)
        if instance is None or instance["state"] != EI_COMPLETING:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETING, value)
        # else: retrying a stalled completing transition after incident resolution

        # output mappings evaluate against the element scope, write to parent
        if element.outputs:
            context = self.state.variables.collect(key)
            try:
                for expr, target in element.outputs:
                    result = expr.evaluate(context, self.clock_millis)
                    self._write_variable(
                        writers, value.get("flowScopeKey", -1), value, target, result
                    )
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.IO_MAPPING_ERROR, str(exc))
                return

        # boundary/catch subscriptions close when the element leaves ACTIVATED
        self._close_subscriptions(key, value, writers)

        if element.element_type == BpmnElementType.EXCLUSIVE_GATEWAY and (
            len(element.outgoing) > 1
            or any(exe.flows[f].condition is not None for f in element.outgoing)
        ):
            taken = self._choose_exclusive_flow(key, value, exe, element, writers)
            if taken is None:
                return  # incident raised; stays in COMPLETING
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            self._take_flow(writers, exe, taken, value)
        else:
            writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_COMPLETED, value)
            for fidx in element.outgoing:
                self._take_flow(writers, exe, exe.flows[fidx], value)

        if element.element_type == BpmnElementType.PROCESS:
            self._on_process_completed(key, value, writers)
            return
        if not element.outgoing:
            self._check_scope_completion(value.get("flowScopeKey", -1), writers)

    def _choose_exclusive_flow(self, key, value, exe, element, writers):
        context = self.state.variables.collect(key)
        for fidx in element.outgoing:
            if fidx == element.default_flow_idx:
                continue
            flow = exe.flows[fidx]
            if flow.condition is None:
                continue
            try:
                result = flow.condition.evaluate(context, self.clock_millis)
            except FeelEvalError as exc:
                self._raise_incident(writers, key, value, ErrorType.EXTRACT_VALUE_ERROR, str(exc))
                return None
            if result is True:
                return flow
        if element.default_flow_idx >= 0:
            return exe.flows[element.default_flow_idx]
        self._raise_incident(
            writers, key, value, ErrorType.CONDITION_ERROR,
            f"Expected at least one condition to evaluate to true, or to have a default flow "
            f"at gateway '{element.id}'",
        )
        return None

    def _take_flow(self, writers: Writers, exe: ExecutableProcess, flow, scope_value: dict) -> None:
        # the scope containing the flow is the completing element's flow scope
        scope_key = scope_value.get("flowScopeKey", -1)
        flow_value = {
            "bpmnProcessId": scope_value["bpmnProcessId"],
            "version": scope_value["version"],
            "processDefinitionKey": scope_value["processDefinitionKey"],
            "processInstanceKey": scope_value["processInstanceKey"],
            "elementId": flow.id,
            "flowScopeKey": scope_key,
            "bpmnElementType": BpmnElementType.SEQUENCE_FLOW.name,
            "bpmnEventType": BpmnEventType.UNSPECIFIED.name,
        }
        flow_key = self.state.next_key()
        writers.append_event(flow_key, ValueType.PROCESS_INSTANCE, PI.SEQUENCE_FLOW_TAKEN, flow_value)

        target = exe.elements[flow.target_idx]
        if target.element_type == BpmnElementType.PARALLEL_GATEWAY:
            incoming = [f.idx for f in exe.flows if f.target_idx == target.idx]
            if self.state.element_instances.taken_flows_satisfy_join(scope_key, target.idx, incoming):
                self._write_activate(writers, exe, target, scope_key, scope_value)
        else:
            self._write_activate(writers, exe, target, scope_key, scope_value)

    def _write_activate(
        self, writers: Writers, exe: ExecutableProcess, element: ExecutableElement,
        scope_key: int, value: dict,
    ) -> None:
        new_key = self.state.next_key()
        child_value = {
            "bpmnProcessId": value["bpmnProcessId"],
            "version": value["version"],
            "processDefinitionKey": value["processDefinitionKey"],
            "processInstanceKey": value["processInstanceKey"],
            "elementId": element.id,
            "flowScopeKey": scope_key,
            "bpmnElementType": element.element_type.name,
            "bpmnEventType": element.event_type.name,
        }
        writers.append_command(new_key, ValueType.PROCESS_INSTANCE, PI.ACTIVATE_ELEMENT, child_value)

    # -------------------------------------------------------- scope completion

    def _check_scope_completion(self, scope_key: int, writers: Writers) -> None:
        if scope_key < 0:
            return
        scope = self.state.element_instances.get(scope_key)
        if scope is None:
            return
        if scope["state"] not in (EI_ACTIVATED, EI_ACTIVATING):
            return
        if scope["activeChildren"] == 0 and scope["activeFlows"] == 0:
            writers.append_command(
                scope_key, ValueType.PROCESS_INSTANCE, PI.COMPLETE_ELEMENT, {}
            )

    def _on_process_completed(self, key: int, value: dict, writers: Writers) -> None:
        # bubble into a parent process (call activity) — forthcoming; top-level
        # completion may answer a create-with-result request (handled by the
        # creation processor's awaitResult bookkeeping, stored on the instance)
        pass

    # -------------------------------------------------------------- terminate

    def _terminate(
        self, key: int, value: dict, exe: ExecutableProcess,
        element: ExecutableElement, writers: Writers,
    ) -> None:
        value = _pi_value(value, element)
        instance = self.state.element_instances.get(key)
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_TERMINATING, value)

        job_key = instance.get("jobKey", -1)
        if job_key >= 0:
            job = self.state.jobs.get(job_key)
            if job is not None:
                writers.append_event(job_key, ValueType.JOB, JobIntent.CANCELED, job)
        self._close_subscriptions(key, value, writers)

        children = self.state.element_instances.children_keys(key)
        if children:
            for child_key in children:
                writers.append_command(
                    child_key, ValueType.PROCESS_INSTANCE, PI.TERMINATE_ELEMENT, {}
                )
            # stay TERMINATING; the last terminated child finishes this scope
            return

        self._finish_terminate(key, value, writers)

    def _finish_terminate(self, key: int, value: dict, writers: Writers) -> None:
        scope_key = value.get("flowScopeKey", -1)
        writers.append_event(key, ValueType.PROCESS_INSTANCE, PI.ELEMENT_TERMINATED, value)
        if scope_key >= 0:
            scope = self.state.element_instances.get(scope_key)
            if scope is not None and scope["state"] == EI_TERMINATING:
                if self.state.element_instances.get(scope_key)["activeChildren"] == 0:
                    scope_value = scope["value"]
                    exe = self._executable(scope_value)
                    self._finish_terminate(scope_key, _pi_value(scope_value, exe.element(scope_value["elementId"])), writers)

    # -------------------------------------------------------------- incidents

    def _raise_incident(
        self, writers: Writers, element_key: int, value: dict,
        error_type: ErrorType, message: str,
    ) -> None:
        incident_key = self.state.next_key()
        writers.append_event(
            incident_key, ValueType.INCIDENT, IncidentIntent.CREATED,
            {
                "errorType": error_type.name,
                "errorMessage": message,
                "bpmnProcessId": value.get("bpmnProcessId", ""),
                "processDefinitionKey": value.get("processDefinitionKey", -1),
                "processInstanceKey": value.get("processInstanceKey", -1),
                "elementId": value.get("elementId", ""),
                "elementInstanceKey": element_key,
                "jobKey": -1,
                "variableScopeKey": element_key,
            },
        )

    # -------------------------------------------------------------- variables

    def _write_variable(
        self, writers: Writers, scope_key: int, pi_value: dict, name: str, result: Any
    ) -> None:
        exists = self.state.variables.has_local(scope_key, name)
        var_key = self.state.next_key()
        writers.append_event(
            var_key, ValueType.VARIABLE,
            VariableIntent.UPDATED if exists else VariableIntent.CREATED,
            {
                "name": name,
                "value": result,
                "scopeKey": scope_key,
                "processInstanceKey": pi_value.get("processInstanceKey", -1),
                "processDefinitionKey": pi_value.get("processDefinitionKey", -1),
                "bpmnProcessId": pi_value.get("bpmnProcessId", ""),
            },
        )


def _pi_value(value: dict, element: ExecutableElement) -> dict:
    """Canonical PROCESS_INSTANCE record value (camelCase, reference shape)."""
    return {
        "bpmnProcessId": value["bpmnProcessId"],
        "version": value["version"],
        "processDefinitionKey": value["processDefinitionKey"],
        "processInstanceKey": value["processInstanceKey"],
        "elementId": element.id,
        "flowScopeKey": value.get("flowScopeKey", -1),
        "bpmnElementType": element.element_type.name,
        "bpmnEventType": element.event_type.name,
        "parentProcessInstanceKey": value.get("parentProcessInstanceKey", -1),
        "parentElementInstanceKey": value.get("parentElementInstanceKey", -1),
    }
