"""Timers, message publish/correlation, and due-date checking.

Reference: engine/…/processing/timer/ (TriggerTimerProcessor, DueDateChecker
:19), processing/message/ (MessagePublishProcessor, MessageCorrelator,
MessageExpireProcessor, Message(Start)EventSubscription processors,
MessageObserver), message/command/SubscriptionCommandSender.java:43, and
job/JobTimeoutTrigger.java:21 + JobBackoffChecker.

Correlation is the reference's two-partition protocol even on one partition
(commands loop back): the process partition opens a PROCESS_MESSAGE_
SUBSCRIPTION and sends MESSAGE_SUBSCRIPTION CREATE to hash(correlationKey)'s
partition; publishing correlates there and sends PROCESS_MESSAGE_SUBSCRIPTION
CORRELATE back; completion acks with MESSAGE_SUBSCRIPTION CORRELATE.
"""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.engine.engine_state import EI_ACTIVATED, EngineState
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.parallel.partitioning import (
    InterPartitionCommandSender,
    subscription_partition_id,
)
from zeebe_tpu.protocol import DEFAULT_TENANT, Record, RejectionType, ValueType, command
from zeebe_tpu.protocol.enums import BpmnElementType
from zeebe_tpu.protocol.intent import (
    JobIntent,
    MessageBatchIntent,
    MessageIntent,
    MessageStartEventSubscriptionIntent,
    MessageSubscriptionIntent,
    ProcessInstanceCreationIntent,
    ProcessInstanceIntent,
    ProcessMessageSubscriptionIntent,
    TimerIntent,
)

#: max message keys per MESSAGE_BATCH EXPIRE command — bounds the record size
#: like the reference's batch-size cap (MessageBatchExpireProcessor)
MESSAGE_EXPIRE_BATCH_MAX = 3000


class TimerProcessors:
    """TIMER TRIGGER / CANCEL commands."""

    def __init__(self, state: EngineState, clock_millis, bpmn) -> None:
        self.state = state
        self.clock_millis = clock_millis
        self.bpmn = bpmn

    def trigger(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        timer = self.state.timers.get(key)
        if timer is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND, f"timer {key} not found or already triggered"
            )
            return
        writers.append_event(key, ValueType.TIMER, TimerIntent.TRIGGERED, timer)

        element_instance_key = timer.get("elementInstanceKey", -1)
        target_element_id = timer["targetElementId"]
        if element_instance_key < 0:
            # timer start event: create a new process instance at that start
            self._trigger_start_event(timer, writers)
            return
        instance = self.state.element_instances.get(element_instance_key)
        if instance is None:
            return  # element already gone; TRIGGERED still recorded
        pi_value = instance["value"]
        exe = self.state.processes.executable(pi_value["processDefinitionKey"])
        target = exe.element(target_element_id)
        # routes to: the waiting catch event itself, an event-based gateway,
        # a boundary event, or an event sub-process start
        self.bpmn.route_trigger(element_instance_key, target_element_id, writers)
        # repeating timers (non-interrupting boundary / event sub-process
        # start with an R-cycle) reschedule themselves
        if target_element_id != pi_value["elementId"] and not target.interrupting:
            reps = timer.get("repetitions", 1)
            interval = timer.get("interval", -1)
            if (reps == -1 or reps > 1) and interval > 0:
                from zeebe_tpu.engine.burst_templates import note_clock_value

                timer_key = self.state.next_key()
                due_date = self.clock_millis() + interval
                note_clock_value(due_date, interval)
                writers.append_event(
                    timer_key, ValueType.TIMER, TimerIntent.CREATED,
                    {
                        **timer,
                        "dueDate": due_date,
                        "repetitions": reps - 1 if reps > 0 else -1,
                    },
                )

    def _trigger_start_event(self, timer: dict, writers: Writers) -> None:
        meta = self.state.processes.get_by_key(timer["processDefinitionKey"])
        if meta is None:
            return
        writers.append_command(
            -1, ValueType.PROCESS_INSTANCE_CREATION, ProcessInstanceCreationIntent.CREATE,
            {
                "bpmnProcessId": meta["bpmnProcessId"],
                "processDefinitionKey": meta["processDefinitionKey"],
                "version": meta["version"],
                "variables": {},
                "startElementId": timer["targetElementId"],
                # the creation must address the definition's own tenant or the
                # cross-tenant key-lookup guard rejects it
                **({"tenantId": meta["tenantId"]}
                   if meta.get("tenantId", DEFAULT_TENANT) != DEFAULT_TENANT else {}),
            },
        )
        reps = timer.get("repetitions", 1)
        interval = timer.get("interval", -1)
        if (reps == -1 or reps > 1) and interval > 0:
            from zeebe_tpu.engine.burst_templates import note_clock_value

            timer_key = self.state.next_key()
            due_date = self.clock_millis() + interval
            note_clock_value(due_date, interval)
            writers.append_event(
                timer_key, ValueType.TIMER, TimerIntent.CREATED,
                {
                    **timer,
                    "dueDate": due_date,
                    "repetitions": reps - 1 if reps > 0 else -1,
                },
            )

    def cancel(self, cmd: LoggedRecord, writers: Writers) -> None:
        timer = self.state.timers.get(cmd.record.key)
        if timer is None:
            return
        writers.append_event(cmd.record.key, ValueType.TIMER, TimerIntent.CANCELED, timer)


class MessageProcessors:
    """MESSAGE PUBLISH / EXPIRE on the message partition."""

    def __init__(
        self, state: EngineState, clock_millis, partition_count: int,
        sender: InterPartitionCommandSender,
    ) -> None:
        self.state = state
        self.clock_millis = clock_millis
        self.partition_count = partition_count
        self.sender = sender

    def publish(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        name = value.get("name", "")
        correlation_key = value.get("correlationKey", "")
        message_id = value.get("messageId", "") or ""
        ttl = value.get("timeToLive", 0)
        tenant = value.get("tenantId") or DEFAULT_TENANT
        from zeebe_tpu.engine.processors import check_tenant_authorized

        if not check_tenant_authorized(cmd, tenant, writers):
            return
        if message_id and self.state.messages.is_id_taken(
                name, correlation_key, message_id, tenant):
            writers.respond_rejection(
                cmd, RejectionType.ALREADY_EXISTS,
                f"a message with id '{message_id}' is already published",
            )
            return
        key = self.state.next_key()
        deadline = self.clock_millis() + max(ttl, 0)
        published_value = {
            "name": name,
            "correlationKey": correlation_key,
            "messageId": message_id,
            "timeToLive": ttl,
            "variables": value.get("variables", {}),
            "deadline": deadline,
            **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
        }
        published = writers.append_event(
            key, ValueType.MESSAGE, MessageIntent.PUBLISHED, published_value
        )
        writers.respond(cmd, published)

        # correlate to open subscriptions of the SAME tenant (once per
        # process instance; reference: tenant-aware MessageSubscriptionState)
        for sub_key, sub in self.state.message_subscriptions.find(name, correlation_key):
            if sub.get("tenantId", DEFAULT_TENANT) != tenant:
                continue
            pi_key = sub.get("processInstanceKey", -1)
            if self.state.messages.was_correlated_to(key, pi_key):
                continue
            self._correlate(key, published_value, sub_key, sub, writers)

        # message start events (tenant-matched)
        for start_sub in self.state.message_start_subscriptions.find(name):
            if start_sub.get("tenantId", DEFAULT_TENANT) != tenant:
                continue
            writers.append_event(
                self.state.next_key(), ValueType.MESSAGE_START_EVENT_SUBSCRIPTION,
                MessageStartEventSubscriptionIntent.CORRELATED,
                {**start_sub, "messageKey": key, "correlationKey": correlation_key},
            )
            writers.append_command(
                -1, ValueType.PROCESS_INSTANCE_CREATION, ProcessInstanceCreationIntent.CREATE,
                {
                    "bpmnProcessId": start_sub["bpmnProcessId"],
                    "processDefinitionKey": start_sub["processDefinitionKey"],
                    "version": -1,
                    "variables": published_value["variables"],
                    "startElementId": start_sub["startEventId"],
                    **({"tenantId": tenant} if tenant != DEFAULT_TENANT else {}),
                },
            )

    def _correlate(self, message_key: int, message: dict, sub_key: int, sub: dict,
                   writers: Writers) -> None:
        _correlate_to_subscription(
            self.state, self.sender, message_key, message, sub_key, sub, writers
        )

    def expire(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        msg = self.state.messages.get(key)
        if msg is None:
            return
        writers.append_event(key, ValueType.MESSAGE, MessageIntent.EXPIRED, msg)

    def expire_batch(self, cmd: LoggedRecord, writers: Writers) -> None:
        """MESSAGE_BATCH EXPIRE: one EXPIRED event removes every named
        message still present — O(batches) records for an N-message backlog
        (reference: MessageBatchExpireProcessor.java; VERDICT r4 item 7)."""
        keys = cmd.record.value.get("messageKeys") or []
        still = [k for k in keys if self.state.messages.get(k) is not None]
        if not still:
            return
        writers.append_event(
            self.state.next_key(), ValueType.MESSAGE_BATCH,
            MessageBatchIntent.EXPIRED, {"messageKeys": still},
        )


def _correlate_to_subscription(
    state: EngineState, sender, message_key: int, message: dict,
    sub_key: int, sub: dict, writers: Writers,
) -> None:
    """Message-partition correlation: CORRELATING event + ship the CORRELATE
    command to the subscription's process partition."""
    writers.append_event(
        sub_key, ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CORRELATING,
        {**sub, "messageKey": message_key, "variables": message.get("variables", {})},
    )
    receiver = sub.get("subscriptionPartitionId", state.partition_id)
    correlate_cmd = command(
        ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
        ProcessMessageSubscriptionIntent.CORRELATE,
        {
            "processInstanceKey": sub["processInstanceKey"],
            "elementInstanceKey": sub["elementInstanceKey"],
            "messageName": sub["messageName"],
            "correlationKey": sub["correlationKey"],
            "messageKey": message_key,
            "messageSubscriptionKey": sub_key,
            "variables": message.get("variables", {}),
            "subscriptionPartitionId": state.partition_id,
        },
        key=sub["elementInstanceKey"],
    )
    writers.after_commit(lambda: sender.send_command(receiver, correlate_cmd))


class MessageSubscriptionProcessors:
    """Message-partition side: CREATE (open) / CORRELATE (ack) / DELETE."""

    def __init__(self, state: EngineState, sender: InterPartitionCommandSender) -> None:
        self.state = state
        self.sender = sender

    def create(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = dict(cmd.record.value)
        # the process partition pre-allocates the subscription key (it travels
        # in the command key) so it can later address deletes/acks
        sub_key = cmd.record.key if cmd.record.key >= 0 else self.state.next_key()
        writers.append_event(
            sub_key, ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CREATED, value
        )
        # an already-buffered message of the same tenant may correlate
        # immediately
        name, corr = value["messageName"], value["correlationKey"]
        tenant = value.get("tenantId", DEFAULT_TENANT)
        pi_key = value.get("processInstanceKey", -1)
        for message_key in self.state.messages.buffered_for(name, corr):
            if self.state.messages.was_correlated_to(message_key, pi_key):
                continue
            message = self.state.messages.get(message_key)
            if message.get("tenantId", DEFAULT_TENANT) != tenant:
                continue
            _correlate_to_subscription(
                self.state, self.sender, message_key, message, sub_key, value, writers
            )
            break

    def correlate_ack(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        sub = self.state.message_subscriptions.get(key)
        if sub is None:
            return
        writers.append_event(
            key, ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CORRELATED,
            {**sub, "messageKey": cmd.record.value.get("messageKey", -1)},
        )

    def delete(self, cmd: LoggedRecord, writers: Writers) -> None:
        key = cmd.record.key
        sub = self.state.message_subscriptions.get(key)
        if sub is None:
            return
        writers.append_event(key, ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.DELETED, sub)


class ProcessMessageSubscriptionProcessors:
    """Process-partition side: CORRELATE completes the waiting element."""

    def __init__(self, state: EngineState, sender: InterPartitionCommandSender,
                 partition_count: int, bpmn) -> None:
        self.state = state
        self.sender = sender
        self.partition_count = partition_count
        self.bpmn = bpmn

    def correlate(self, cmd: LoggedRecord, writers: Writers) -> None:
        value = cmd.record.value
        element_key = value.get("elementInstanceKey", -1)
        name = value.get("messageName", "")
        sub = self.state.process_message_subscriptions.get(element_key, name)
        instance = self.state.element_instances.get(element_key)
        if sub is None or instance is None:
            # element gone (terminated/completed); the subscription-close path
            # already sent the delete — at-least-once semantics
            return
        writers.append_event(
            element_key, ValueType.PROCESS_MESSAGE_SUBSCRIPTION,
            ProcessMessageSubscriptionIntent.CORRELATED,
            {**sub, "messageKey": value.get("messageKey", -1)},
        )
        # message variables merge into the process instance scope
        pi_value = instance["value"]
        from zeebe_tpu.protocol.intent import VariableIntent

        for var_name, var_value in (value.get("variables") or {}).items():
            var_key = self.state.next_key()
            target_scope = (
                self.state.variables.find_scope_with(element_key, var_name)
                or pi_value["processInstanceKey"]
            )
            exists = self.state.variables.has_local(target_scope, var_name)
            writers.append_event(
                var_key, ValueType.VARIABLE,
                VariableIntent.UPDATED if exists else VariableIntent.CREATED,
                {
                    "name": var_name, "value": var_value, "scopeKey": target_scope,
                    "processInstanceKey": pi_value["processInstanceKey"],
                    "processDefinitionKey": pi_value["processDefinitionKey"],
                    "bpmnProcessId": pi_value["bpmnProcessId"],
                },
            )

        target_element_id = sub.get("targetElementId", pi_value["elementId"])
        # routes to: the waiting catch element, an event-based gateway, a
        # boundary event, or an event sub-process start
        self.bpmn.route_trigger(element_key, target_element_id, writers)

        # ack to the message partition so the (single-use) subscription closes
        message_sub_key = value.get("messageSubscriptionKey", -1)
        if message_sub_key >= 0:
            message_partition = subscription_partition_id(
                sub["correlationKey"], self.partition_count
            )
            ack = command(
                ValueType.MESSAGE_SUBSCRIPTION, MessageSubscriptionIntent.CORRELATE,
                {"messageKey": value.get("messageKey", -1)},
                key=message_sub_key,
            )
            writers.after_commit(
                lambda: self.sender.send_command(message_partition, ack)
            )


class DueDateCheckers:
    """Schedules and runs the due-date sweeps: timers, message TTL, job
    deadlines, job retry backoff (reference: DueDateChecker, MessageObserver,
    JobTimeoutTrigger, JobBackoffChecker). Wired by the harness/broker pump:
    call ``reschedule()`` after every processing batch.

    Scheduling rides the hierarchical timer wheel (engine/timer_wheel.py,
    ISSUE 8): the wheel is rebuilt from the due-date indexes at construction
    (every partition transition builds fresh checkers) and fed afterwards by
    the ``ZbDb.note_due`` seam, so ``reschedule()`` is a constant-time wheel
    probe instead of four index scans per processing batch. The wheel only
    over-approximates (lazy cancellation, rolled-back inserts); the sweep
    itself re-verifies against the sorted state indexes with range-bounded
    O(due) scans — state stays the single source of truth."""

    def __init__(self, engine_state: EngineState, schedule_service,
                 clock_millis) -> None:
        from zeebe_tpu.engine.timer_wheel import DueDateWheel

        self.state = engine_state
        self.schedule = schedule_service
        self.clock_millis = clock_millis
        self._handle = None
        self._scheduled_due: int | None = None
        self.wheel = DueDateWheel(clock_millis,
                                  partition_id=engine_state.partition_id)
        self.wheel.rebuild(engine_state)
        engine_state.db.due_listener = self.wheel.note_due

    def _next_due(self) -> int | None:
        """The state-index next-due probe (kept as the test oracle for the
        wheel's never-late property; O(log n) per index since ISSUE 8)."""
        with self.state.db.transaction():
            candidates = [
                self.state.timers.next_due(),
                self.state.messages.next_deadline(),
                self.state.jobs.next_deadline(),
                self.state.jobs.next_backoff(),
            ]
        due = [c for c in candidates if c is not None]
        return min(due) if due else None

    def maybe_advance_wheel(self, now_ms: int) -> None:
        """Follower-side wheel hygiene: drop deadlines the leader has long
        since swept (replay feeds the wheel on followers too). Throttled —
        one advance per second of stream clock."""
        if now_ms - self._last_follower_advance_ms >= 1000:
            self._last_follower_advance_ms = now_ms
            self.wheel.advance(now_ms)

    _last_follower_advance_ms = 0

    def reschedule(self) -> None:
        due = self.wheel.next_due()
        if due == self._scheduled_due and self._handle is not None \
                and not self._handle.cancelled:
            return  # already armed for exactly this instant
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        self._scheduled_due = due
        if due is not None:
            self._handle = self.schedule.run_at(due, self._sweep)

    def _sweep(self) -> list[Record]:
        now = self.clock_millis()
        # the wheel entries this sweep covers are spent: drop them and
        # cascade entered coarse buckets (stale/canceled entries die here
        # too — their only cost was this sweep looking)
        self.wheel.advance(now)
        self._scheduled_due = None
        commands: list[Record] = []
        with self.state.db.transaction():
            for timer_key, _timer in self.state.timers.due_timers(now):
                commands.append(
                    command(ValueType.TIMER, TimerIntent.TRIGGER, {}, key=timer_key)
                )
            # batched expiry: ONE MESSAGE_BATCH command expires the whole due
            # backlog (chunked to bound record size) — per-message EXPIRE is
            # exactly the per-record overhead this framework exists to kill
            # (reference: protocol.xml MESSAGE_BATCH,
            # MessageBatchExpireProcessor.java)
            expired_keys = [mk for _d, mk in self.state.messages.expired(now)]
            for i in range(0, len(expired_keys), MESSAGE_EXPIRE_BATCH_MAX):
                commands.append(
                    command(ValueType.MESSAGE_BATCH, MessageBatchIntent.EXPIRE,
                            {"messageKeys":
                             expired_keys[i:i + MESSAGE_EXPIRE_BATCH_MAX]})
                )
            for job_key in self.state.jobs.expired_deadlines(now):
                commands.append(
                    command(ValueType.JOB, JobIntent.TIME_OUT, {}, key=job_key)
                )
            for until, job_key in self.state.jobs.backoff_due(now):
                commands.append(
                    command(ValueType.JOB, JobIntent.RECUR_AFTER_BACKOFF,
                            {"recurAt": until}, key=job_key)
                )
        return commands
