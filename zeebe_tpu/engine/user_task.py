"""Native user task lifecycle (zeebe:userTask, no job worker).

Reference: engine/…/processing/usertask/ UserTask*Processors (8.4 native user
tasks): CREATING/CREATED on activation; COMPLETE → COMPLETING/COMPLETED
completes the element; ASSIGN/CLAIM set the assignee (CLAIM rejects when
already assigned to someone else); UPDATE changes candidate groups/users/due
date; element termination cancels the task (CANCELING/CANCELED).
"""

from __future__ import annotations

from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import RejectionType, ValueType
from zeebe_tpu.protocol.intent import ProcessInstanceIntent, UserTaskIntent, VariableIntent


class UserTaskProcessors:
    def __init__(self, state: EngineState) -> None:
        self.state = state

    def _lookup(self, cmd: LoggedRecord, writers: Writers) -> dict | None:
        task = self.state.user_tasks.get(cmd.record.key)
        if task is None:
            writers.respond_rejection(
                cmd, RejectionType.NOT_FOUND,
                f"Expected to handle user task {cmd.record.key}, but none found",
            )
        return task

    def complete(self, cmd: LoggedRecord, writers: Writers) -> None:
        task = self._lookup(cmd, writers)
        if task is None:
            return
        variables = cmd.record.value.get("variables") or {}
        element_key = task["elementInstanceKey"]
        writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.COMPLETING, task
        )
        # completion variables merge into the process scope like job variables
        for name, val in variables.items():
            scope = (
                self.state.variables.find_scope_with(element_key, name)
                or task.get("processInstanceKey", element_key)
            )
            exists = self.state.variables.has_local(scope, name)
            writers.append_event(
                self.state.next_key(), ValueType.VARIABLE,
                VariableIntent.UPDATED if exists else VariableIntent.CREATED,
                {"name": name, "value": val, "scopeKey": scope,
                 "processInstanceKey": task.get("processInstanceKey", -1),
                 "processDefinitionKey": task.get("processDefinitionKey", -1),
                 "bpmnProcessId": task.get("bpmnProcessId", "")},
            )
        completed = writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.COMPLETED, task
        )
        writers.respond(cmd, completed)
        writers.append_command(
            element_key, ValueType.PROCESS_INSTANCE,
            ProcessInstanceIntent.COMPLETE_ELEMENT, {},
        )

    def assign(self, cmd: LoggedRecord, writers: Writers) -> None:
        task = self._lookup(cmd, writers)
        if task is None:
            return
        assignee = cmd.record.value.get("assignee", "")
        updated = {**task, "assignee": assignee}
        writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.ASSIGNING, updated
        )
        assigned = writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.ASSIGNED, updated
        )
        writers.respond(cmd, assigned)

    def claim(self, cmd: LoggedRecord, writers: Writers) -> None:
        task = self._lookup(cmd, writers)
        if task is None:
            return
        assignee = cmd.record.value.get("assignee", "")
        current = task.get("assignee", "")
        if current and current != assignee:
            writers.respond_rejection(
                cmd, RejectionType.INVALID_STATE,
                f"Expected to claim user task {cmd.record.key}, but it is "
                f"already assigned to '{current}'",
            )
            return
        updated = {**task, "assignee": assignee}
        assigned = writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.ASSIGNED, updated
        )
        writers.respond(cmd, assigned)

    def update(self, cmd: LoggedRecord, writers: Writers) -> None:
        task = self._lookup(cmd, writers)
        if task is None:
            return
        changes = {
            k: v for k, v in cmd.record.value.items()
            if k in ("candidateGroups", "candidateUsers", "dueDate",
                     "followUpDate", "priority")
        }
        updated_value = {**task, **changes}
        updated = writers.append_event(
            cmd.record.key, ValueType.USER_TASK, UserTaskIntent.UPDATED,
            updated_value,
        )
        writers.respond(cmd, updated)
