"""Generalized command distribution across partitions.

Reference: engine/src/main/java/io/camunda/zeebe/engine/processing/distribution/
CommandDistributionBehavior.java and docs/generalized_distribution.md:1-80 —
lifecycle STARTED → DISTRIBUTING (per target partition) → receiver processes
the same command → ACKNOWLEDGE back to origin → ACKNOWLEDGED → FINISHED when
every target acked. CommandRedistributor (distribution/CommandRedistributor.java)
retries pending sends forever; receiver dedup keeps the retries idempotent.

The distribution key carries the origin partition in its high bits
(protocol keys), so the receiver knows where to send the ACKNOWLEDGE.
"""

from __future__ import annotations

from typing import Callable, Iterable

from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.engine.writers import Writers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import ValueType, command
from zeebe_tpu.protocol.intent import CommandDistributionIntent, Intent
from zeebe_tpu.protocol.keys import decode_partition_id

# retry cadence for pending distributions (reference: COMMAND_REDISTRIBUTION_INTERVAL,
# CommandRedistributor.java — 10s fixed interval with backoff multiplier)
REDISTRIBUTION_INTERVAL_MS = 10_000


class CommandDistributionBehavior:
    """Origin-side fan-out of a command to every other partition."""

    def __init__(self, state: EngineState, partition_count: int, sender,
                 clock_millis=None) -> None:
        self.state = state
        self.partition_count = partition_count
        self.sender = sender
        self.clock_millis = clock_millis or (lambda: 0)

    def other_partitions(self) -> list[int]:
        return [
            p for p in range(1, self.partition_count + 1)
            if p != self.state.partition_id
        ]

    def distribute(
        self,
        writers: Writers,
        distribution_key: int,
        value_type: ValueType,
        intent: Intent,
        value: dict,
        targets: Iterable[int] | None = None,
    ) -> bool:
        """Start distributing ``(value_type, intent, value)``; returns False when
        there is nobody to distribute to (single-partition deployments complete
        immediately — the caller writes its own terminal event)."""
        target_list = list(targets) if targets is not None else self.other_partitions()
        if not target_list:
            return False
        dist_value = {
            "partitionId": self.state.partition_id,
            "valueType": int(value_type),
            "intent": int(intent),
            "commandValue": dict(value),
        }
        writers.append_event(
            distribution_key, ValueType.COMMAND_DISTRIBUTION,
            CommandDistributionIntent.STARTED, dist_value,
        )
        for partition in target_list:
            writers.append_event(
                distribution_key, ValueType.COMMAND_DISTRIBUTION,
                CommandDistributionIntent.DISTRIBUTING,
                {**dist_value, "partitionId": partition},
            )
            self._send(writers, distribution_key, partition, value_type, intent, value)
        return True

    def _send(self, writers: Writers, distribution_key: int, partition: int,
              value_type: ValueType, intent: Intent, value: dict) -> None:
        rec = command(value_type, intent, dict(value), key=distribution_key)
        sender = self.sender

        def push() -> None:
            sender.send_command(partition, rec)

        writers.after_commit(push)

    # -- receiver side --------------------------------------------------------

    def is_distributed_command(self, cmd: LoggedRecord) -> bool:
        """A command whose key was minted on another partition arrived via
        distribution (reference: receiver dedups via the key's partition bits)."""
        key = cmd.record.key
        return key > 0 and decode_partition_id(key) != self.state.partition_id

    def was_received(self, distribution_key: int) -> bool:
        return self.state.distribution.was_received(distribution_key)

    def handle_distributed(self, cmd: LoggedRecord, writers: Writers,
                           on_first_receive: Callable[[], None]) -> None:
        """The whole receiver-side contract in one place: run the work exactly
        once per distribution key (dedup on retried sends), always ACKNOWLEDGE.
        Every distributed value type routes through this helper so none can
        forget the was_received check."""
        if not self.was_received(cmd.record.key):
            on_first_receive()
        self.acknowledge_after_commit(writers, cmd)

    def acknowledge_after_commit(self, writers: Writers, cmd: LoggedRecord) -> None:
        """Receiver: mark the distribution processed and ACKNOWLEDGE to origin."""
        distribution_key = cmd.record.key
        origin = decode_partition_id(distribution_key)
        writers.append_event(
            distribution_key, ValueType.COMMAND_DISTRIBUTION,
            CommandDistributionIntent.ACKNOWLEDGED,
            {"partitionId": self.state.partition_id, "valueType": int(cmd.record.value_type),
             "intent": int(cmd.record.intent), "commandValue": {}, "received": True,
             # processor-side clock baked into the event so replay purges the
             # dedup marker index identically (same pattern as timer dueDate)
             "receivedAt": self.clock_millis()},
        )
        ack = command(
            ValueType.COMMAND_DISTRIBUTION, CommandDistributionIntent.ACKNOWLEDGE,
            {"partitionId": self.state.partition_id},
            key=distribution_key,
        )
        sender = self.sender

        def push() -> None:
            sender.send_command(origin, ack)

        writers.after_commit(push)


class CommandDistributionAcknowledgeProcessor:
    """Origin: COMMAND_DISTRIBUTION ACKNOWLEDGE → ACKNOWLEDGED; FINISHED once
    every target partition acked; runs the per-value-type completion hook
    (e.g. Deployment FULLY_DISTRIBUTED)."""

    def __init__(self, state: EngineState) -> None:
        self.state = state
        # value_type(int) → hook(writers, distribution_key, stored_distribution)
        self.completion_hooks: dict[int, Callable[[Writers, int, dict], None]] = {}

    def on_finished(self, value_type: ValueType, hook: Callable[[Writers, int, dict], None]) -> None:
        self.completion_hooks[int(value_type)] = hook

    def process(self, cmd: LoggedRecord, writers: Writers) -> None:
        distribution_key = cmd.record.key
        partition = cmd.record.value.get("partitionId", -1)
        stored = self.state.distribution.get(distribution_key)
        if stored is None or not self.state.distribution.is_pending(distribution_key, partition):
            return  # duplicate ack after retry: already acknowledged
        writers.append_event(
            distribution_key, ValueType.COMMAND_DISTRIBUTION,
            CommandDistributionIntent.ACKNOWLEDGED,
            {"partitionId": partition, "valueType": stored["valueType"],
             "intent": stored["intent"], "commandValue": {}},
        )
        if self.state.distribution.none_pending(distribution_key):
            writers.append_event(
                distribution_key, ValueType.COMMAND_DISTRIBUTION,
                CommandDistributionIntent.FINISHED,
                {"partitionId": self.state.partition_id, "valueType": stored["valueType"],
                 "intent": stored["intent"], "commandValue": {}},
            )
            hook = self.completion_hooks.get(stored["valueType"])
            if hook is not None:
                hook(writers, distribution_key, stored)


class CommandRedistributor:
    """Periodic resend of every still-pending distribution (at-least-once;
    reference: distribution/CommandRedistributor.java — retries forever)."""

    def __init__(self, state: EngineState, sender, schedule_service, clock_millis) -> None:
        self.state = state
        self.sender = sender
        self.schedule = schedule_service
        self.clock_millis = clock_millis
        self._handle = None

    def reschedule(self) -> None:
        """Idempotent: an already-armed retry deadline is left in place so
        frequent pumps cannot starve the fixed retry interval."""
        if self._handle is not None:
            return
        with self.state.db.transaction():
            pending = self.state.distribution.has_any_pending()
        if pending:
            self._handle = self.schedule.run_at(
                self.clock_millis() + REDISTRIBUTION_INTERVAL_MS, self._resend_all
            )

    def _resend_all(self) -> list:
        self._handle = None
        with self.state.db.transaction():
            pending = [
                (key, partition, self.state.distribution.get(key))
                for key, partition in self.state.distribution.all_pending()
            ]
        for distribution_key, partition, stored in pending:
            if stored is None:
                continue
            value_type = ValueType(stored["valueType"])
            intent_cls = Intent.for_value_type(value_type)
            rec = command(
                value_type, intent_cls(stored["intent"]),
                dict(stored["commandValue"]), key=distribution_key,
            )
            self.sender.send_command(partition, rec)
        self.reschedule()
        return []
