"""Query service: point lookups against a partition's engine state.

Reference: engine/src/main/java/io/camunda/zeebe/engine/state/query/
StateQueryService.java — the QueryService handed to gateway interceptors
(QueryApiCfg): resolve the bpmnProcessId owning a process definition key, a
process instance key, or a job key, without going through the record stream.

Thread-safety: lookups use ``ZbDb.committed_get`` (committed-store point
reads that never touch the processing transaction slot), so any thread —
gateway interceptor, management endpoint — may query concurrently with the
partition's processing, like the reference's reads against a storage
snapshot."""

from __future__ import annotations

from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF


class QueryService:
    def __init__(self, db: ZbDb, state=None) -> None:
        # ``state`` accepted for interface symmetry with other partition
        # services; lookups go straight to the db's committed store
        self._db = db
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def _ensure_open(self) -> None:
        if self._closed:
            raise RuntimeError("query service is closed (partition transitioned)")

    def get_bpmn_process_id_for_process(self, process_definition_key: int) -> str | None:
        self._ensure_open()
        meta = self._db.committed_get(CF.PROCESS_CACHE, (process_definition_key,))
        return None if meta is None else meta["bpmnProcessId"]

    def get_bpmn_process_id_for_process_instance(self, process_instance_key: int) -> str | None:
        self._ensure_open()
        instance = self._db.committed_get(
            CF.ELEMENT_INSTANCE_KEY, (process_instance_key,))
        if instance is None:
            return None
        return instance["value"].get("bpmnProcessId")

    def get_bpmn_process_id_for_job(self, job_key: int) -> str | None:
        self._ensure_open()
        job = self._db.committed_get(CF.JOBS, (job_key,))
        return None if job is None else job.get("bpmnProcessId")
