"""Query service: point lookups against a partition's engine state.

Reference: engine/src/main/java/io/camunda/zeebe/engine/state/query/
StateQueryService.java — the QueryService handed to gateway interceptors
(QueryApiCfg): resolve the bpmnProcessId owning a process definition key, a
process instance key, or a job key, without going through the record stream.
"""

from __future__ import annotations

from zeebe_tpu.engine.engine_state import EngineState
from zeebe_tpu.state import ZbDb


class QueryService:
    def __init__(self, db: ZbDb, state: EngineState) -> None:
        self._db = db
        self._state = state
        self._closed = False

    def close(self) -> None:
        self._closed = True

    def _ensure_open(self) -> None:
        if self._closed:
            raise RuntimeError("query service is closed (partition transitioned)")

    def get_bpmn_process_id_for_process(self, process_definition_key: int) -> str | None:
        self._ensure_open()
        with self._db.transaction():
            meta = self._state.processes.get_by_key(process_definition_key)
        return None if meta is None else meta["bpmnProcessId"]

    def get_bpmn_process_id_for_process_instance(self, process_instance_key: int) -> str | None:
        self._ensure_open()
        with self._db.transaction():
            instance = self._state.element_instances.get(process_instance_key)
        if instance is None:
            return None
        return instance["value"].get("bpmnProcessId")

    def get_bpmn_process_id_for_job(self, job_key: int) -> str | None:
        self._ensure_open()
        with self._db.transaction():
            job = self._state.jobs.get(job_key)
        return None if job is None else job.get("bpmnProcessId")
