"""Engine writers: the append-and-apply seam.

Reference: engine/…/processing/streamprocessor/writers/Writers.java —
StateWriter (appending an event also applies it to state immediately,
StateWriter.java:11), TypedCommandWriter, TypedRejectionWriter,
TypedResponseWriter. Keeping "write event" and "apply event" in lock-step is
what guarantees replay equivalence.
"""

from __future__ import annotations

from typing import Any, Mapping

from zeebe_tpu.engine.appliers import EventAppliers
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import (
    Record,
    RejectionType,
    ValueType,
    command,
    event,
    rejection,
)
from zeebe_tpu.protocol.intent import Intent
from zeebe_tpu.stream import ProcessingResultBuilder


class Writers:
    def __init__(self, builder: ProcessingResultBuilder, appliers: EventAppliers) -> None:
        self._builder = builder
        self._appliers = appliers

    # -- StateWriter: append event + apply immediately ------------------------

    def append_event(
        self, key: int, value_type: ValueType, intent: Intent, value: Mapping[str, Any]
    ) -> Record:
        rec = event(value_type, intent, value, key=key)
        self._builder.append_record(rec)
        self._appliers.apply(rec)
        return rec

    # -- TypedCommandWriter ---------------------------------------------------

    def append_command(
        self, key: int, value_type: ValueType, intent: Intent, value: Mapping[str, Any]
    ) -> Record:
        rec = command(value_type, intent, value, key=key)
        self._builder.append_record(rec)
        return rec

    # -- TypedRejectionWriter -------------------------------------------------

    def append_rejection(
        self, cmd: LoggedRecord, rejection_type: RejectionType, reason: str
    ) -> Record:
        rec = rejection(cmd.record.replace(position=cmd.position), rejection_type, reason)
        self._builder.append_record(rec)
        return rec

    # -- TypedResponseWriter --------------------------------------------------

    def _stamp_response(self, record: Record, request_stream_id: int,
                        request_id: int) -> Record:
        """Stamp the request identity into the response record's FRAME (the
        reference does the same in RecordMetadata): the logged bytes then
        carry which request a reply answers, which is what lets the
        replicated dedupe table (state/request_dedupe.py) be materialized
        identically on processing and replay. Appliers never read the
        request fields, so applied state is unchanged; the follow-up entry
        is swapped in place so the stamped frame is what gets logged."""
        if (record.request_id == request_id
                and record.request_stream_id == request_stream_id):
            return record  # rejections arrive pre-stamped
        stamped = record.replace(request_stream_id=request_stream_id,
                                 request_id=request_id)
        for entry in self._builder.follow_ups:
            if entry.record is record:
                entry.record = stamped
                break
        return stamped

    def respond(self, cmd: LoggedRecord, record: Record) -> None:
        if cmd.record.request_id >= 0:
            stamped = self._stamp_response(
                record, cmd.record.request_stream_id, cmd.record.request_id)
            self._builder.with_response(
                stamped, cmd.record.request_stream_id, cmd.record.request_id
            )

    def respond_to(self, record: Record, request_stream_id: int, request_id: int) -> None:
        """Answer a parked request from an earlier command (await-result)."""
        if request_id >= 0:
            stamped = self._stamp_response(record, request_stream_id,
                                           request_id)
            self._builder.add_response(stamped, request_stream_id, request_id)

    def respond_rejection(self, cmd: LoggedRecord, rejection_type: RejectionType, reason: str) -> None:
        rec = self.append_rejection(cmd, rejection_type, reason)
        self.respond(cmd, rec)

    # -- SideEffectWriter: run after the transaction commits ------------------

    def after_commit(self, task) -> None:
        self._builder.append_post_commit_task(task)
