/* Native msgpack codec — the record-value hot path.
 *
 * C implementation of zeebe_tpu/protocol/msgpack.py (that module is the
 * specification; tests assert byte-equality between the two). The reference
 * keeps its record codec native for the same reason (zero-alloc MsgPackWriter/
 * MsgPackReader over Agrona buffers, msgpack-core/src/main/java/io/camunda/
 * zeebe/msgpack/spec/): every record append, replay, export, and transport
 * frame round-trips through it.
 *
 * Exposes packb(obj) -> bytes and unpackb(buffer) -> obj, raising the
 * exception class registered via set_error_class (MsgPackError) on malformed
 * input — same contract as the Python module.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

static PyObject *error_class = NULL; /* MsgPackError, set from Python */

static PyObject *codec_error(const char *fmt, ...)
{
    char buf[256];
    va_list ap;
    va_start(ap, fmt);
    vsnprintf(buf, sizeof buf, fmt, ap);
    va_end(ap);
    PyErr_SetString(error_class ? error_class : PyExc_ValueError, buf);
    return NULL;
}

/* ---------------------------------------------------------------- writer */

typedef struct {
    uint8_t *data;
    Py_ssize_t len;
    Py_ssize_t cap;
} Writer;

static int writer_grow(Writer *w, Py_ssize_t need)
{
    Py_ssize_t cap = w->cap ? w->cap : 256;
    while (cap < w->len + need)
        cap *= 2;
    uint8_t *p = PyMem_Realloc(w->data, cap);
    if (!p) {
        PyErr_NoMemory();
        return -1;
    }
    w->data = p;
    w->cap = cap;
    return 0;
}

static inline int put(Writer *w, const void *src, Py_ssize_t n)
{
    if (w->len + n > w->cap && writer_grow(w, n) < 0)
        return -1;
    memcpy(w->data + w->len, src, n);
    w->len += n;
    return 0;
}

static inline int put1(Writer *w, uint8_t b) { return put(w, &b, 1); }

static inline int put_be16(Writer *w, uint16_t v)
{
    uint8_t b[2] = {(uint8_t)(v >> 8), (uint8_t)v};
    return put(w, b, 2);
}

static inline int put_be32(Writer *w, uint32_t v)
{
    uint8_t b[4] = {(uint8_t)(v >> 24), (uint8_t)(v >> 16), (uint8_t)(v >> 8), (uint8_t)v};
    return put(w, b, 4);
}

static inline int put_be64(Writer *w, uint64_t v)
{
    uint8_t b[8];
    for (int i = 0; i < 8; i++)
        b[i] = (uint8_t)(v >> (56 - 8 * i));
    return put(w, b, 8);
}

static int pack_obj(Writer *w, PyObject *obj, int depth);
static int pack_ll(Writer *w, long long v);

static int pack_long(Writer *w, PyObject *obj)
{
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow > 0) {
        unsigned long long u = PyLong_AsUnsignedLongLong(obj);
        if (u == (unsigned long long)-1 && PyErr_Occurred()) {
            PyErr_Clear();
            codec_error("int too large");
            return -1;
        }
        return put1(w, 0xCF) < 0 || put_be64(w, u) < 0 ? -1 : 0;
    }
    if (overflow < 0) {
        codec_error("int too small");
        return -1;
    }
    if (v == -1 && PyErr_Occurred())
        return -1;
    return pack_ll(w, v);
}

static int pack_ll(Writer *w, long long v)
{
    if (v >= 0) {
        if (v < 0x80)
            return put1(w, (uint8_t)v);
        if (v < 0x100)
            return put1(w, 0xCC) < 0 || put1(w, (uint8_t)v) < 0 ? -1 : 0;
        if (v < 0x10000)
            return put1(w, 0xCD) < 0 || put_be16(w, (uint16_t)v) < 0 ? -1 : 0;
        if (v < 0x100000000LL)
            return put1(w, 0xCE) < 0 || put_be32(w, (uint32_t)v) < 0 ? -1 : 0;
        return put1(w, 0xCF) < 0 || put_be64(w, (uint64_t)v) < 0 ? -1 : 0;
    }
    if (v >= -32)
        return put1(w, (uint8_t)(v & 0xFF));
    if (v >= -0x80)
        return put1(w, 0xD0) < 0 || put1(w, (uint8_t)(int8_t)v) < 0 ? -1 : 0;
    if (v >= -0x8000)
        return put1(w, 0xD1) < 0 || put_be16(w, (uint16_t)(int16_t)v) < 0 ? -1 : 0;
    if (v >= -0x80000000LL)
        return put1(w, 0xD2) < 0 || put_be32(w, (uint32_t)(int32_t)v) < 0 ? -1 : 0;
    return put1(w, 0xD3) < 0 || put_be64(w, (uint64_t)v) < 0 ? -1 : 0;
}

static int pack_str(Writer *w, PyObject *obj)
{
    Py_ssize_t n;
    const char *raw = PyUnicode_AsUTF8AndSize(obj, &n);
    if (!raw)
        return -1;
    if (n < 32) {
        if (put1(w, (uint8_t)(0xA0 | n)) < 0)
            return -1;
    } else if (n < 0x100) {
        if (put1(w, 0xD9) < 0 || put1(w, (uint8_t)n) < 0)
            return -1;
    } else if (n < 0x10000) {
        if (put1(w, 0xDA) < 0 || put_be16(w, (uint16_t)n) < 0)
            return -1;
    } else {
        if (put1(w, 0xDB) < 0 || put_be32(w, (uint32_t)n) < 0)
            return -1;
    }
    return put(w, raw, n);
}

static int pack_bin(Writer *w, const uint8_t *raw, Py_ssize_t n)
{
    if (n < 0x100) {
        if (put1(w, 0xC4) < 0 || put1(w, (uint8_t)n) < 0)
            return -1;
    } else if (n < 0x10000) {
        if (put1(w, 0xC5) < 0 || put_be16(w, (uint16_t)n) < 0)
            return -1;
    } else {
        if (put1(w, 0xC6) < 0 || put_be32(w, (uint32_t)n) < 0)
            return -1;
    }
    return put(w, raw, n);
}

#define MAX_DEPTH 256

static int pack_obj(Writer *w, PyObject *obj, int depth)
{
    if (depth > MAX_DEPTH) {
        codec_error("msgpack nesting exceeds %d", MAX_DEPTH);
        return -1;
    }
    if (obj == Py_None)
        return put1(w, 0xC0);
    if (obj == Py_True)
        return put1(w, 0xC3);
    if (obj == Py_False)
        return put1(w, 0xC2);
    if (PyLong_Check(obj))
        return pack_long(w, obj);
    if (PyFloat_Check(obj)) {
        double d = PyFloat_AS_DOUBLE(obj);
        uint64_t bits;
        memcpy(&bits, &d, 8);
        return put1(w, 0xCB) < 0 || put_be64(w, bits) < 0 ? -1 : 0;
    }
    if (PyUnicode_Check(obj))
        return pack_str(w, obj);
    if (PyBytes_Check(obj))
        return pack_bin(w, (const uint8_t *)PyBytes_AS_STRING(obj), PyBytes_GET_SIZE(obj));
    if (PyByteArray_Check(obj))
        return pack_bin(w, (const uint8_t *)PyByteArray_AS_STRING(obj), PyByteArray_GET_SIZE(obj));
    if (PyMemoryView_Check(obj)) {
        Py_buffer *view = PyMemoryView_GET_BUFFER(obj);
        if (!PyBuffer_IsContiguous(view, 'C')) {
            codec_error("cannot msgpack non-contiguous memoryview");
            return -1;
        }
        return pack_bin(w, (const uint8_t *)view->buf, view->len);
    }
    if (PyList_Check(obj) || PyTuple_Check(obj)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        if (n < 16) {
            if (put1(w, (uint8_t)(0x90 | n)) < 0)
                return -1;
        } else if (n < 0x10000) {
            if (put1(w, 0xDC) < 0 || put_be16(w, (uint16_t)n) < 0)
                return -1;
        } else {
            if (put1(w, 0xDD) < 0 || put_be32(w, (uint32_t)n) < 0)
                return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++)
            if (pack_obj(w, PySequence_Fast_GET_ITEM(obj, i), depth + 1) < 0)
                return -1;
        return 0;
    }
    if (PyDict_Check(obj)) {
        Py_ssize_t n = PyDict_GET_SIZE(obj);
        if (n < 16) {
            if (put1(w, (uint8_t)(0x80 | n)) < 0)
                return -1;
        } else if (n < 0x10000) {
            if (put1(w, 0xDE) < 0 || put_be16(w, (uint16_t)n) < 0)
                return -1;
        } else {
            if (put1(w, 0xDF) < 0 || put_be32(w, (uint32_t)n) < 0)
                return -1;
        }
        PyObject *key, *value;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &key, &value)) {
            if (pack_obj(w, key, depth + 1) < 0)
                return -1;
            if (pack_obj(w, value, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    codec_error("cannot msgpack type %s", Py_TYPE(obj)->tp_name);
    return -1;
}

static PyObject *codec_packb(PyObject *self, PyObject *obj)
{
    Writer w = {NULL, 0, 0};
    if (pack_obj(&w, obj, 0) < 0) {
        PyMem_Free(w.data);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)w.data, w.len);
    PyMem_Free(w.data);
    return out;
}

/* ---------------------------------------------------------------- reader */

typedef struct {
    const uint8_t *data;
    Py_ssize_t len;
    Py_ssize_t pos;
} Reader;

static PyObject *read_obj(Reader *r, int depth);

static inline int take(Reader *r, Py_ssize_t n, const uint8_t **out)
{
    if (r->pos + n > r->len) {
        codec_error("truncated msgpack data");
        return -1;
    }
    *out = r->data + r->pos;
    r->pos += n;
    return 0;
}

static inline int read_be(Reader *r, int n, uint64_t *out)
{
    const uint8_t *p;
    if (take(r, n, &p) < 0)
        return -1;
    uint64_t v = 0;
    for (int i = 0; i < n; i++)
        v = (v << 8) | p[i];
    *out = v;
    return 0;
}

static PyObject *read_str(Reader *r, Py_ssize_t n)
{
    const uint8_t *p;
    if (take(r, n, &p) < 0)
        return NULL;
    PyObject *s = PyUnicode_DecodeUTF8((const char *)p, n, NULL);
    if (!s && PyErr_ExceptionMatches(PyExc_UnicodeDecodeError)) {
        PyErr_Clear();
        codec_error("malformed msgpack data: invalid utf-8");
    }
    return s;
}

static PyObject *read_bin(Reader *r, Py_ssize_t n)
{
    const uint8_t *p;
    if (take(r, n, &p) < 0)
        return NULL;
    return PyBytes_FromStringAndSize((const char *)p, n);
}

static PyObject *read_array(Reader *r, Py_ssize_t n, int depth)
{
    /* every element needs >= 1 byte: reject corrupt lengths before the
     * allocation so malformed frames raise MsgPackError, not MemoryError */
    if (n > r->len - r->pos)
        return codec_error("truncated msgpack data");
    PyObject *list = PyList_New(n);
    if (!list)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = read_obj(r, depth);
        if (!item) {
            Py_DECREF(list);
            return NULL;
        }
        PyList_SET_ITEM(list, i, item);
    }
    return list;
}

static PyObject *read_map(Reader *r, Py_ssize_t n, int depth)
{
    if (n > (r->len - r->pos) / 2) /* each entry needs >= 2 bytes */
        return codec_error("truncated msgpack data");
    PyObject *dict = PyDict_New();
    if (!dict)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *key = read_obj(r, depth);
        if (!key) {
            Py_DECREF(dict);
            return NULL;
        }
        PyObject *value = read_obj(r, depth);
        if (!value) {
            Py_DECREF(key);
            Py_DECREF(dict);
            return NULL;
        }
        int rc = PyDict_SetItem(dict, key, value);
        Py_DECREF(key);
        Py_DECREF(value);
        if (rc < 0) {
            Py_DECREF(dict);
            if (PyErr_ExceptionMatches(PyExc_TypeError)) { /* unhashable key */
                PyErr_Clear();
                return codec_error("malformed msgpack data: unhashable map key");
            }
            return NULL;
        }
    }
    return dict;
}

static PyObject *read_obj(Reader *r, int depth)
{
    const uint8_t *p;
    uint64_t u;
    /* depth = number of enclosing containers; checked at value-read entry to
     * mirror the pure-Python _Reader.read() exactly (a container at the limit
     * still decodes if it has no children) */
    if (depth > MAX_DEPTH)
        return codec_error("msgpack nesting exceeds %d", MAX_DEPTH);
    if (take(r, 1, &p) < 0)
        return NULL;
    uint8_t b = *p;
    if (b < 0x80)
        return PyLong_FromLong(b);
    if (b >= 0xE0)
        return PyLong_FromLong((long)b - 0x100);
    if (b <= 0x8F)
        return read_map(r, b & 0x0F, depth + 1);
    if (b <= 0x9F)
        return read_array(r, b & 0x0F, depth + 1);
    if (b <= 0xBF)
        return read_str(r, b & 0x1F);
    switch (b) {
    case 0xC0:
        Py_RETURN_NONE;
    case 0xC2:
        Py_RETURN_FALSE;
    case 0xC3:
        Py_RETURN_TRUE;
    case 0xC4:
        if (read_be(r, 1, &u) < 0)
            return NULL;
        return read_bin(r, (Py_ssize_t)u);
    case 0xC5:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return read_bin(r, (Py_ssize_t)u);
    case 0xC6:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return read_bin(r, (Py_ssize_t)u);
    case 0xCA: {
        if (read_be(r, 4, &u) < 0)
            return NULL;
        uint32_t bits = (uint32_t)u;
        float f;
        memcpy(&f, &bits, 4);
        return PyFloat_FromDouble((double)f);
    }
    case 0xCB: {
        if (read_be(r, 8, &u) < 0)
            return NULL;
        double d;
        memcpy(&d, &u, 8);
        return PyFloat_FromDouble(d);
    }
    case 0xCC:
        if (read_be(r, 1, &u) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(u);
    case 0xCD:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(u);
    case 0xCE:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(u);
    case 0xCF:
        if (read_be(r, 8, &u) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(u);
    case 0xD0:
        if (read_be(r, 1, &u) < 0)
            return NULL;
        return PyLong_FromLong((int8_t)u);
    case 0xD1:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return PyLong_FromLong((int16_t)u);
    case 0xD2:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return PyLong_FromLong((int32_t)u);
    case 0xD3:
        if (read_be(r, 8, &u) < 0)
            return NULL;
        return PyLong_FromLongLong((int64_t)u);
    case 0xD9:
        if (read_be(r, 1, &u) < 0)
            return NULL;
        return read_str(r, (Py_ssize_t)u);
    case 0xDA:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return read_str(r, (Py_ssize_t)u);
    case 0xDB:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return read_str(r, (Py_ssize_t)u);
    case 0xDC:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return read_array(r, (Py_ssize_t)u, depth + 1);
    case 0xDD:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return read_array(r, (Py_ssize_t)u, depth + 1);
    case 0xDE:
        if (read_be(r, 2, &u) < 0)
            return NULL;
        return read_map(r, (Py_ssize_t)u, depth + 1);
    case 0xDF:
        if (read_be(r, 4, &u) < 0)
            return NULL;
        return read_map(r, (Py_ssize_t)u, depth + 1);
    default:
        return codec_error("unsupported msgpack byte 0x%02x", b);
    }
}

static PyObject *codec_unpackb(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Reader r = {(const uint8_t *)view.buf, view.len, 0};
    PyObject *obj = read_obj(&r, 0);
    if (obj && r.pos != r.len) {
        Py_DECREF(obj);
        obj = codec_error("trailing bytes after msgpack value: %zd", r.len - r.pos);
    }
    PyBuffer_Release(&view);
    return obj;
}

static PyObject *codec_set_error_class(PyObject *self, PyObject *cls)
{
    Py_XINCREF(cls);
    Py_XDECREF(error_class);
    error_class = cls;
    Py_RETURN_NONE;
}

/* Record wire frame (protocol/record.py _HEADER, little endian):
 *   u8 recordType | u8 valueType | u8 intent | u8 rejectionType
 *   i64 key | i64 sourceRecordPosition | i64 timestamp
 *   i32 requestStreamId | i64 requestId | i64 operationReference
 *   u16 rejectionReasonLen | reason utf-8 | u32 valueLen | value msgpack
 * decode_record_frame(data) -> 12-tuple mirroring that order with the
 * reason as str and the value as the decoded msgpack object — one C call
 * replaces struct.unpack_from + two slices + a separate unpackb on the
 * log-scan hot path. */
#define FRAME_HEADER_SIZE (4 + 8 * 3 + 4 + 8 * 2 + 2)

static int64_t rd_i64(const uint8_t *p) { int64_t v; memcpy(&v, p, 8); return v; }
static int32_t rd_i32(const uint8_t *p) { int32_t v; memcpy(&v, p, 4); return v; }

static PyObject *codec_decode_record_frame(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    const uint8_t *p = (const uint8_t *)view.buf;
    Py_ssize_t len = view.len;
    PyObject *out = NULL, *reason = NULL, *value = NULL;
    if (len < FRAME_HEADER_SIZE) {
        codec_error("record frame truncated: %zd bytes", len);
        goto done;
    }
    unsigned record_type = p[0], value_type = p[1], intent = p[2], rejection = p[3];
    int64_t key = rd_i64(p + 4);
    int64_t source_pos = rd_i64(p + 12);
    int64_t timestamp = rd_i64(p + 20);
    int32_t request_stream_id = rd_i32(p + 28);
    int64_t request_id = rd_i64(p + 32);
    int64_t operation_reference = rd_i64(p + 40);
    unsigned reason_len = (unsigned)p[48] | ((unsigned)p[49] << 8);
    Py_ssize_t off = FRAME_HEADER_SIZE;
    if (off + (Py_ssize_t)reason_len + 4 > len) {
        codec_error("record frame truncated in reason/value length");
        goto done;
    }
    reason = PyUnicode_DecodeUTF8((const char *)p + off, reason_len, NULL);
    if (!reason)
        goto done;
    off += reason_len;
    uint32_t value_len = (uint32_t)p[off] | ((uint32_t)p[off + 1] << 8)
        | ((uint32_t)p[off + 2] << 16) | ((uint32_t)p[off + 3] << 24);
    off += 4;
    if (off + (Py_ssize_t)value_len != len) {
        codec_error("record frame length mismatch: header says %zd, got %zd",
                    off + (Py_ssize_t)value_len, len);
        goto done;
    }
    Reader r = {p + off, (Py_ssize_t)value_len, 0};
    value = read_obj(&r, 0);
    if (!value)
        goto done;
    if (r.pos != r.len) {
        codec_error("trailing bytes after record value: %zd", r.len - r.pos);
        goto done;
    }
    out = PyTuple_New(12);
    if (!out)
        goto done;
    {
        PyObject *items[12];
        items[0] = PyLong_FromUnsignedLong(record_type);
        items[1] = PyLong_FromUnsignedLong(value_type);
        items[2] = PyLong_FromUnsignedLong(intent);
        items[3] = PyLong_FromUnsignedLong(rejection);
        items[4] = PyLong_FromLongLong(key);
        items[5] = PyLong_FromLongLong(source_pos);
        items[6] = PyLong_FromLongLong(timestamp);
        items[7] = PyLong_FromLong(request_stream_id);
        items[8] = PyLong_FromLongLong(request_id);
        items[9] = PyLong_FromLongLong(operation_reference);
        items[10] = reason;
        items[11] = value;
        for (int i = 0; i < 12; i++) {
            if (!items[i]) { /* an int alloc failed: free the rest */
                for (int j = 0; j < 12; j++)
                    if (j != 10 && j != 11)
                        Py_XDECREF(items[j]);
                Py_CLEAR(out);
                goto done;
            }
        }
        for (int i = 0; i < 12; i++)
            PyTuple_SET_ITEM(out, i, items[i]);
        /* the tuple now owns reason/value */
        reason = NULL;
        value = NULL;
    }
done:
    Py_XDECREF(reason);
    Py_XDECREF(value);
    PyBuffer_Release(&view);
    return out;
}

static void wr_i64(uint8_t *p, int64_t v) { memcpy(p, &v, 8); }
static void wr_i32(uint8_t *p, int32_t v) { memcpy(p, &v, 4); }

/* encode_record_frame(record_type, value_type, intent, rejection_type,
 *     key, source_position, timestamp, request_stream_id, request_id,
 *     operation_reference, reason, value) -> (frame, value_body)
 * One-pass encode mirror of decode_record_frame above. protocol/record.py
 * Record.encode is the specification (tests assert byte-equality): fixed
 * little-endian header, rejection reason truncated to u16 bytes on a
 * codepoint boundary, u32 body length, msgpack body. The body bytes are
 * returned separately so the append path can seed its decode cache
 * without re-packing the value. */
static PyObject *codec_encode_record_frame(PyObject *self, PyObject *args)
{
    int record_type, value_type, intent, rejection, request_stream_id;
    long long key, source_pos, timestamp, request_id, operation_reference;
    PyObject *reason_obj, *value;
    if (!PyArg_ParseTuple(args, "iiiiLLLiLLUO",
                          &record_type, &value_type, &intent, &rejection,
                          &key, &source_pos, &timestamp, &request_stream_id,
                          &request_id, &operation_reference,
                          &reason_obj, &value))
        return NULL;
    if ((unsigned)record_type > 0xFF || (unsigned)value_type > 0xFF
        || (unsigned)intent > 0xFF || (unsigned)rejection > 0xFF)
        return codec_error("record header byte field out of range");
    Py_ssize_t rlen;
    const char *reason = PyUnicode_AsUTF8AndSize(reason_obj, &rlen);
    if (!reason)
        return NULL;
    if (rlen > 0xFFFF) {
        /* the wire field is u16; truncate on a codepoint boundary so an
         * oversized error message can never poison the append path (same
         * continuation/lead-byte walk as Record.encode) */
        rlen = 0xFFFF;
        while (rlen && ((unsigned char)reason[rlen - 1] & 0xC0) == 0x80)
            rlen--;
        if (rlen && (unsigned char)reason[rlen - 1] >= 0xC0)
            rlen--;
    }
    uint8_t hdr[FRAME_HEADER_SIZE];
    hdr[0] = (uint8_t)record_type;
    hdr[1] = (uint8_t)value_type;
    hdr[2] = (uint8_t)intent;
    hdr[3] = (uint8_t)rejection;
    wr_i64(hdr + 4, key);
    wr_i64(hdr + 12, source_pos);
    wr_i64(hdr + 20, timestamp);
    wr_i32(hdr + 28, request_stream_id);
    wr_i64(hdr + 32, request_id);
    wr_i64(hdr + 40, operation_reference);
    hdr[48] = (uint8_t)(rlen & 0xFF);
    hdr[49] = (uint8_t)(rlen >> 8);
    Writer w = {NULL, 0, 0};
    static const uint8_t zero4[4] = {0, 0, 0, 0};
    if (put(&w, hdr, FRAME_HEADER_SIZE) < 0 || put(&w, reason, rlen) < 0
        || put(&w, zero4, 4) < 0)
        goto fail;
    Py_ssize_t body_off = w.len;
    if (pack_obj(&w, value, 0) < 0)
        goto fail;
    Py_ssize_t body_len = w.len - body_off;
    if (body_len > 0xFFFFFFFFLL) {
        codec_error("record value too large: %zd bytes", body_len);
        goto fail;
    }
    wr_i32(w.data + body_off - 4, (int32_t)(uint32_t)body_len);
    {
        PyObject *frame = PyBytes_FromStringAndSize((const char *)w.data, w.len);
        PyObject *body = PyBytes_FromStringAndSize(
            (const char *)w.data + body_off, body_len);
        PyMem_Free(w.data);
        if (!frame || !body) {
            Py_XDECREF(frame);
            Py_XDECREF(body);
            return NULL;
        }
        return Py_BuildValue("(NN)", frame, body);
    }
fail:
    PyMem_Free(w.data);
    return NULL;
}

/* Sequenced-batch header scan (logstreams/log_stream.py framing):
 *   batch header:  u32 count | i64 sourcePosition | u64 timestamp
 *   per entry:     u8 processed | i64 position | u32 recordLen | frame
 * scan_batch_headers(payload) -> (source_position, timestamp,
 *   [(processed, position, record_type, value_type, intent, key,
 *     frame_off, frame_len), ...])
 * Only the fixed frame prefix is touched — rejection reason and msgpack
 * value stay raw bytes, so a filtering scan (job discovery, command scan,
 * export filters) pays nothing for records it skips. */
#define BATCH_HEADER_SIZE (4 + 8 + 8)
#define ENTRY_HEADER_SIZE (1 + 8 + 4)

/* shared worker: want_rt/want_vt/want_intent of -1 match anything (the
 * unfiltered entry point passes -1,-1,-1 and preallocates the list) */
static PyObject *scan_batch_headers_impl(PyObject *arg, int want_rt,
                                         int want_vt, int want_intent)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    const uint8_t *p = (const uint8_t *)view.buf;
    Py_ssize_t len = view.len;
    int filtered = want_rt >= 0 || want_vt >= 0 || want_intent >= 0;
    PyObject *out = NULL, *records = NULL;
    if (len < BATCH_HEADER_SIZE) {
        codec_error("batch payload truncated: %zd bytes", len);
        goto done;
    }
    uint32_t count = (uint32_t)rd_i32(p);
    int64_t source_position = rd_i64(p + 4);
    int64_t timestamp = rd_i64(p + 12);
    /* a corrupted count must not drive a huge allocation: every entry needs
     * at least its header, so this bound holds for any valid payload */
    if ((Py_ssize_t)count > (len - BATCH_HEADER_SIZE) / ENTRY_HEADER_SIZE) {
        codec_error("batch count %u impossible for %zd-byte payload", count, len);
        goto done;
    }
    records = filtered ? PyList_New(0) : PyList_New((Py_ssize_t)count);
    if (!records)
        goto done;
    Py_ssize_t off = BATCH_HEADER_SIZE;
    for (uint32_t i = 0; i < count; i++) {
        if (off + ENTRY_HEADER_SIZE > len) {
            codec_error("batch entry %u truncated", i);
            goto done;
        }
        unsigned processed = p[off];
        int64_t position = rd_i64(p + off + 1);
        uint32_t rec_len = (uint32_t)rd_i32(p + off + 9);
        off += ENTRY_HEADER_SIZE;
        if (off + (Py_ssize_t)rec_len > len || rec_len < FRAME_HEADER_SIZE) {
            codec_error("batch record %u truncated", i);
            goto done;
        }
        const uint8_t *f = p + off;
        if ((want_rt < 0 || (int)f[0] == want_rt)
            && (want_vt < 0 || (int)f[1] == want_vt)
            && (want_intent < 0 || (int)f[2] == want_intent)) {
            PyObject *tup = Py_BuildValue(
                "(iLiiiLnn)", (int)processed, (long long)position,
                (int)f[0], (int)f[1], (int)f[2], (long long)rd_i64(f + 4),
                (Py_ssize_t)off, (Py_ssize_t)rec_len);
            if (!tup)
                goto done;
            if (filtered) {
                int rc = PyList_Append(records, tup);
                Py_DECREF(tup);
                if (rc < 0)
                    goto done;
            } else {
                PyList_SET_ITEM(records, (Py_ssize_t)i, tup);
            }
        }
        off += rec_len;
    }
    if (off != len) {
        codec_error("trailing bytes after batch: %zd", len - off);
        goto done;
    }
    out = Py_BuildValue("(LLO)", (long long)source_position,
                        (long long)timestamp, records);
done:
    Py_XDECREF(records);
    PyBuffer_Release(&view);
    return out;
}

static PyObject *codec_scan_batch_headers(PyObject *self, PyObject *arg)
{
    return scan_batch_headers_impl(arg, -1, -1, -1);
}

/* ------------------------------------------------------------------------
 * Fingerprint packer (spec: kernel_backend._fingerprint's pure-Python walk).
 *
 * pack_fingerprint(docs, roles, fp_fields) -> (bytes, fp_values)
 *   roles:     dict int -> str tag (keys known at admission)
 *   fp_fields: set of dict-key names whose large-int values are extracted
 * Two passes: collect large ints pinned at non-whitelisted positions, then
 * emit msgpack with role markers ["\x00r", tag], extraction markers
 * ["\x00f", ordinal], and "\x00s" string escaping — byte-identical to
 * packb(norm(docs)) from the Python implementation. */

typedef struct {
    PyObject *roles;      /* borrowed: dict int -> str */
    PyObject *fp_fields;  /* borrowed: set/frozenset of str */
    PyObject *pinned;     /* owned: set of ints */
    PyObject *fp_ordinal; /* owned: dict int -> int */
    PyObject *fp_values;  /* owned: list of ints */
    PyObject *min_obj;    /* owned: 2^32 */
    PyObject *neg_min_obj; /* owned: -(2^32) */
} FpCtx;

static int fp_large(FpCtx *c, PyObject *obj, int *large)
{
    int r = PyObject_RichCompareBool(obj, c->min_obj, Py_GE);
    if (r < 0)
        return -1;
    *large = r;
    return 0;
}

static int fp_field_match(FpCtx *c, PyObject *key)
{
    if (!PyUnicode_CheckExact(key))
        return 0;
    return PySet_Contains(c->fp_fields, key);
}

static int fp_scan(FpCtx *c, PyObject *obj, int in_fp_field, int depth)
{
    if (depth > MAX_DEPTH) {
        codec_error("fingerprint nesting exceeds %d", MAX_DEPTH);
        return -1;
    }
    if (PyLong_CheckExact(obj)) {
        int large;
        if (fp_large(c, obj, &large) < 0)
            return -1;
        if (large) {
            if (!in_fp_field) {
                int in_roles = PyDict_Contains(c->roles, obj);
                if (in_roles < 0)
                    return -1;
                if (!in_roles && PySet_Add(c->pinned, obj) < 0)
                    return -1;
            }
        } else {
            /* large negatives are never roles and never extracted — the
             * emit pass copies them unchanged everywhere, so they are
             * fingerprint-pinned (sound template constants) */
            int neg = PyObject_RichCompareBool(obj, c->neg_min_obj, Py_LE);
            if (neg < 0)
                return -1;
            if (neg && PySet_Add(c->pinned, obj) < 0)
                return -1;
        }
        return 0;
    }
    if (PyDict_CheckExact(obj)) {
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (fp_scan(c, k, 0, depth + 1) < 0)
                return -1;
            int fp = fp_field_match(c, k);
            if (fp < 0 || fp_scan(c, v, fp, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        for (Py_ssize_t i = 0; i < n; i++)
            if (fp_scan(c, PySequence_Fast_GET_ITEM(obj, i), 0, depth + 1) < 0)
                return -1;
        return 0;
    }
    return 0;
}

static const uint8_t FP_ROLE_MARK[4] = {0x92, 0xA2, 0x00, 'r'};
static const uint8_t FP_EXTRACT_MARK[4] = {0x92, 0xA2, 0x00, 'f'};

static int fp_emit(FpCtx *c, Writer *w, PyObject *obj, int in_fp_field, int depth)
{
    if (depth > MAX_DEPTH) {
        codec_error("fingerprint nesting exceeds %d", MAX_DEPTH);
        return -1;
    }
    if (PyLong_CheckExact(obj)) {
        int large;
        if (fp_large(c, obj, &large) < 0)
            return -1;
        if (large) {
            PyObject *tag = PyDict_GetItemWithError(c->roles, obj);
            if (!tag && PyErr_Occurred())
                return -1;
            if (tag) {
                if (put(w, FP_ROLE_MARK, 4) < 0)
                    return -1;
                return pack_str(w, tag);
            }
            if (in_fp_field) {
                int pinned = PySet_Contains(c->pinned, obj);
                if (pinned < 0)
                    return -1;
                if (!pinned) {
                    PyObject *ord = PyDict_GetItemWithError(c->fp_ordinal, obj);
                    long long ordv;
                    if (!ord && PyErr_Occurred())
                        return -1;
                    if (ord) {
                        ordv = PyLong_AsLongLong(ord);
                    } else {
                        ordv = PyList_GET_SIZE(c->fp_values);
                        PyObject *o = PyLong_FromLongLong(ordv);
                        if (!o)
                            return -1;
                        int rc = PyDict_SetItem(c->fp_ordinal, obj, o);
                        if (rc == 0)
                            rc = PyList_Append(c->fp_values, obj);
                        Py_DECREF(o);
                        if (rc < 0)
                            return -1;
                    }
                    if (put(w, FP_EXTRACT_MARK, 4) < 0)
                        return -1;
                    return pack_ll(w, ordv);
                }
            }
        }
        return pack_long(w, obj);
    }
    if (PyUnicode_CheckExact(obj)) {
        Py_ssize_t n;
        const char *raw = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!raw)
            return -1;
        if (n > 0 && raw[0] == 0) {
            /* "\x00"-prefixed user string: escape as "\x00s" + original so
             * it can never forge a role/extract marker */
            Py_ssize_t total = n + 2;
            if (total < 32) {
                if (put1(w, (uint8_t)(0xA0 | total)) < 0)
                    return -1;
            } else if (total < 0x100) {
                if (put1(w, 0xD9) < 0 || put1(w, (uint8_t)total) < 0)
                    return -1;
            } else if (total < 0x10000) {
                if (put1(w, 0xDA) < 0 || put_be16(w, (uint16_t)total) < 0)
                    return -1;
            } else {
                if (put1(w, 0xDB) < 0 || put_be32(w, (uint32_t)total) < 0)
                    return -1;
            }
            static const uint8_t esc[2] = {0x00, 's'};
            return put(w, esc, 2) < 0 || put(w, raw, n) < 0 ? -1 : 0;
        }
        return pack_str(w, obj);
    }
    if (PyDict_CheckExact(obj)) {
        Py_ssize_t n = PyDict_GET_SIZE(obj);
        if (n < 16) {
            if (put1(w, (uint8_t)(0x80 | n)) < 0)
                return -1;
        } else if (n < 0x10000) {
            if (put1(w, 0xDE) < 0 || put_be16(w, (uint16_t)n) < 0)
                return -1;
        } else {
            if (put1(w, 0xDF) < 0 || put_be32(w, (uint32_t)n) < 0)
                return -1;
        }
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (fp_emit(c, w, k, 0, depth + 1) < 0)
                return -1;
            int fp = fp_field_match(c, k);
            if (fp < 0 || fp_emit(c, w, v, fp, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        if (n < 16) {
            if (put1(w, (uint8_t)(0x90 | n)) < 0)
                return -1;
        } else if (n < 0x10000) {
            if (put1(w, 0xDC) < 0 || put_be16(w, (uint16_t)n) < 0)
                return -1;
        } else {
            if (put1(w, 0xDD) < 0 || put_be32(w, (uint32_t)n) < 0)
                return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++)
            if (fp_emit(c, w, PySequence_Fast_GET_ITEM(obj, i), 0, depth + 1) < 0)
                return -1;
        return 0;
    }
    return pack_obj(w, obj, depth);
}

static PyObject *codec_pack_fingerprint(PyObject *self, PyObject *args)
{
    PyObject *docs, *roles, *fp_fields;
    if (!PyArg_ParseTuple(args, "OOO", &docs, &roles, &fp_fields))
        return NULL;
    if (!PyDict_Check(roles) || !PyAnySet_Check(fp_fields)) {
        PyErr_SetString(PyExc_TypeError, "roles must be dict, fp_fields a set");
        return NULL;
    }
    FpCtx c = {roles, fp_fields, NULL, NULL, NULL, NULL, NULL};
    PyObject *out = NULL, *payload = NULL;
    Writer w = {NULL, 0, 0};
    c.pinned = PySet_New(NULL);
    c.fp_ordinal = PyDict_New();
    c.fp_values = PyList_New(0);
    c.min_obj = PyLong_FromUnsignedLongLong(1ULL << 32);
    c.neg_min_obj = PyLong_FromLongLong(-(1LL << 32));
    if (!c.pinned || !c.fp_ordinal || !c.fp_values || !c.min_obj
        || !c.neg_min_obj)
        goto done;
    if (fp_scan(&c, docs, 0, 0) < 0)
        goto done;
    if (fp_emit(&c, &w, docs, 0, 0) < 0)
        goto done;
    payload = PyBytes_FromStringAndSize((const char *)w.data, w.len);
    if (!payload)
        goto done;
    out = PyTuple_Pack(3, payload, c.fp_values, c.pinned);
done:
    PyMem_Free(w.data);
    Py_XDECREF(payload);
    Py_XDECREF(c.pinned);
    Py_XDECREF(c.fp_ordinal);
    Py_XDECREF(c.fp_values);
    Py_XDECREF(c.min_obj);
    Py_XDECREF(c.neg_min_obj);
    return out;
}

/* ------------------------------------------------------------------------
 * Bulk patch applier (burst-template instantiation fast path).
 *
 * apply_patches(buf, plan, values) -> None
 *   buf:    bytearray to patch in place
 *   plan:   bytes of little-endian entries {u32 offset; u8 fmt; u8 value_idx}
 *           fmt 0 = i64 LE, 1 = i32 LE, 2 = u64 BE (masked),
 *           fmt 3 = u64 BE with the state-key sign flip (v ^ 2^63)
 *   values: sequence of ints, indexed by value_idx */
#define PATCH_ENTRY_SIZE 6

static PyObject *codec_apply_patches(PyObject *self, PyObject *args)
{
    PyObject *buf, *plan, *values;
    if (!PyArg_ParseTuple(args, "OOO", &buf, &plan, &values))
        return NULL;
    if (!PyByteArray_CheckExact(buf) || !PyBytes_CheckExact(plan)
        || !PyList_CheckExact(values)) {
        PyErr_SetString(PyExc_TypeError,
                        "apply_patches(bytearray, bytes, list) expected");
        return NULL;
    }
    uint8_t *b = (uint8_t *)PyByteArray_AS_STRING(buf);
    Py_ssize_t blen = PyByteArray_GET_SIZE(buf);
    const uint8_t *p = (const uint8_t *)PyBytes_AS_STRING(plan);
    Py_ssize_t plen = PyBytes_GET_SIZE(plan);
    if (plen % PATCH_ENTRY_SIZE) {
        PyErr_SetString(PyExc_ValueError, "malformed patch plan");
        return NULL;
    }
    Py_ssize_t nvals = PyList_GET_SIZE(values);
    int64_t cache[256];
    uint8_t cached[256] = {0};
    for (Py_ssize_t e = 0; e < plen; e += PATCH_ENTRY_SIZE) {
        uint32_t off = (uint32_t)p[e] | ((uint32_t)p[e + 1] << 8)
            | ((uint32_t)p[e + 2] << 16) | ((uint32_t)p[e + 3] << 24);
        uint8_t fmt = p[e + 4];
        uint8_t idx = p[e + 5];
        if (idx >= nvals) {
            PyErr_SetString(PyExc_IndexError, "patch value index out of range");
            return NULL;
        }
        int64_t v;
        if (cached[idx]) {
            v = cache[idx];
        } else {
            int overflow = 0;
            v = PyLong_AsLongLongAndOverflow(PyList_GET_ITEM(values, idx), &overflow);
            if (v == -1 && PyErr_Occurred())
                return NULL;
            if (overflow) {
                PyErr_SetString(PyExc_OverflowError, "patch value out of i64 range");
                return NULL;
            }
            cache[idx] = v;
            cached[idx] = 1;
        }
        Py_ssize_t width = (fmt == 1) ? 4 : 8;
        if ((Py_ssize_t)off + width > blen) {
            PyErr_SetString(PyExc_ValueError, "patch offset out of range");
            return NULL;
        }
        switch (fmt) {
        case 0:
            memcpy(b + off, &v, 8);
            break;
        case 1: {
            int32_t v32 = (int32_t)v;
            memcpy(b + off, &v32, 4);
            break;
        }
        case 2:
        case 3: {
            uint64_t u = (uint64_t)v;
            if (fmt == 3)
                u ^= 0x8000000000000000ULL;
            for (int i = 0; i < 8; i++)
                b[off + i] = (uint8_t)(u >> (56 - 8 * i));
            break;
        }
        default:
            PyErr_SetString(PyExc_ValueError, "unknown patch format");
            return NULL;
        }
    }
    Py_RETURN_NONE;
}

/* stamp_batch(buf, pos_offsets, ts_offsets, first_position, timestamp):
 * write first_position+i LE at pos_offsets[i] and timestamp LE at every
 * ts_offset — the only two unknowns of a pre-serialized burst batch,
 * patched under the append lock. */
static PyObject *codec_stamp_batch(PyObject *self, PyObject *args)
{
    PyObject *buf, *pos_offsets, *ts_offsets;
    long long first_position, timestamp;
    if (!PyArg_ParseTuple(args, "OOOLL", &buf, &pos_offsets, &ts_offsets,
                          &first_position, &timestamp))
        return NULL;
    if (!PyByteArray_CheckExact(buf) || !PyList_CheckExact(pos_offsets)
        || !PyList_CheckExact(ts_offsets)) {
        PyErr_SetString(PyExc_TypeError,
                        "stamp_batch(bytearray, list, list, int, int) expected");
        return NULL;
    }
    uint8_t *b = (uint8_t *)PyByteArray_AS_STRING(buf);
    Py_ssize_t blen = PyByteArray_GET_SIZE(buf);
    Py_ssize_t n = PyList_GET_SIZE(pos_offsets);
    for (Py_ssize_t i = 0; i < n; i++) {
        long long off = PyLong_AsLongLong(PyList_GET_ITEM(pos_offsets, i));
        if (off == -1 && PyErr_Occurred())
            return NULL;
        if (off < 0 || off + 8 > blen) {
            PyErr_SetString(PyExc_ValueError, "position offset out of range");
            return NULL;
        }
        int64_t v = first_position + i;
        memcpy(b + off, &v, 8);
    }
    n = PyList_GET_SIZE(ts_offsets);
    for (Py_ssize_t i = 0; i < n; i++) {
        long long off = PyLong_AsLongLong(PyList_GET_ITEM(ts_offsets, i));
        if (off == -1 && PyErr_Occurred())
            return NULL;
        if (off < 0 || off + 8 > blen) {
            PyErr_SetString(PyExc_ValueError, "timestamp offset out of range");
            return NULL;
        }
        int64_t v = timestamp;
        memcpy(b + off, &v, 8);
    }
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------------
 * scan_batch_headers_filtered(payload, record_type, value_type, intent):
 * scan_batch_headers that keeps only entries matching the given header ints
 * (intent < 0 matches any intent) — a discovery sweep over N records with k
 * matches allocates k tuples, not N. Same framing as scan_batch_headers. */
static PyObject *codec_scan_batch_headers_filtered(PyObject *self, PyObject *args)
{
    PyObject *arg;
    int want_rt, want_vt, want_intent;
    if (!PyArg_ParseTuple(args, "Oiii", &arg, &want_rt, &want_vt, &want_intent))
        return NULL;
    return scan_batch_headers_impl(arg, want_rt, want_vt, want_intent);
}

/* ------------------------------------------------------------------------
 * apply_state_plan: a burst template's state write-set applied natively,
 * with Transaction.put/delete semantics (state/db.py): a key not yet in the
 * overlay dict is insorted into the sorted-keys list; the dict then maps
 * key -> fresh value object (puts) or the _DELETED sentinel (deletes).
 *
 * apply_state_plan(plan, values, writes, sorted_writes, deleted)
 *   plan: list of (op:int 0=del/1=put, key:bytes, key_patches:bytes,
 *                  value_bytes:bytes|None, value_patches:bytes)
 *     patches are packed (u32 LE offset, u8 role index), 5 bytes each;
 *     key patches write BE u64 sign-flipped (db key int encoding), value
 *     patches write BE u64 raw (msgpack uint64 body) — matching
 *     StateOp.build_value / BurstTemplate.apply_state exactly.
 *   values: list of resolved role ints (one resolve per distinct role)
 * Every put unpacks a FRESH value object (the engine mutates state values
 * in place, so object sharing across instantiations would corrupt state). */
#define STATE_PATCH_SIZE 5

static int apply_packed_patches(uint8_t *buf, Py_ssize_t blen,
                                const uint8_t *patches, Py_ssize_t plen,
                                const int64_t *vals, Py_ssize_t nvals,
                                int sign_flip)
{
    if (plen % STATE_PATCH_SIZE) {
        PyErr_SetString(PyExc_ValueError, "malformed state patch plan");
        return -1;
    }
    for (Py_ssize_t e = 0; e < plen; e += STATE_PATCH_SIZE) {
        uint32_t off = (uint32_t)patches[e] | ((uint32_t)patches[e + 1] << 8)
            | ((uint32_t)patches[e + 2] << 16) | ((uint32_t)patches[e + 3] << 24);
        uint8_t idx = patches[e + 4];
        if (idx >= nvals || (Py_ssize_t)off + 8 > blen) {
            PyErr_SetString(PyExc_ValueError, "state patch out of range");
            return -1;
        }
        uint64_t u = (uint64_t)vals[idx];
        if (sign_flip)
            u ^= 0x8000000000000000ULL;
        for (int i = 0; i < 8; i++)
            buf[off + i] = (uint8_t)(u >> (56 - 8 * i));
    }
    return 0;
}

/* bisect_left over an ascending list of bytes keys (memcmp fast path,
 * RichCompare fallback for non-bytes items); -1 on comparison error */
static Py_ssize_t bisect_left_bytes(PyObject *list, PyObject *key)
{
    Py_ssize_t lo = 0, hi = PyList_GET_SIZE(list);
    const char *kbuf = PyBytes_CheckExact(key) ? PyBytes_AS_STRING(key) : NULL;
    Py_ssize_t klen = kbuf ? PyBytes_GET_SIZE(key) : 0;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        PyObject *item = PyList_GET_ITEM(list, mid);
        int lt;
        if (kbuf && PyBytes_CheckExact(item)) {
            Py_ssize_t ilen = PyBytes_GET_SIZE(item);
            Py_ssize_t n = ilen < klen ? ilen : klen;
            int c = memcmp(PyBytes_AS_STRING(item), kbuf, (size_t)n);
            lt = c < 0 || (c == 0 && ilen < klen);
        } else {
            lt = PyObject_RichCompareBool(item, key, Py_LT);
            if (lt < 0)
                return -1;
        }
        if (lt)
            lo = mid + 1;
        else
            hi = mid;
    }
    return lo;
}

/* ascending-bytes insort (Transaction._sorted_writes invariant) */
static int insort_bytes(PyObject *list, PyObject *key)
{
    Py_ssize_t lo = bisect_left_bytes(list, key);
    if (lo < 0)
        return -1;
    return PyList_Insert(list, lo, key);
}

/* commit_overlay(writes, data, sorted_keys, deleted):
 * Transaction.commit's apply loop, natively — for each (key, val) in the
 * overlay dict: a deleted-sentinel val removes the key from the committed
 * dict and its sorted-keys list; any other val upserts (insort on first
 * insert). Mirrors ZbDb._put_committed/_delete_committed exactly. */
static PyObject *codec_commit_overlay(PyObject *self, PyObject *args)
{
    PyObject *writes, *data, *sorted_keys, *deleted;
    if (!PyArg_ParseTuple(args, "OOOO", &writes, &data, &sorted_keys, &deleted))
        return NULL;
    if (!PyDict_CheckExact(writes) || !PyDict_CheckExact(data)
        || !PyList_CheckExact(sorted_keys)) {
        PyErr_SetString(PyExc_TypeError,
                        "commit_overlay(dict, dict, list, obj) expected");
        return NULL;
    }
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(writes, &pos, &key, &val)) {
        int present = PyDict_Contains(data, key);
        if (present < 0)
            return NULL;
        if (val == deleted) {
            if (!present)
                continue;
            if (PyDict_DelItem(data, key) < 0)
                return NULL;
            /* locate the key in the sorted list (bisect_left + equality) */
            Py_ssize_t lo = bisect_left_bytes(sorted_keys, key);
            if (lo < 0)
                return NULL;
            if (lo < PyList_GET_SIZE(sorted_keys)) {
                int eq = PyObject_RichCompareBool(
                    PyList_GET_ITEM(sorted_keys, lo), key, Py_EQ);
                if (eq < 0)
                    return NULL;
                if (eq && PySequence_DelItem(sorted_keys, lo) < 0)
                    return NULL;
            }
        } else {
            if (!present && insort_bytes(sorted_keys, key) < 0)
                return NULL;
            if (PyDict_SetItem(data, key, val) < 0)
                return NULL;
        }
    }
    Py_RETURN_NONE;
}

/* iterate_snapshot(sorted_keys, data, prefix, sorted_writes, writes,
 *                  deleted, reads_cache):
 * Transaction.iterate's merge, natively — one pass building the ordered
 * committed-union-overlay snapshot list for a prefix range. Committed
 * values go through the same defensive-copy-and-cache discipline as
 * Transaction._committed_read (dict/list values are shallow-copied once
 * per transaction via reads_cache); overlay values are returned verbatim
 * with deleted-sentinel entries dropped. Both inputs are sorted, so the
 * output merges in order with no final sort. */
static PyObject *codec_iterate_snapshot(PyObject *self, PyObject *args)
{
    PyObject *sorted_keys, *data, *prefix, *sorted_writes, *writes, *deleted,
        *reads;
    if (!PyArg_ParseTuple(args, "OOOOOOO", &sorted_keys, &data, &prefix,
                          &sorted_writes, &writes, &deleted, &reads))
        return NULL;
    if (!PyList_CheckExact(sorted_keys) || !PyDict_CheckExact(data)
        || !PyBytes_CheckExact(prefix) || !PyList_CheckExact(sorted_writes)
        || !PyDict_CheckExact(writes) || !PyDict_CheckExact(reads)) {
        PyErr_SetString(PyExc_TypeError,
                        "iterate_snapshot(list, dict, bytes, list, dict, obj, "
                        "dict) expected");
        return NULL;
    }
    /* range bounds: [prefix, successor(prefix)) on both sorted lists */
    Py_ssize_t plen = PyBytes_GET_SIZE(prefix);
    PyObject *end = NULL; /* NULL = unbounded */
    {
        const char *p = PyBytes_AS_STRING(prefix);
        Py_ssize_t n = plen;
        while (n > 0 && (unsigned char)p[n - 1] == 0xFF)
            n--;
        if (n > 0) {
            end = PyBytes_FromStringAndSize(p, n);
            if (!end)
                return NULL;
            ((unsigned char *)PyBytes_AS_STRING(end))[n - 1]++;
        }
    }
    Py_ssize_t clo = bisect_left_bytes(sorted_keys, prefix);
    Py_ssize_t chi = end ? bisect_left_bytes(sorted_keys, end)
                         : PyList_GET_SIZE(sorted_keys);
    Py_ssize_t wlo = bisect_left_bytes(sorted_writes, prefix);
    Py_ssize_t whi = end ? bisect_left_bytes(sorted_writes, end)
                         : PyList_GET_SIZE(sorted_writes);
    Py_XDECREF(end);
    if (clo < 0 || chi < 0 || wlo < 0 || whi < 0)
        return NULL;
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    Py_ssize_t ci = clo, wi = wlo;
    while (ci < chi || wi < whi) {
        PyObject *key;
        PyObject *val;
        int from_overlay;
        if (wi >= whi) {
            from_overlay = 0;
            key = PyList_GET_ITEM(sorted_keys, ci);
            ci++;
        } else if (ci >= chi) {
            from_overlay = 1;
            key = PyList_GET_ITEM(sorted_writes, wi);
            wi++;
        } else {
            PyObject *ck = PyList_GET_ITEM(sorted_keys, ci);
            PyObject *wk = PyList_GET_ITEM(sorted_writes, wi);
            int cmp;
            if (PyBytes_CheckExact(ck) && PyBytes_CheckExact(wk)) {
                Py_ssize_t cl = PyBytes_GET_SIZE(ck), wl = PyBytes_GET_SIZE(wk);
                Py_ssize_t n = cl < wl ? cl : wl;
                int c = memcmp(PyBytes_AS_STRING(ck), PyBytes_AS_STRING(wk),
                               (size_t)n);
                cmp = c != 0 ? c : (cl < wl ? -1 : (cl > wl ? 1 : 0));
            } else {
                int lt = PyObject_RichCompareBool(ck, wk, Py_LT);
                if (lt < 0)
                    goto fail;
                cmp = lt ? -1 : 1;
                if (!lt) {
                    int eq = PyObject_RichCompareBool(ck, wk, Py_EQ);
                    if (eq < 0)
                        goto fail;
                    if (eq)
                        cmp = 0;
                }
            }
            if (cmp < 0) {
                from_overlay = 0;
                key = ck;
                ci++;
            } else if (cmp > 0) {
                from_overlay = 1;
                key = wk;
                wi++;
            } else {
                /* overlay supersedes the committed entry */
                from_overlay = 1;
                key = wk;
                ci++;
                wi++;
            }
        }
        if (from_overlay) {
            val = PyDict_GetItemWithError(writes, key);
            if (!val) {
                if (PyErr_Occurred())
                    goto fail;
                continue; /* raced away — cannot happen on these dicts */
            }
            if (val == deleted)
                continue;
            Py_INCREF(val);
        } else {
            /* _committed_read: copy-and-cache containers, scalars verbatim */
            val = PyDict_GetItemWithError(reads, key);
            if (!val && PyErr_Occurred())
                goto fail;
            if (!val) {
                val = PyDict_GetItemWithError(data, key);
                if (!val) {
                    if (PyErr_Occurred())
                        goto fail;
                    continue; /* deleted between index and dict — unreachable */
                }
                if (PyDict_CheckExact(val)) {
                    val = PyDict_Copy(val);
                    if (!val || PyDict_SetItem(reads, key, val) < 0)
                        goto fail_val;
                } else if (PyList_CheckExact(val)) {
                    val = PyList_GetSlice(val, 0, PyList_GET_SIZE(val));
                    if (!val || PyDict_SetItem(reads, key, val) < 0)
                        goto fail_val;
                } else {
                    Py_INCREF(val);
                }
            } else {
                Py_INCREF(val);
            }
        }
        {
            PyObject *pair = PyTuple_Pack(2, key, val);
            Py_DECREF(val);
            if (!pair)
                goto fail;
            if (PyList_Append(out, pair) < 0) {
                Py_DECREF(pair);
                goto fail;
            }
            Py_DECREF(pair);
        }
        continue;
    fail_val:
        Py_XDECREF(val);
        goto fail;
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyObject *codec_apply_state_plan(PyObject *self, PyObject *args)
{
    PyObject *plan, *values, *writes, *sorted_writes, *deleted;
    if (!PyArg_ParseTuple(args, "OOOOO", &plan, &values, &writes,
                          &sorted_writes, &deleted))
        return NULL;
    if (!PyList_CheckExact(plan) || !PyList_CheckExact(values)
        || !PyDict_CheckExact(writes) || !PyList_CheckExact(sorted_writes)) {
        PyErr_SetString(PyExc_TypeError,
                        "apply_state_plan(list, list, dict, list, obj) expected");
        return NULL;
    }
    Py_ssize_t nvals = PyList_GET_SIZE(values);
    if (nvals > 256) {
        PyErr_SetString(PyExc_ValueError, "too many roles in state plan");
        return NULL;
    }
    int64_t vals[256];
    for (Py_ssize_t i = 0; i < nvals; i++) {
        int overflow = 0;
        vals[i] = PyLong_AsLongLongAndOverflow(PyList_GET_ITEM(values, i), &overflow);
        if (vals[i] == -1 && PyErr_Occurred())
            return NULL;
        if (overflow) {
            PyErr_SetString(PyExc_OverflowError, "role value out of i64 range");
            return NULL;
        }
    }
    Py_ssize_t nops = PyList_GET_SIZE(plan);
    for (Py_ssize_t i = 0; i < nops; i++) {
        PyObject *op = PyList_GET_ITEM(plan, i);
        if (!PyTuple_CheckExact(op) || PyTuple_GET_SIZE(op) != 5) {
            PyErr_SetString(PyExc_TypeError, "malformed state plan op");
            return NULL;
        }
        long code = PyLong_AsLong(PyTuple_GET_ITEM(op, 0));
        PyObject *key_tmpl = PyTuple_GET_ITEM(op, 1);
        PyObject *kp = PyTuple_GET_ITEM(op, 2);
        PyObject *vb = PyTuple_GET_ITEM(op, 3);
        PyObject *vp = PyTuple_GET_ITEM(op, 4);
        if ((code == -1 && PyErr_Occurred()) || !PyBytes_CheckExact(key_tmpl)
            || !PyBytes_CheckExact(kp) || !PyBytes_CheckExact(vp)) {
            PyErr_SetString(PyExc_TypeError, "malformed state plan op");
            return NULL;
        }
        /* key: reuse the template bytes when patch-free (immutable) */
        PyObject *key;
        Py_ssize_t kplen = PyBytes_GET_SIZE(kp);
        if (kplen == 0) {
            key = key_tmpl;
            Py_INCREF(key);
        } else {
            key = PyBytes_FromStringAndSize(PyBytes_AS_STRING(key_tmpl),
                                            PyBytes_GET_SIZE(key_tmpl));
            if (!key)
                return NULL;
            if (apply_packed_patches((uint8_t *)PyBytes_AS_STRING(key),
                                     PyBytes_GET_SIZE(key),
                                     (const uint8_t *)PyBytes_AS_STRING(kp),
                                     kplen, vals, nvals, 1) < 0) {
                Py_DECREF(key);
                return NULL;
            }
        }
        /* value: fresh unpack per op (deletes store the sentinel) */
        PyObject *value;
        if (code == 0) {
            value = deleted;
            Py_INCREF(value);
        } else {
            if (!PyBytes_CheckExact(vb)) {
                Py_DECREF(key);
                PyErr_SetString(PyExc_TypeError, "state plan put without value bytes");
                return NULL;
            }
            Py_ssize_t vlen = PyBytes_GET_SIZE(vb);
            Py_ssize_t vplen = PyBytes_GET_SIZE(vp);
            if (vplen == 0) {
                Reader r = {(const uint8_t *)PyBytes_AS_STRING(vb), vlen, 0};
                value = read_obj(&r, 0);
                if (value && r.pos != r.len) {
                    Py_DECREF(value);
                    value = codec_error("trailing bytes in state value");
                }
            } else {
                uint8_t stack_buf[512];
                uint8_t *vbuf = vlen <= (Py_ssize_t)sizeof stack_buf
                    ? stack_buf : PyMem_Malloc(vlen);
                if (!vbuf) {
                    Py_DECREF(key);
                    return PyErr_NoMemory();
                }
                memcpy(vbuf, PyBytes_AS_STRING(vb), vlen);
                if (apply_packed_patches(vbuf, vlen,
                                         (const uint8_t *)PyBytes_AS_STRING(vp),
                                         vplen, vals, nvals, 0) < 0) {
                    if (vbuf != stack_buf)
                        PyMem_Free(vbuf);
                    Py_DECREF(key);
                    return NULL;
                }
                Reader r = {vbuf, vlen, 0};
                value = read_obj(&r, 0);
                if (value && r.pos != r.len) {
                    Py_DECREF(value);
                    value = codec_error("trailing bytes in state value");
                }
                if (vbuf != stack_buf)
                    PyMem_Free(vbuf);
            }
            if (!value) {
                Py_DECREF(key);
                return NULL;
            }
        }
        /* Transaction.put/delete: insort on first write of the key */
        int present = PyDict_Contains(writes, key);
        if (present < 0 || (present == 0 && insort_bytes(sorted_writes, key) < 0)
            || PyDict_SetItem(writes, key, value) < 0) {
            Py_DECREF(key);
            Py_DECREF(value);
            return NULL;
        }
        Py_DECREF(key);
        Py_DECREF(value);
    }
    Py_RETURN_NONE;
}

/* -- durable-state base-segment indexing ---------------------------------- */

static uint32_t crc32_tab[256];
static uint32_t crc32_tab8[8][256]; /* slice-by-8 lanes; lane 0 == crc32_tab */
static int crc32_ready = 0;

static void crc32_build(void)
{
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc32_tab[i] = c;
        crc32_tab8[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int k = 1; k < 8; k++)
            crc32_tab8[k][i] =
                (crc32_tab8[k - 1][i] >> 8) ^ crc32_tab[crc32_tab8[k - 1][i] & 0xFF];
    crc32_ready = 1;
}

/* advance the RAW crc register (pre/post inversion is the caller's business)
 * over n bytes — slice-by-8 body, bytewise tail. Little-endian word loads,
 * the same host assumption the frame readers (rd_i64 &c.) already make. */
static uint32_t crc32_update(uint32_t c, const unsigned char *p, Py_ssize_t n)
{
    while (n >= 8) {
        uint32_t lo, hi;
        memcpy(&lo, p, 4);
        memcpy(&hi, p + 4, 4);
        c ^= lo;
        c = crc32_tab8[7][c & 0xFF] ^ crc32_tab8[6][(c >> 8) & 0xFF]
            ^ crc32_tab8[5][(c >> 16) & 0xFF] ^ crc32_tab8[4][c >> 24]
            ^ crc32_tab8[3][hi & 0xFF] ^ crc32_tab8[2][(hi >> 8) & 0xFF]
            ^ crc32_tab8[1][(hi >> 16) & 0xFF] ^ crc32_tab8[0][hi >> 24];
        p += 8;
        n -= 8;
    }
    while (n--) {
        c = crc32_tab[(c ^ *p++) & 0xFF] ^ (c >> 8);
    }
    return c;
}

static uint32_t crc32_buf(const unsigned char *p, Py_ssize_t n)
{
    return crc32_update(0xFFFFFFFFu, p, n) ^ 0xFFFFFFFFu;
}

/* index_base_segment(view, data) -> [keys in file order]
 * Scan a durable base segment (state/durable.py layout: per entry a <HII>
 * header = key len, value len, key crc — then key bytes, then the cold
 * slice [value crc u32 | value bytes]). Key crcs verify eagerly; values
 * install as raw zero-copy memoryview slices of the caller's mmap view
 * (crc-checked lazily at resolution, _resolve_view). A torn or corrupt
 * entry truncates the scan (journal discipline). File order == sorted. */
static PyObject *codec_index_base_segment(PyObject *self, PyObject *args)
{
    PyObject *view, *data;
    if (!PyArg_ParseTuple(args, "OO", &view, &data))
        return NULL;
    if (!PyDict_CheckExact(data)) {
        PyErr_SetString(PyExc_TypeError, "data must be a dict");
        return NULL;
    }
    Py_buffer buf;
    if (PyObject_GetBuffer(view, &buf, PyBUF_SIMPLE) < 0)
        return NULL;
    if (!crc32_ready)
        crc32_build();
    const unsigned char *p = (const unsigned char *)buf.buf;
    Py_ssize_t n = buf.len;
    PyObject *keys = PyList_New(0);
    if (!keys) {
        PyBuffer_Release(&buf);
        return NULL;
    }
    Py_ssize_t off = 0;
    while (off + 10 <= n) {
        uint16_t klen = (uint16_t)(p[off] | (p[off + 1] << 8));
        uint32_t vlen = (uint32_t)p[off + 2] | ((uint32_t)p[off + 3] << 8)
            | ((uint32_t)p[off + 4] << 16) | ((uint32_t)p[off + 5] << 24);
        uint32_t kcrc = (uint32_t)p[off + 6] | ((uint32_t)p[off + 7] << 8)
            | ((uint32_t)p[off + 8] << 16) | ((uint32_t)p[off + 9] << 24);
        Py_ssize_t kstart = off + 10;
        Py_ssize_t vstart = kstart + klen; /* [vcrc|value] slice start */
        Py_ssize_t vend = vstart + 4 + (Py_ssize_t)vlen;
        if (vend > n)
            break;
        if (crc32_buf(p + kstart, klen) != kcrc)
            break;
        PyObject *key = PyBytes_FromStringAndSize((const char *)p + kstart, klen);
        if (!key)
            goto fail;
        /* zero-copy cold slice narrowed to [vcrc|value]. obj stays NULL:
         * the view does NOT pin the mmap — DurableZbDb owns the map for
         * the db's lifetime (self._maps) and drops _data before unmapping,
         * and cold views never escape the db (every read path resolves
         * them to fresh objects). This keeps indexing to ONE allocation
         * per value. */
        Py_buffer vb = buf;
        vb.obj = NULL;
        vb.buf = (char *)buf.buf + vstart;
        vb.len = vend - vstart;
        PyObject *vview = PyMemoryView_FromBuffer(&vb);
        if (!vview) {
            Py_DECREF(key);
            goto fail;
        }
        if (PyDict_SetItem(data, key, vview) < 0
            || PyList_Append(keys, key) < 0) {
            Py_DECREF(vview);
            Py_DECREF(key);
            goto fail;
        }
        Py_DECREF(vview);
        Py_DECREF(key);
        off = vend;
    }
    PyBuffer_Release(&buf);
    return keys;
fail:
    PyBuffer_Release(&buf);
    Py_DECREF(keys);
    return NULL;
}

/* encode_key(prefix, parts) -> bytes
 * Order-preserving state-key encoding (spec: state/db.py encode_key /
 * _encode_part — that Python implementation is the contract; tests assert
 * byte-equality). prefix is the 2-byte column-family prefix; parts is a
 * tuple of int | str | bytes. */
static PyObject *codec_encode_key(PyObject *self, PyObject *args)
{
    PyObject *prefix, *parts;
    if (!PyArg_ParseTuple(args, "SO!", &prefix, &PyTuple_Type, &parts))
        return NULL;
    unsigned char stack_buf[256];
    Py_ssize_t cap = sizeof(stack_buf);
    unsigned char *buf = stack_buf;
    Py_ssize_t n = PyBytes_GET_SIZE(prefix);
    if (n > cap)
        return PyErr_Format(PyExc_ValueError, "oversized cf prefix");
    memcpy(buf, PyBytes_AS_STRING(prefix), n);
    PyObject *heap = NULL; /* switch-over for long keys */
    Py_ssize_t count = PyTuple_GET_SIZE(parts);
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *part = PyTuple_GET_ITEM(parts, i);
        const void *src = NULL;
        Py_ssize_t need, slen = 0;
        uint64_t flipped = 0;
        int kind;
        if (PyBool_Check(part)) {
            Py_XDECREF(heap);
            PyErr_SetString(PyExc_TypeError,
                            "bool key parts are ambiguous; use int 0/1");
            return NULL;
        } else if (PyLong_Check(part)) {
            /* wrap to 64 bits like the Python spec's `& 0xFFFF…` mask */
            uint64_t v = (uint64_t)PyLong_AsUnsignedLongLongMask(part);
            if (v == (uint64_t)-1 && PyErr_Occurred()) {
                Py_XDECREF(heap);
                return NULL;
            }
            flipped = v ^ 0x8000000000000000ULL;
            kind = 1;
            need = 9;
        } else if (PyUnicode_Check(part)) {
            src = PyUnicode_AsUTF8AndSize(part, &slen);
            if (!src) {
                Py_XDECREF(heap);
                return NULL;
            }
            if (memchr(src, 0, (size_t)slen)) {
                Py_XDECREF(heap);
                PyErr_SetString(PyExc_ValueError, "NUL byte in string key part");
                return NULL;
            }
            kind = 2;
            need = slen + 2;
        } else if (PyBytes_Check(part)) {
            src = PyBytes_AS_STRING(part);
            slen = PyBytes_GET_SIZE(part);
            kind = 3;
            need = slen + 9;
        } else {
            Py_XDECREF(heap);
            return PyErr_Format(PyExc_TypeError,
                                "unsupported key part type %.100s",
                                Py_TYPE(part)->tp_name);
        }
        if (n + need > cap) {
            Py_ssize_t newcap = (cap * 2 > n + need + 64) ? cap * 2 : n + need + 64;
            PyObject *nh = PyBytes_FromStringAndSize(NULL, newcap);
            if (!nh) {
                Py_XDECREF(heap);
                return NULL;
            }
            memcpy(PyBytes_AS_STRING(nh), buf, (size_t)n);
            Py_XDECREF(heap);
            heap = nh;
            buf = (unsigned char *)PyBytes_AS_STRING(nh);
            cap = newcap;
        }
        if (kind == 1) {
            buf[n++] = 0x01;
            for (int b = 7; b >= 0; b--)
                buf[n++] = (unsigned char)(flipped >> (8 * b));
        } else if (kind == 2) {
            buf[n++] = 0x02;
            memcpy(buf + n, src, (size_t)slen);
            n += slen;
            buf[n++] = 0x00;
        } else {
            buf[n++] = 0x03;
            uint64_t ulen = (uint64_t)slen;
            for (int b = 7; b >= 0; b--)
                buf[n++] = (unsigned char)(ulen >> (8 * b));
            memcpy(buf + n, src, (size_t)slen);
            n += slen;
        }
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)buf, n);
    Py_XDECREF(heap);
    return out;
}

/* -- journal frame fast path ---------------------------------------------- */

/* journal/journal.py _checksum is the specification: one continuous crc32
 * register over pack("<Qq", index, asqn) then the payload — the exact
 * zlib.crc32(data, zlib.crc32(head)) continuation semantics. */
static uint32_t journal_crc(uint64_t index, int64_t asqn,
                            const unsigned char *data, Py_ssize_t n)
{
    unsigned char head[16];
    memcpy(head, &index, 8);
    memcpy(head + 8, &asqn, 8);
    uint32_t c = crc32_update(0xFFFFFFFFu, head, 16);
    return crc32_update(c, data, n) ^ 0xFFFFFFFFu;
}

/* journal_checksum(index, asqn, data) -> int — the scan/verify side. */
static PyObject *codec_journal_checksum(PyObject *self, PyObject *args)
{
    unsigned long long index;
    long long asqn;
    Py_buffer data;
    if (!PyArg_ParseTuple(args, "KLy*", &index, &asqn, &data))
        return NULL;
    if (!crc32_ready)
        crc32_build();
    uint32_t crc = journal_crc(index, asqn,
                               (const unsigned char *)data.buf, data.len);
    PyBuffer_Release(&data);
    return PyLong_FromUnsignedLong(crc);
}

/* journal_frame(index, asqn, data) -> bytes — the append side: one
 * complete frame (<IIQq> header = payload length, checksum, index, asqn —
 * then the payload) in a single allocation and a single crc pass,
 * replacing two zlib.crc32 calls, two struct packs, and a bytes concat
 * per append. Accepts any contiguous buffer (the prepatched burst path
 * hands the writer's bytearray straight through). */
static PyObject *codec_journal_frame(PyObject *self, PyObject *args)
{
    unsigned long long index;
    long long asqn;
    Py_buffer data;
    if (!PyArg_ParseTuple(args, "KLy*", &index, &asqn, &data))
        return NULL;
    if (!crc32_ready)
        crc32_build();
    const unsigned char *p = (const unsigned char *)data.buf;
    Py_ssize_t n = data.len;
    if (n > 0xFFFFFFFFLL) {
        PyBuffer_Release(&data);
        return codec_error("journal payload too large: %zd bytes", n);
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, 24 + n);
    if (!out) {
        PyBuffer_Release(&data);
        return NULL;
    }
    unsigned char *q = (unsigned char *)PyBytes_AS_STRING(out);
    uint32_t length = (uint32_t)n;
    uint32_t crc = journal_crc(index, asqn, p, n);
    int64_t sq = asqn;
    memcpy(q, &length, 4);
    memcpy(q + 4, &crc, 4);
    memcpy(q + 8, &index, 8);
    memcpy(q + 16, &sq, 8);
    memcpy(q + 24, p, n);
    PyBuffer_Release(&data);
    return out;
}

static PyMethodDef codec_methods[] = {
    {"encode_key", codec_encode_key, METH_VARARGS,
     "Order-preserving state-key encoding (spec: state/db.py encode_key)."},
    {"index_base_segment", codec_index_base_segment, METH_VARARGS,
     "Index a durable-state base segment: keys eager, values as lazy cold slices."},
    {"stamp_batch", codec_stamp_batch, METH_VARARGS,
     "Stamp record positions and the batch timestamp into a pre-serialized burst."},
    {"pack_fingerprint", codec_pack_fingerprint, METH_VARARGS,
     "Role-normalizing fingerprint packer: (docs, roles, fp_fields) -> "
     "(bytes, fp_values, pinned_ints)."},
    {"apply_patches", codec_apply_patches, METH_VARARGS,
     "Apply a compiled patch plan to a bytearray in place."},
    {"packb", codec_packb, METH_O, "Serialize an object to msgpack bytes."},
    {"unpackb", codec_unpackb, METH_O, "Deserialize one msgpack value (consumes all bytes)."},
    {"decode_record_frame", codec_decode_record_frame, METH_O,
     "Parse one record wire frame into a 12-tuple (header fields, reason, value)."},
    {"encode_record_frame", codec_encode_record_frame, METH_VARARGS,
     "Serialize one record wire frame; returns (frame, value_body)."},
    {"journal_frame", codec_journal_frame, METH_VARARGS,
     "Build one complete journal frame (header + payload) in a single pass."},
    {"journal_checksum", codec_journal_checksum, METH_VARARGS,
     "Journal frame checksum over (index, asqn, payload) — zlib.crc32 parity."},
    {"scan_batch_headers", codec_scan_batch_headers, METH_O,
     "Parse a sequenced batch into per-record header tuples without decoding values."},
    {"scan_batch_headers_filtered", codec_scan_batch_headers_filtered, METH_VARARGS,
     "scan_batch_headers keeping only entries matching (record_type, value_type, intent)."},
    {"apply_state_plan", codec_apply_state_plan, METH_VARARGS,
     "Apply a compiled burst-template state plan to a transaction overlay."},
    {"iterate_snapshot", codec_iterate_snapshot, METH_VARARGS,
     "Transaction.iterate committed-union-overlay merge in one native pass"},
    {"commit_overlay", codec_commit_overlay, METH_VARARGS,
     "Apply a transaction overlay dict to the committed store (dict + sorted keys)."},
    {"set_error_class", codec_set_error_class, METH_O, "Register the exception class raised on malformed input."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef codec_module = {
    PyModuleDef_HEAD_INIT, "_zb_codec", "Native msgpack codec for zeebe_tpu records.", -1, codec_methods,
};

PyMODINIT_FUNC PyInit__zb_codec(void)
{
    return PyModule_Create(&codec_module);
}
