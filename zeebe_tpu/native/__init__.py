"""Native (C) hot-path components, built on demand with gcc.

The reference gets its native muscle from dependencies (RocksDB JNI, Netty,
Agrona, SBE codegen — SURVEY.md §intro); here the hot paths that stay on the
host CPU are C extensions compiled from sources in this directory the first
time they are needed and cached next to them. Every consumer falls back to
its pure-Python implementation when the toolchain or build is unavailable, so
nothing in the framework *requires* the native path — it is a performance
floor, not a correctness dependency.

Current components:
- ``_zb_codec`` (codec.c): msgpack record codec (spec: protocol/msgpack.py).
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
import threading

logger = logging.getLogger("zeebe_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_CACHE: dict[str, object | None] = {}


def _build_and_load(module_name: str, source: str):
    src = os.path.join(_DIR, source)
    tag = sysconfig.get_config_var("SOABI") or "so"
    out = os.path.join(_DIR, f"{module_name}.{tag}.so")
    if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
        include = sysconfig.get_paths()["include"]
        # compile to a per-pid temp path and rename into place: rename is
        # atomic, so concurrent processes racing the build can never dlopen a
        # half-written .so (they either see the old complete one or the new
        # complete one)
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [
            os.environ.get("CC", "gcc"), "-O2", "-shared", "-fPIC",
            f"-I{include}", src, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    spec = importlib.util.spec_from_file_location(module_name, out)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def load(module_name: str, source: str):
    """Build (if stale) and import a native module; None when unavailable.

    Set ZEEBE_TPU_NO_NATIVE=1 to force the pure-Python fallbacks (used by the
    parity tests to exercise both paths)."""
    if os.environ.get("ZEEBE_TPU_NO_NATIVE"):
        return None
    with _LOCK:
        if module_name in _CACHE:
            return _CACHE[module_name]
        try:
            module = _build_and_load(module_name, source)
        except Exception as exc:  # noqa: BLE001 — any build/load failure → fallback
            logger.warning("native %s unavailable (%s); using pure-Python fallback",
                           module_name, exc)
            module = None
        _CACHE[module_name] = module
        return module


def load_codec():
    return load("_zb_codec", "codec.c")


def codec_fn(name: str):
    """A named function from the codec module, or None when the native
    build is unavailable or predates the function (stale .so)."""
    codec = load_codec()
    return getattr(codec, name, None) if codec is not None else None
