"""Append-only segmented journal with checksummed framing.

The durable log under Raft and the log stream (reference: journal/src/main/java/io/
camunda/zeebe/journal/file/SegmentedJournal.java:34, SegmentedJournalWriter,
SegmentsManager, SparseJournalIndex, record/SBESerializer.java,
util/ChecksumGenerator.java, JournalMetaStore.java).

Design (host-side, file-per-segment):
- A journal is a directory of fixed-capacity segment files ``<name>-<id>.log``
  plus a ``meta`` file holding the last-flushed index.
- Each segment starts with a fixed header (magic, version, segment id, first
  index); records are framed as
  ``u32 length | u32 crc32c | u64 index | i64 asqn | data``.
- ``asqn`` (application sequence number) carries the record *position* assigned
  by the sequencer, enabling ``seek_to_asqn`` during recovery — exactly the
  reference's asqn contract (SegmentedJournal's JournalRecord.asqn).
- A sparse in-memory index (every Nth record) accelerates seeks.
- Corruption: a bad checksum or truncated frame on open truncates the journal at
  the last valid record (the reference's CorruptedJournalException/FrameUtil
  handling — data after a crash-torn write is discarded, consistent with Raft
  semantics where unflushed suffix entries were never acknowledged).

The hot append path is deliberately simple buffered-write + explicit flush so it
can later be swapped for the C++ implementation without contract changes.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import struct
import zlib
from pathlib import Path
from typing import Callable, Iterator

from zeebe_tpu.observability.tracer import get_tracer as _get_tracer
from zeebe_tpu.utils import storage_io
from zeebe_tpu.utils.metrics import REGISTRY as _REGISTRY

# group-flush tracing (singleton mutated in place; one enabled-check per
# flush when tracing is off)
_TRACER = _get_tracer()

logger = logging.getLogger("zeebe_tpu.journal")

# journal metrics (reference names: journal/ JournalMetrics —
# zeebe_journal_append_total, flush counts/latency); process-global because a
# journal only knows its directory, not its partition
_M_APPENDS = _REGISTRY.counter(
    "journal_append_total", "records appended across all journals")
_M_APPEND_RATE = _REGISTRY.counter(
    "journal_append_rate", "records appended (rate source)")
_M_APPEND_BYTES = _REGISTRY.counter(
    "journal_append_data_rate", "bytes appended (rate source)")
_M_APPEND_LATENCY = _REGISTRY.histogram(
    "journal_append_latency", "seconds per journal append")
_M_TRY_APPEND = _REGISTRY.counter(
    "try_to_append_total", "append attempts incl. rejected asqn")
_M_FLUSHES = _REGISTRY.counter(
    "journal_flush_total", "journal fsyncs across all journals")
_M_FLUSH_SECONDS = _REGISTRY.histogram(
    "journal_flush_duration_seconds", "time per journal fsync")
_M_FLUSH_TIME = _REGISTRY.histogram(
    "journal_flush_time", "time per journal fsync (reference name)")
_M_FAILED_FLUSH = _REGISTRY.counter(
    "failed_flush", "journal fsyncs that raised")
_M_OPEN_TIME = _REGISTRY.histogram(
    "journal_open_time", "seconds to open+scan a journal")
_M_SEEK_LATENCY = _REGISTRY.histogram(
    "journal_seek_latency", "seconds per random-access journal read/seek")
_M_SEGMENT_COUNT = _REGISTRY.gauge(
    "segment_count", "live segment files across all journals")
_M_SEGMENT_CREATION = _REGISTRY.histogram(
    "segment_creation_time", "seconds to roll/create a segment")
_M_SEGMENT_FLUSH = _REGISTRY.histogram(
    "segment_flush_time", "seconds to fsync one segment")
_M_SEGMENT_TRUNCATE = _REGISTRY.histogram(
    "segment_truncate_time", "seconds to truncate a segment")
_M_LAST_FLUSHED = _REGISTRY.gauge(
    "last_flushed_index_update", "last index recorded as flushed")
_M_COMPACTION_MS = _REGISTRY.histogram(
    "compaction_time_ms", "ms per journal compaction pass",
    buckets=(0.1, 0.5, 1, 5, 10, 50, 100, 1000))
_M_COMPACTION_CLAMPED = _REGISTRY.counter(
    "journal_compaction_clamped_total",
    "compaction requests clamped by the safety guard "
    "(min of snapshot position and exporter cursors)")
_M_SEGMENT_ALLOC = _REGISTRY.histogram(
    "segment_allocation_time", "seconds to allocate a new segment file")
_M_DRAINS = _REGISTRY.counter(
    "journal_buffer_drain_total",
    "group-commit write-buffer drains (one file write each)")
_M_DRAIN_BYTES = _REGISTRY.histogram(
    "journal_buffer_drain_bytes", "bytes per write-buffer drain",
    buckets=(1024, 4096, 16384, 65536, 262144, 1048576, 4194304))
# cached label-less children: the append path is hot, and Metric.inc() pays a
# lock + dict lookup per call that the child skips
_C_APPENDS = _M_APPENDS.labels()
_C_APPEND_RATE = _M_APPEND_RATE.labels()
_C_APPEND_BYTES = _M_APPEND_BYTES.labels()
_C_APPEND_LATENCY = _M_APPEND_LATENCY.labels()
_C_TRY_APPEND = _M_TRY_APPEND.labels()
_C_DRAINS = _M_DRAINS.labels()
_C_DRAIN_BYTES = _M_DRAIN_BYTES.labels()

# flight-recorder seam (observability/flight_recorder.py): listeners called
# with (directory, seconds) when a flush exceeds the stall threshold. Module
# level because a journal knows only its directory, not its partition; the
# empty-list common case costs one truthiness check per fsync (not per append)
SLOW_FLUSH_THRESHOLD_S = 0.25
slow_flush_listeners: list = []

from time import perf_counter as _perf

_MAGIC = 0x5A4A4E4C  # "ZJNL"
_VERSION = 1
_SEG_HEADER = struct.Struct("<IIQQ")  # magic, version, segment_id, first_index
_FRAME = struct.Struct("<IIQq")  # length, crc32, index, asqn
_SPARSE_EVERY = 64


class CorruptedJournalError(Exception):
    """Corruption detected on a read path (checksum mismatch, bad header).

    ``index`` (first corrupt record index, when known) and ``path`` (the
    segment file) let the storage-repair plane (ISSUE 14) truncate at the
    corrupt frame and re-converge from a replica instead of crashing."""

    def __init__(self, message: str, index: int | None = None,
                 path: Path | None = None) -> None:
        super().__init__(message)
        self.index = index
        self.path = path


class FlushFailedError(OSError):
    """An fsync failed (fsyncgate, ISSUE 14): the page cache state of the
    device is undefined, so the journal already failed the segment hard —
    closed the fd, reopened, and re-verified from the last known-flushed
    offset. Bytes covered by the failed fsync were discarded and MUST NOT
    count toward any acked prefix (the raft layer clamps its flushed index
    to ``journal.last_index`` on this error)."""


class InvalidAsqnError(Exception):
    """Append with an asqn that is not monotonically increasing."""


ASQN_IGNORE = -1


@dataclasses.dataclass(frozen=True, slots=True)
class JournalRecord:
    index: int
    asqn: int
    data: bytes


def _py_checksum(index: int, asqn: int, data: bytes) -> int:
    head = struct.pack("<Qq", index, asqn)
    return zlib.crc32(data, zlib.crc32(head)) & 0xFFFFFFFF


# native frame fast path (native/codec.c): _py_checksum above is the crc
# specification (tests assert equality); journal_frame builds the complete
# <IIQq>-framed record in one C pass — one allocation and one crc sweep per
# append instead of two zlib calls, two struct packs, and a bytes concat
from zeebe_tpu import native as _native  # noqa: E402  (cycle-free leaf package)

_native_checksum = _native.codec_fn("journal_checksum")
_native_frame = _native.codec_fn("journal_frame")
_checksum = _native_checksum if _native_checksum is not None else _py_checksum


class _Segment:
    """One segment file: header + frames. Keeps an in-memory sparse index of
    (record index → file offset) for every ``_SPARSE_EVERY``-th record.

    Appends land in an in-memory write buffer (``_pending``) and reach the
    file in one bulk write per ``_drain()`` — interleaved per-append
    seek+write on a BufferedRandom thrashes its read buffer into a syscall
    per record (measured ~13% of e2e wall time), while group-commit drains
    pay one write per processed group. ``size`` is the LOGICAL size (file +
    pending); every read path drains first. ``durable_size`` tracks the
    fsync-covered prefix for power-loss simulation."""

    def __init__(self, path: Path, segment_id: int, first_index: int, create: bool) -> None:
        self.path = path
        self.segment_id = segment_id
        self.first_index = first_index
        self.last_index = first_index - 1
        self.last_asqn = ASQN_IGNORE
        self.sparse: list[tuple[int, int]] = []  # (index, offset)
        # (next_index, its_offset) after the last read_entry — log scans are
        # sequential, so most reads jump straight here
        self._read_hint: tuple[int, int] | None = None
        # file position tracker: -1 = unknown (a read moved it); the drain
        # only seeks when the position is not already at the file tail
        self._file_pos = -1
        self._pending: list[bytes] = []
        self._pending_bytes = 0
        if create:
            start = _perf()
            self.file = storage_io.open_file(path, "w+b")
            self.file.write(_SEG_HEADER.pack(_MAGIC, _VERSION, segment_id, first_index))
            self.file.flush()
            self.size = _SEG_HEADER.size
            self.durable_size = _SEG_HEADER.size
            _M_SEGMENT_ALLOC.observe(_perf() - start)
        else:
            self.file = storage_io.open_file(path, "r+b")
            self.size = _SEG_HEADER.size  # recomputed by scan()
            self.durable_size = _SEG_HEADER.size

    @classmethod
    def open_existing(cls, path: Path) -> "_Segment":
        with storage_io.open_file(path, "rb") as f:
            raw = f.read(_SEG_HEADER.size)
        if len(raw) < _SEG_HEADER.size:
            raise CorruptedJournalError(f"segment header truncated: {path}")
        magic, version, segment_id, first_index = _SEG_HEADER.unpack(raw)
        if magic != _MAGIC:
            raise CorruptedJournalError(f"bad segment magic in {path}: 0x{magic:08x}")
        if version != _VERSION:
            raise CorruptedJournalError(f"unsupported segment version {version} in {path}")
        return cls(path, segment_id, first_index, create=False)

    def scan(self) -> None:
        """Rebuild in-memory state from disk; truncate at first corrupt
        frame. Idempotent: a RE-scan (the ISSUE 14 repair path) resets the
        in-memory view first, so a walk that finds less than before (a
        mid-file corruption truncation) cannot leave stale last_index /
        last_asqn claims behind."""
        f = self.file
        self._pending.clear()
        self._pending_bytes = 0
        self._file_pos = -1
        self.last_index = self.first_index - 1
        self.last_asqn = ASQN_IGNORE
        f.seek(0, os.SEEK_END)
        file_len = f.tell()
        offset = _SEG_HEADER.size
        expected = self.first_index
        self.sparse.clear()
        self._read_hint = None
        mv = None
        f.seek(0)
        mv = memoryview(f.read())
        while offset + _FRAME.size <= file_len:
            length, crc, index, asqn = _FRAME.unpack_from(mv, offset)
            end = offset + _FRAME.size + length
            if length == 0 or end > file_len or index != expected:
                break
            data = bytes(mv[offset + _FRAME.size : end])
            if _checksum(index, asqn, data) != crc:
                break
            if (index - self.first_index) % _SPARSE_EVERY == 0:
                self.sparse.append((index, offset))
            self.last_index = index
            if asqn != ASQN_IGNORE:
                self.last_asqn = asqn
            expected += 1
            offset = end
        mv.release()
        if offset < file_len:
            # crash-torn or corrupt suffix: discard it
            f.truncate(offset)
            f.flush()
        self.size = offset
        self.durable_size = offset

    def append(self, index: int, asqn: int, data: bytes) -> None:
        # data may be any bytes-like object (the prepatched burst path hands
        # the writer's bytearray straight through); both paths below copy it
        # into an immutable pending frame synchronously, so the caller's
        # buffer is never aliased past this call
        if _native_frame is not None:
            self._pending.append(_native_frame(index, asqn, data))
        else:
            frame = _FRAME.pack(len(data), _checksum(index, asqn, data), index, asqn)
            self._pending.append(frame + data)
        self._pending_bytes += _FRAME.size + len(data)
        if (index - self.first_index) % _SPARSE_EVERY == 0:
            self.sparse.append((index, self.size))
        self.size += _FRAME.size + len(data)
        self.last_index = index
        if asqn != ASQN_IGNORE:
            self.last_asqn = asqn

    def _drain(self) -> None:
        """Write buffered appends to the file in one bulk write. Every read,
        fsync, truncation, and close goes through here first, so the file
        view is complete whenever anything other than append looks at it."""
        if not self._pending:
            return
        file_size = self.size - self._pending_bytes
        if self._file_pos != file_size:
            self.file.seek(file_size)
        # invalidate across the write: if it tears mid-way (ENOSPC), the next
        # drain must re-seek and overwrite the torn bytes
        self._file_pos = -1
        chunk = b"".join(self._pending)
        self.file.write(chunk)
        self._pending.clear()
        self._pending_bytes = 0
        self._file_pos = self.size
        _C_DRAINS.inc()
        _C_DRAIN_BYTES.observe(len(chunk))

    def _sparse_span(self, index: int) -> tuple[int, int]:
        """(start_offset, end_offset) of the sparse span holding ``index`` —
        O(1): record indexes are consecutive, so sparse entry k covers
        records [first_index + k*N, first_index + (k+1)*N)."""
        k = (index - self.first_index) // _SPARSE_EVERY
        if k < 0 or not self.sparse:
            return _SEG_HEADER.size, self.size
        k = min(k, len(self.sparse) - 1)
        start = self.sparse[k][1]
        end = self.sparse[k + 1][1] if k + 1 < len(self.sparse) else self.size
        return start, end

    def read_from(self, index: int) -> Iterator[JournalRecord]:
        """Yield records from ``index`` (clamped to first_index) to the end."""
        if index < self.first_index:
            index = self.first_index
        if index > self.last_index:
            return
        self._drain()
        offset, _ = self._sparse_span(index)
        self.file.seek(offset)
        self._file_pos = -1
        mv = memoryview(self.file.read(self.size - offset))
        pos = 0
        while pos + _FRAME.size <= len(mv):
            length, crc, rec_index, asqn = _FRAME.unpack_from(mv, pos)
            data = bytes(mv[pos + _FRAME.size : pos + _FRAME.size + length])
            pos += _FRAME.size + length
            if rec_index >= index:
                if _checksum(rec_index, asqn, data) != crc:
                    mv.release()
                    raise CorruptedJournalError(
                        f"checksum mismatch reading record {rec_index} in "
                        f"{self.path}", index=rec_index, path=self.path)
                yield JournalRecord(rec_index, asqn, data)
        mv.release()

    def read_entry(self, index: int) -> JournalRecord | None:
        """Read exactly one record by index (sparse-index seek + bounded walk),
        without materializing the rest of the segment."""
        if index < self.first_index or index > self.last_index:
            return None
        # sequential-read hint: log scans read index, index+1, … — the hint
        # jumps straight to the frame with no sparse walk at all; otherwise
        # the O(1) sparse floor bounds the walk to < _SPARSE_EVERY frames,
        # skipped header-by-header (seek past bodies, never reading them)
        hint = self._read_hint
        if hint is not None and hint[0] == index:
            offset = hint[1]
        else:
            offset, _ = self._sparse_span(index)
        f = self.file
        self._drain()
        self._file_pos = -1
        while offset < self.size:
            f.seek(offset)
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                return None
            length, crc, rec_index, asqn = _FRAME.unpack(head)
            if rec_index == index:
                data = f.read(length)
                if _checksum(rec_index, asqn, data) != crc:
                    raise CorruptedJournalError(
                        f"checksum mismatch reading record {rec_index} in "
                        f"{self.path}", index=rec_index, path=self.path)
                self._read_hint = (index + 1, offset + _FRAME.size + length)
                return JournalRecord(rec_index, asqn, data)
            offset += _FRAME.size + length
        return None

    def truncate_after(self, index: int) -> None:
        """Delete all records with index > ``index``."""
        if index >= self.last_index:
            return
        self._drain()
        offset = _SEG_HEADER.size
        new_last = self.first_index - 1
        new_asqn = ASQN_IGNORE
        for rec in self.read_from(self.first_index):
            if rec.index > index:
                break
            offset += _FRAME.size + len(rec.data)
            new_last = rec.index
            if rec.asqn != ASQN_IGNORE:
                new_asqn = rec.asqn
        start = _perf()
        self.file.truncate(offset)
        self.file.flush()
        self._file_pos = -1
        self.size = offset
        self.durable_size = min(self.durable_size, offset)
        self.last_index = new_last
        _M_SEGMENT_TRUNCATE.observe(_perf() - start)
        self.last_asqn = new_asqn
        self.sparse = [(i, o) for i, o in self.sparse if i <= new_last]
        self._read_hint = None

    def flush(self) -> None:
        start = _perf()
        self._drain()
        self.file.flush()
        try:
            storage_io.fsync(self.file.fileno(), self.path)
        except OSError as exc:
            # fsyncgate (ISSUE 14): after a failed fsync the page cache
            # state is UNDEFINED — retrying on the same fd can "succeed"
            # without the earlier dirty pages ever reaching the platter
            # (the PostgreSQL fsyncgate lesson). Fail the segment hard:
            # drop the fd, reopen, re-verify from the last known-flushed
            # offset; everything the failed fsync covered is discarded and
            # must never count toward an acked prefix.
            self._reopen_after_failed_fsync()
            raise FlushFailedError(
                exc.errno, f"fsync failed on {self.path}: {exc}") from exc
        self.durable_size = self.size
        _M_SEGMENT_FLUSH.observe(_perf() - start)

    def _reopen_after_failed_fsync(self) -> None:
        self._pending.clear()
        self._pending_bytes = 0
        try:
            self.file.close()
        except OSError:
            pass
        self.file = storage_io.open_file(self.path, "r+b")
        # bytes beyond the durable prefix may or may not have hit the
        # platter — truncate them away and re-verify what remains (scan
        # re-CRCs every frame and truncates at the first bad one)
        try:
            self.file.truncate(self.durable_size)
        except OSError:
            pass
        self.last_index = self.first_index - 1
        self.last_asqn = ASQN_IGNORE
        self.scan()

    def scrub(self, from_index: int, max_bytes: int) -> tuple[int, int, int | None]:
        """CRC-walk the drained file extent from ``from_index`` for up to
        ``max_bytes`` (ISSUE 14 scrubber). Returns ``(next_index,
        scanned_bytes, corrupt_index)`` — ``next_index`` past this
        segment's end means the segment is clean through its extent. Never
        drains and never raises on corruption: detection is the caller's
        signal to repair. Runs on the pump thread (the only writer), so
        the extent is stable for the duration of the walk."""
        limit = self.size - self._pending_bytes
        if from_index < self.first_index:
            from_index = self.first_index
        offset, _ = self._sparse_span(from_index)
        f = self.file
        self._file_pos = -1
        scanned = 0
        index = from_index
        while offset < limit and scanned < max_bytes:
            f.seek(offset)
            head = f.read(_FRAME.size)
            if len(head) < _FRAME.size:
                break
            length, crc, rec_index, asqn = _FRAME.unpack(head)
            end = offset + _FRAME.size + length
            if length == 0 or end > limit:
                # a torn frame inside the drained extent: corrupt from
                # here. The garbage header's rec_index is only trusted
                # when it is a plausible index for this segment — rotted
                # header bytes otherwise leak an arbitrary huge value into
                # the repair evidence
                plausible = (self.first_index <= rec_index
                             <= self.last_index + 1)
                return (self.last_index + 1, scanned,
                        rec_index if plausible else index)
            if rec_index >= from_index:
                data = f.read(length)
                scanned += _FRAME.size + length
                if _checksum(rec_index, asqn, data) != crc:
                    return self.last_index + 1, scanned, rec_index
                index = rec_index + 1
            offset = end
        return index, scanned, None

    def close(self) -> None:
        # clean shutdown: buffered appends reach the OS (matching the old
        # behavior where the file object's own buffer flushed on close)
        self._drain()
        self.file.close()

    def delete(self) -> None:
        self._pending.clear()  # no point writing out a file being unlinked
        self._pending_bytes = 0
        self.close()
        self.path.unlink(missing_ok=True)


class SegmentedJournal:
    """The journal: ordered segments, append/read/seek/truncate/compact.

    Indexes are 1-based and contiguous; asqns are strictly increasing where
    provided (reference: SegmentedJournalWriter append validation).
    """

    def __init__(
        self,
        directory: str | Path,
        name: str = "journal",
        max_segment_size: int = 8 * 1024 * 1024,
        flush_interval: float | None = None,
        max_unflushed_bytes: int = 1 << 20,
    ) -> None:
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.name = name
        self.max_segment_size = max_segment_size
        # group-commit knobs: appends buffer in memory and reach the file in
        # one write per drain (at ``max_unflushed_bytes``, or whenever a read
        # or fsync needs the file view); ``maybe_flush`` — called by the
        # stream processor at group boundaries — fsyncs only when
        # ``flush_interval`` seconds elapsed since the last fsync or the
        # unflushed backlog exceeds ``max_unflushed_bytes``. ``flush()``
        # itself stays an unconditional drain + fsync (Raft ack barriers).
        self.flush_interval = flush_interval
        self.max_unflushed_bytes = max_unflushed_bytes
        self._unflushed_bytes = 0
        self._last_flush_t = _perf()
        self._meta_path = self.dir / f"{name}.meta"
        self._meta_fd: int | None = None
        # compaction safety guard (broker/partition.py installs one): a
        # callable returning the max journal index (exclusive) compaction may
        # delete below — derived from min(snapshot position, all exporter
        # container cursors). compact() clamps to it; a guard failure fails
        # SAFE (no compaction this pass). None = unguarded (standalone
        # journals: tests, raft-internal resets).
        self.compact_guard: "Callable[[], int] | None" = None
        # async ack seam (ISSUE 17): called with the covered last index after
        # EVERY successful fsync — the pump-tail cadence flush, the idle
        # boundary, a backup barrier. Flush-gated consumers (the stream
        # processor's deferred client replies) release acks from here instead
        # of polling at the pump tail. Listeners are only ever invoked after
        # the fsync returned, so an acked prefix is a durable prefix by
        # construction; a failed fsync raises before this point and the
        # listeners stay silent.
        self.flush_listeners: list[Callable[[int], None]] = []
        self.segments: list[_Segment] = []
        # this journal's contribution to the global segment_count gauge —
        # updated by delta whenever the segment list changes, and returned
        # on close, so reopen cycles and resets can never drift the gauge
        self._counted_segments = 0
        # amortized append-metric accumulators (flushed by _flush_append_metrics)
        self._m_pending = 0
        self._m_pending_bytes = 0
        start = _perf()
        self._open_or_create()
        _M_OPEN_TIME.observe(_perf() - start)
        self._update_segment_gauge()

    def _update_segment_gauge(self) -> None:
        n = len(self.segments)
        if n != self._counted_segments:
            _M_SEGMENT_COUNT.inc(n - self._counted_segments)
            self._counted_segments = n

    # -- lifecycle -----------------------------------------------------------

    def _segment_path(self, segment_id: int) -> Path:
        return self.dir / f"{self.name}-{segment_id}.log"

    def _open_or_create(self) -> None:
        paths = sorted(
            self.dir.glob(f"{self.name}-*.log"),
            key=lambda p: int(p.stem.rsplit("-", 1)[1]),
        )
        prev_last: int | None = None
        for path in paths:
            seg = _Segment.open_existing(path)
            seg.scan()
            if prev_last is not None and seg.first_index != prev_last + 1:
                # gap between segments: discard this and all later segments
                seg.delete()
                for later in paths[paths.index(path) + 1 :]:
                    later.unlink(missing_ok=True)
                break
            self.segments.append(seg)
            prev_last = seg.last_index
        if not self.segments:
            self.segments.append(_Segment(self._segment_path(1), 1, 1, create=True))
        # drop empty trailing segments except the first
        while len(self.segments) > 1 and self.segments[-1].last_index < self.segments[-1].first_index:
            self.segments.pop().delete()

    def close(self) -> None:
        self._flush_append_metrics()
        if self._counted_segments:
            _M_SEGMENT_COUNT.inc(-self._counted_segments)
            self._counted_segments = 0
        for seg in self.segments:
            seg.close()
        if self._meta_fd is not None:
            os.close(self._meta_fd)
            self._meta_fd = None

    # -- properties ----------------------------------------------------------

    @property
    def first_index(self) -> int:
        return self.segments[0].first_index

    @property
    def last_index(self) -> int:
        return self.segments[-1].last_index

    @property
    def last_asqn(self) -> int:
        for seg in reversed(self.segments):
            if seg.last_asqn != ASQN_IGNORE:
                return seg.last_asqn
        return ASQN_IGNORE

    def is_empty(self) -> bool:
        return self.last_index < self.first_index

    @property
    def unflushed_bytes(self) -> int:
        """Appended bytes not yet covered by an fsync (group-commit pacing
        reads this to decide when a deferred flush is due)."""
        return self._unflushed_bytes

    # -- write path ----------------------------------------------------------

    def append(self, data: bytes, asqn: int = ASQN_IGNORE) -> JournalRecord:
        """Append one record; returns it with its assigned index. ``data``
        may be any contiguous bytes-like object — it is copied into the
        segment's framed write buffer before this call returns, so passing a
        mutable buffer (the prepatched burst path) is safe; the returned
        record aliases the caller's object.

        Metric updates are amortized the way the reference's hot loops do:
        counts/bytes accumulate in plain ints and flush to the registry every
        64 appends (and on fsync/close), and the latency histogram sees a
        1-in-64 sample — per-append registry traffic would otherwise be a
        measurable share of the append itself."""
        if asqn != ASQN_IGNORE and asqn <= self.last_asqn:
            _C_TRY_APPEND.inc()
            raise InvalidAsqnError(f"asqn {asqn} <= last asqn {self.last_asqn}")
        sampled = (self._m_pending & 63) == 0
        start = _perf() if sampled else 0.0
        tail = self.segments[-1]
        if tail.size + _FRAME.size + len(data) > self.max_segment_size and tail.last_index >= tail.first_index:
            tail = self._roll_segment()
        index = tail.last_index + 1
        tail.append(index, asqn, data)
        self._unflushed_bytes += _FRAME.size + len(data)
        if tail._pending_bytes >= self.max_unflushed_bytes:
            try:
                tail._drain()
            except OSError:
                # transient write fault (EIO/ENOSPC/torn): the buffered
                # frames are KEPT and the next drain re-seeks over any torn
                # prefix — the append itself stays valid, and durability is
                # decided at flush() where a persistent error surfaces
                pass
        self._m_pending += 1
        self._m_pending_bytes += _FRAME.size + len(data)
        if sampled:
            _C_APPEND_LATENCY.observe(_perf() - start)
        elif self._m_pending >= 64:
            self._flush_append_metrics()
        return JournalRecord(index, asqn, data)

    def _flush_append_metrics(self) -> None:
        n = self._m_pending
        if n:
            self._m_pending = 0
            _C_APPENDS.inc(n)
            _C_APPEND_RATE.inc(n)
            _C_TRY_APPEND.inc(n)
            _C_APPEND_BYTES.inc(self._m_pending_bytes)
            self._m_pending_bytes = 0

    def _roll_segment(self) -> _Segment:
        start = _perf()
        prev = self.segments[-1]
        prev.flush()
        seg = _Segment(
            self._segment_path(prev.segment_id + 1),
            prev.segment_id + 1,
            prev.last_index + 1,
            create=True,
        )
        self.segments.append(seg)
        self._update_segment_gauge()
        _M_SEGMENT_CREATION.observe(_perf() - start)
        return seg

    def flush(self) -> int:
        """fsync the tail segment (the only one that can be dirty: rolling
        flushes the previous segment, and truncation makes the truncated
        segment the tail) and record the last flushed index (reference:
        JournalMetaStore last-flushed index). The meta write is advisory —
        recovery re-derives state from segment scans — so it is a plain
        8-byte overwrite, not an fsync'd rename, keeping the hot append path
        at one fsync per flush."""
        self._flush_append_metrics()
        covered_bytes = self._unflushed_bytes
        start = _perf()
        try:
            self.segments[-1].flush()
        except OSError:
            _M_FAILED_FLUSH.inc()
            raise
        idx = self.last_index
        self._write_flush_marker(max(idx, 0))
        self._unflushed_bytes = 0
        self._last_flush_t = _perf()
        _M_LAST_FLUSHED.set(max(idx, 0))
        _M_FLUSHES.inc()
        elapsed = _perf() - start
        _M_FLUSH_SECONDS.observe(elapsed)
        _M_FLUSH_TIME.observe(elapsed)
        if slow_flush_listeners and elapsed >= SLOW_FLUSH_THRESHOLD_S:
            for listener in list(slow_flush_listeners):
                try:
                    listener(str(self.dir), elapsed)
                except Exception:  # noqa: BLE001 — diagnostics must never
                    pass           # fail the durability path
        # async ack callbacks: the fsync succeeded, so every appended byte is
        # durable — release whatever was gated on this covering flush. Fired
        # after all durability bookkeeping; listener failures must not
        # invalidate the flush itself.
        for listener in list(self.flush_listeners):
            try:
                listener(max(idx, 0))
            except Exception:  # noqa: BLE001 — ack fan-out must never
                logger.exception("journal flush listener failed (%s)", self.dir)
        if _TRACER.enabled:
            # group-flush span: the durability edge every gated ack waits on
            # (flushes are group-commit cadence, not per-append — cheap)
            _TRACER.emit("infra:journal", "journal.flush", elapsed,
                         attrs={"coveredBytes": covered_bytes,
                                "lastIndex": idx})
        return idx

    def maybe_flush(self) -> int | None:
        """Group-commit flush point: called once per processed group (not per
        append). fsyncs — and returns the covered index — only when there is
        an unflushed backlog AND the configured cadence says so: either
        ``flush_interval`` seconds passed since the last fsync, or the
        backlog exceeds ``max_unflushed_bytes``. With ``flush_interval=None``
        (the default) it never fsyncs on its own — durability stays owned by
        explicit ``flush()`` callers (Raft ack barriers, backups) exactly as
        before."""
        if self.flush_interval is None or not self._unflushed_bytes:
            return None
        if (self._unflushed_bytes >= self.max_unflushed_bytes
                or _perf() - self._last_flush_t >= self.flush_interval):
            return self.flush()
        return None

    @property
    def unflushed_bytes(self) -> int:
        return self._unflushed_bytes

    def simulate_power_loss(self) -> None:
        """Crash simulation for tests: discard every byte not covered by an
        fsync — in-memory append buffers AND file bytes written after the
        last ``flush()`` — then close the files. The caller reopens a fresh
        journal over the directory, exactly like a process restart after the
        machine lost power between a buffered append and its covering
        flush."""
        self._flush_append_metrics()
        if self._counted_segments:
            _M_SEGMENT_COUNT.inc(-self._counted_segments)
            self._counted_segments = 0
        for seg in self.segments:
            seg._pending.clear()
            seg._pending_bytes = 0
            seg.file.truncate(seg.durable_size)
            seg.file.close()
        if self._meta_fd is not None:
            os.close(self._meta_fd)
            self._meta_fd = None

    def _write_flush_marker(self, idx: int) -> None:
        # advisory (recovery re-derives from segment scans): a write fault
        # here must not fail a flush whose fsync already succeeded
        try:
            if self._meta_fd is None:
                self._meta_fd = storage_io.os_open(
                    self._meta_path, os.O_RDWR | os.O_CREAT, 0o644)
            storage_io.pwrite(self._meta_fd, struct.pack("<Q", idx), 0,
                              path=self._meta_path)
        except OSError:
            pass

    @property
    def last_flushed_index(self) -> int:
        try:
            return struct.unpack("<Q", self._meta_path.read_bytes())[0]
        except FileNotFoundError:
            return 0

    # -- read path -----------------------------------------------------------

    def read_from(self, index: int) -> Iterator[JournalRecord]:
        """Iterate records with record.index >= index, in order."""
        for seg in self.segments:
            if seg.last_index < index:
                continue
            yield from seg.read_from(index)

    def read_entry(self, index: int) -> JournalRecord | None:
        """Random-access read of one record by index (O(segment count) + one
        sparse-bounded walk; no whole-segment materialization)."""
        start = _perf()
        try:
            for seg in self.segments:
                if seg.first_index <= index <= seg.last_index:
                    return seg.read_entry(index)
            return None
        finally:
            _M_SEEK_LATENCY.observe(_perf() - start)

    def entries_meta(self) -> Iterator[tuple[int, int]]:
        """Yield (index, asqn) for every record — header-only scan used to
        rebuild derived indexes on open (e.g. the log stream's position map)."""
        for seg in self.segments:
            f = seg.file
            seg._drain()
            seg._file_pos = -1
            offset = _SEG_HEADER.size
            while offset < seg.size:
                f.seek(offset)
                head = f.read(_FRAME.size)
                if len(head) < _FRAME.size:
                    break
                length, _, rec_index, asqn = _FRAME.unpack(head)
                yield rec_index, asqn
                offset += _FRAME.size + length

    def seek_to_asqn(self, asqn: int) -> int:
        """Return the index of the last record with record.asqn <= asqn
        (0 if none) — recovery's entry point (reference: Journal.seekToAsqn)."""
        best = 0
        for rec in self.read_from(self.first_index):
            if rec.asqn != ASQN_IGNORE and rec.asqn <= asqn:
                best = rec.index
            elif rec.asqn != ASQN_IGNORE and rec.asqn > asqn:
                break
        return best

    # -- admin ---------------------------------------------------------------

    def truncate_after(self, index: int) -> None:
        """Remove all records after ``index`` (Raft conflict resolution).

        ``_unflushed_bytes`` intentionally keeps counting the discarded
        suffix: the counter must never UNDER-report (maybe_flush skipping a
        needed fsync would ack without durability), and the truncated
        segment's surviving prefix may itself still be un-fsynced — the
        worst case of the conservative choice is one spurious fsync."""
        while len(self.segments) > 1 and self.segments[-1].first_index > index:
            self.segments.pop().delete()
        self.segments[-1].truncate_after(index)

    # -- at-rest integrity (ISSUE 14) ----------------------------------------

    def scrub(self, from_index: int, max_bytes: int
              ) -> tuple[int, int, int | None]:
        """Incremental CRC walk over the drained file bytes, resumable at
        ``from_index``: returns ``(next_index, scanned_bytes,
        corrupt_index)``. ``next_index > last_index`` means the walk
        wrapped (one full pass complete). Detection only — the caller
        decides whether to :meth:`repair_corruption`. Pump-thread only."""
        scanned = 0
        index = max(from_index, self.first_index)
        for seg in self.segments:
            if scanned >= max_bytes:
                break
            if seg.last_index < index and seg.last_index >= seg.first_index:
                continue
            next_index, seg_scanned, corrupt = seg.scrub(
                index, max_bytes - scanned)
            scanned += seg_scanned
            if corrupt is not None:
                return next_index, scanned, corrupt
            index = max(index, next_index)
        return index, scanned, None

    def repair_corruption(self) -> dict:
        """Truncate the journal at its first corrupt frame (ISSUE 14 repair
        seam): every segment is re-scanned from disk — ``scan()`` re-CRCs
        each frame and truncates at the first bad one — and any segment
        left non-contiguous with its predecessor is deleted. The surviving
        prefix is exactly what a crash-restart open would have recovered.
        Returns before/after evidence for the repair's flight event. The
        caller (raft) owns the consequences: clamping its flushed index and
        re-converging the lost suffix from the leader."""
        before_last = self.last_index
        self._flush_append_metrics()
        for seg in self.segments:
            try:
                seg._drain()  # valid buffered appends survive the re-scan
            except OSError:
                pass  # never-acked bytes; losing them is safe
            seg.scan()
        kept = [self.segments[0]]
        for seg in self.segments[1:]:
            if seg.first_index != kept[-1].last_index + 1:
                seg.delete()
                continue
            kept.append(seg)
        self.segments = kept
        # drop empty trailing segments except the first (mirrors open)
        while len(self.segments) > 1 and \
                self.segments[-1].last_index < self.segments[-1].first_index:
            self.segments.pop().delete()
        self._update_segment_gauge()
        return {"beforeLastIndex": before_last,
                "afterLastIndex": self.last_index,
                "truncatedRecords": max(before_last - self.last_index, 0)}

    def compact(self, index: int) -> None:
        """Delete whole segments whose records are all < ``index`` (snapshot
        compaction; reference: SegmentedJournal.deleteUntil). Never deletes the
        tail segment, and never passes the installed ``compact_guard`` — the
        durability invariant that segment deletion cannot outrun the latest
        snapshot or any exporter container cursor is enforced HERE, below
        every caller."""
        if self.compact_guard is not None:
            try:
                bound = self.compact_guard()
            except Exception:  # noqa: BLE001 — a broken guard must fail safe
                bound = 0      # (skip compaction), never delete unguarded
            if index > bound:
                _M_COMPACTION_CLAMPED.inc()
                index = bound
        start = _perf()
        compacted = False
        while len(self.segments) > 1 and self.segments[0].last_index < index:
            self.segments.pop(0).delete()
            compacted = True
        if compacted:
            self._update_segment_gauge()
            _M_COMPACTION_MS.observe((_perf() - start) * 1000.0)

    def reset(self, next_index: int) -> None:
        """Discard everything and restart at ``next_index`` (snapshot install)."""
        for seg in self.segments:
            seg.delete()
        self.segments = [_Segment(self._segment_path(1), 1, next_index, create=True)]
        self._unflushed_bytes = 0  # the pre-reset backlog no longer exists
        self._update_segment_gauge()
        # invalidate the stale flushed-index marker from the pre-reset log
        self._write_flush_marker(max(next_index - 1, 0))


def read_only_records(directory: str | Path,
                      name: str = "journal") -> Iterator[JournalRecord]:
    """Iterate a journal directory's records WITHOUT opening it for write —
    unlike ``SegmentedJournal`` (which truncates crash-torn suffixes on
    open), this never mutates anything, so operator inspection tools (``cli
    snapshots``) can point it at a live broker's data directory. Stops
    silently at the first corrupt/torn frame, exactly where a real open
    would truncate."""
    directory = Path(directory)
    paths = sorted(directory.glob(f"{name}-*.log"),
                   key=lambda p: int(p.stem.rsplit("-", 1)[1]))
    prev_last: int | None = None
    for path in paths:
        try:
            raw = path.read_bytes()
        except OSError:
            return
        if len(raw) < _SEG_HEADER.size:
            return
        magic, version, _seg_id, first_index = _SEG_HEADER.unpack_from(raw)
        if magic != _MAGIC or version != _VERSION:
            return
        if prev_last is not None and first_index != prev_last + 1:
            return  # gap between segments: later segments are unreachable
        offset = _SEG_HEADER.size
        expected = first_index
        n = len(raw)
        while offset + _FRAME.size <= n:
            length, crc, index, asqn = _FRAME.unpack_from(raw, offset)
            end = offset + _FRAME.size + length
            if length == 0 or end > n or index != expected:
                return
            data = raw[offset + _FRAME.size:end]
            if _checksum(index, asqn, data) != crc:
                return
            yield JournalRecord(index, asqn, data)
            prev_last = index
            expected += 1
            offset = end
