"""Durable segmented journal (SURVEY.md §2.3)."""

from zeebe_tpu.journal.journal import (
    ASQN_IGNORE,
    CorruptedJournalError,
    InvalidAsqnError,
    JournalRecord,
    SegmentedJournal,
    read_only_records,
)

__all__ = [
    "ASQN_IGNORE",
    "CorruptedJournalError",
    "InvalidAsqnError",
    "JournalRecord",
    "SegmentedJournal",
    "read_only_records",
]
