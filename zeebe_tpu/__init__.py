"""zeebe_tpu — a TPU-native distributed workflow engine with Zeebe-capability parity.

A horizontally-scalable, fault-tolerant BPMN 2.0 process engine where the per-record
BPMN state machine is re-expressed as a data-parallel automaton kernel in JAX:
thousands of process-instance element records packed into device arrays, advanced
lock-step under ``jax.jit``/``pjit`` over a TPU mesh, while the host keeps the
event-sourced log, replication, snapshotting, state store, and client API.

Layer map (mirrors SURVEY.md §1, reference: honlyc/zeebe):

- ``protocol``    record schema: RecordType/ValueType/Intent, msgpack codec, keys
- ``journal``     append-only segmented log with checksummed framing
- ``state``       column-family KV store with transactions + snapshots
- ``logstreams``  per-partition log facade: sequencer, writer, readers
- ``stream``      stream-processing platform: processing/replay state machines
- ``engine``      BPMN workflow engine: processors, event appliers, engine state
- ``models``      BPMN model, fluent builder, deploy-time transformer
- ``feel``        FEEL-lite expression language (parse/eval + device compilation)
- ``ops``         JAX/Pallas device kernels: the batched automaton step
- ``parallel``    mesh/sharding, partitions, inter-partition command routing
- ``gateway``     client-facing API front-end
- ``exporters``   exporter SPI + recording exporter test harness
"""

__version__ = "0.1.0"
