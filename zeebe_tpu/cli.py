"""zbctl-parity CLI.

Reference: clients/go/cmd/zbctl/internal/commands/*.go — status, deploy,
create instance/worker, activate jobs, complete/fail job, publish message,
broadcast signal, resolve incident, set variables. JSON in, JSON out.

Beyond zbctl parity:
  trace        — offline causal-tree reconstruction from a journal
  top          — htop-style live cluster view over GET /cluster/status
                 (``--once`` prints a single frame for scripting)
  profile      — sample a live node's threads via the management server
                 (``--folded -o out.txt`` writes flamegraph.pl/speedscope
                 collapsed stacks; ``--continuous`` reads the always-on
                 profiler's retained windows instead of blocking)
  metrics-doc  — generate docs/metrics.md from the live metric registry
                 (``--check`` fails on drift; wired into CI)
  lint         — zlint, the repo's AST invariant linter (replay
                 determinism, device-call discipline, pump hygiene,
                 committed-read discipline, drift copies) against the
                 committed ``.zlint-baseline``; ``--check`` is the CI gate
  knobs-doc    — generate docs/knobs.md from every ``ZEEBE_*`` env knob the
                 AST scanner finds (``--check`` fails on drift or on an
                 undocumented knob; wired into CI)
  eligibility  — static kernel-eligibility report: which elements of a
                 definition ride the device kernel vs the host path, with
                 a typed catalog reason per host-forced element (offline;
                 a .bpmn file or ``--deployed --data-dir``)
  eligibility-doc — generate docs/eligibility.md from the reason catalog
                 + curated notes (``--check`` fails on drift or an
                 unexplained reason; wired into CI)

Usage: python -m zeebe_tpu.cli --address host:port <command> …
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _out(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="zbctl",
                                     description="tpu-zeebe cluster CLI")
    parser.add_argument("--address", default="127.0.0.1:26500",
                        help="gateway address (host:port)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster topology")

    p = sub.add_parser("deploy", help="deploy BPMN resources")
    p.add_argument("files", nargs="+")

    p = sub.add_parser("create", help="create resources")
    create_sub = p.add_subparsers(dest="what", required=True)
    ci = create_sub.add_parser("instance")
    ci.add_argument("process_id")
    ci.add_argument("--variables", default="{}")
    ci.add_argument("--version", type=int, default=0)
    ci.add_argument("--with-result", action="store_true")
    cw = create_sub.add_parser("worker")
    cw.add_argument("job_type")
    cw.add_argument("--handler", default="",
                    help="python expression over `job` returning variables dict")
    cw.add_argument("--max-jobs", type=int, default=32)

    p = sub.add_parser("cancel", help="cancel instance")
    p.add_argument("what", choices=["instance"])
    p.add_argument("key", type=int)

    p = sub.add_parser("activate", help="activate jobs")
    p.add_argument("what", choices=["jobs"])
    p.add_argument("job_type")
    p.add_argument("--max-jobs", type=int, default=32)
    p.add_argument("--worker", default="zbctl")

    p = sub.add_parser("complete", help="complete job")
    p.add_argument("what", choices=["job"])
    p.add_argument("key", type=int)
    p.add_argument("--variables", default="{}")

    p = sub.add_parser("fail", help="fail job")
    p.add_argument("what", choices=["job"])
    p.add_argument("key", type=int)
    p.add_argument("--retries", type=int, required=True)
    p.add_argument("--message", default="")

    p = sub.add_parser("publish", help="publish message")
    p.add_argument("what", choices=["message"])
    p.add_argument("name")
    p.add_argument("--correlation-key", required=True)
    p.add_argument("--variables", default="{}")
    p.add_argument("--ttl", type=int, default=3_600_000)
    p.add_argument("--message-id", default="")

    p = sub.add_parser("broadcast", help="broadcast signal")
    p.add_argument("what", choices=["signal"])
    p.add_argument("name")
    p.add_argument("--variables", default="{}")

    p = sub.add_parser("resolve", help="resolve incident")
    p.add_argument("what", choices=["incident"])
    p.add_argument("key", type=int)

    p = sub.add_parser("set", help="set variables")
    p.add_argument("what", choices=["variables"])
    p.add_argument("key", type=int)
    p.add_argument("--variables", required=True)
    p.add_argument("--local", action="store_true")

    p = sub.add_parser(
        "trace",
        help="reconstruct a process instance's causal record tree from a "
             "journal (offline; no gateway needed)")
    p.add_argument("key", type=int, help="process instance key")
    p.add_argument("--journal-dir", default=None,
                   help="path to a partition's stream journal directory "
                        "(e.g. <data>/partition-1/stream, or a harness's "
                        "<dir>/log)")
    p.add_argument("--data-dir", default=None,
                   help="broker data directory; the partition is derived "
                        "from the key unless --partition is given")
    p.add_argument("--partition", type=int, default=0,
                   help="partition id override (default: decoded from key)")
    p.add_argument("--exported-position", type=int, default=None,
                   help="an exporter's acked position; annotates each node "
                        "with whether it was exported")
    p.add_argument("--pretty", action="store_true",
                   help="ASCII tree instead of JSON")

    p = sub.add_parser(
        "top",
        help="live cluster view (health, roles, rates, alerts) over the "
             "management server's /cluster/status")
    p.add_argument("--management", default="http://127.0.0.1:9600",
                   help="management server base URL")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period, seconds")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (scripting)")

    p = sub.add_parser(
        "profile",
        help="profile a live node over the management server's /profile "
             "endpoints (one-shot by default; --continuous reads the "
             "always-on profiler without blocking)")
    p.add_argument("--management", default="http://127.0.0.1:9600",
                   help="management server base URL")
    p.add_argument("--seconds", type=float, default=2.0,
                   help="one-shot sampling window (server-capped at 30)")
    p.add_argument("--folded", action="store_true",
                   help="collapsed-stack output (flamegraph.pl/speedscope) "
                        "instead of JSON")
    p.add_argument("-o", "--output", default=None,
                   help="write the profile to a file instead of stdout")
    p.add_argument("--continuous", action="store_true",
                   help="read the continuous profiler's retained windows "
                        "(GET /profile/continuous) instead of taking a "
                        "blocking one-shot sample")
    p.add_argument("--since", type=int, default=0,
                   help="with --continuous: only windows ending after this "
                        "unix-ms timestamp")

    p = sub.add_parser(
        "lint",
        help="run zlint, the repo's AST-based invariant linter "
             "(offline; no gateway, no jax)")
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: the tree this package "
                        "was imported from)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 on findings not covered by the committed "
                        "baseline (CI gate)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to cover current findings, "
                        "preserving existing justifications")

    p = sub.add_parser(
        "knobs-doc",
        help="generate the env-knob reference (docs/knobs.md) from the "
             "AST scanner's ZEEBE_* inventory")
    p.add_argument("--root", default=None,
                   help="repo root to scan (default: the tree this package "
                        "was imported from)")
    p.add_argument("--output", default="docs/knobs.md")
    p.add_argument("--check", action="store_true",
                   help="exit 1 if the committed file drifted or any knob "
                        "lacks a KNOB_NOTES one-liner (CI gate)")

    p = sub.add_parser(
        "eligibility",
        help="static kernel-eligibility report for process definitions: "
             "which elements ride the device kernel vs the host path, with "
             "a typed reason per host-forced element (offline; classifies "
             "a .bpmn file or everything deployed in a data dir)")
    p.add_argument("definition", nargs="?",
                   help="a .bpmn file to classify (omit with --deployed)")
    p.add_argument("--deployed", action="store_true",
                   help="classify every definition deployed in --data-dir "
                        "(read from the stream journals' PROCESS CREATED "
                        "records; call activities resolve against what is "
                        "actually deployed)")
    p.add_argument("--data-dir", default=None,
                   help="broker data dir (partition-*/ children) or one "
                        "partition's dir, for --deployed")
    p.add_argument("--pretty", action="store_true",
                   help="human-readable table instead of JSON")
    p.add_argument("-o", "--output", default=None,
                   help="write the JSON report to a file")

    p = sub.add_parser(
        "eligibility-doc",
        help="generate the eligibility reason-catalog reference "
             "(docs/eligibility.md) from the catalog + curated notes")
    p.add_argument("--root", default=None,
                   help="repo root (default: the tree this package was "
                        "imported from)")
    p.add_argument("--output", default="docs/eligibility.md")
    p.add_argument("--check", action="store_true",
                   help="exit 1 if the committed file drifted or any "
                        "catalog reason lacks a REASON_NOTES one-liner "
                        "(CI gate)")

    p = sub.add_parser(
        "snapshots",
        help="list snapshot chains (positions, sizes, validity, projected "
             "replay debt) from a data directory — offline, read-only, safe "
             "on a live or postmortem broker dir")
    p.add_argument("data_dir",
                   help="a broker data dir (partition-*/ children), one "
                        "partition's dir, or a snapshot store root")
    p.add_argument("--pretty", action="store_true",
                   help="human-readable table instead of JSON")

    p = sub.add_parser(
        "metrics-doc",
        help="generate the metrics reference (docs/metrics.md) from a "
             "representative broker scenario's live registry")
    p.add_argument("--output", default="docs/metrics.md")
    p.add_argument("--check", action="store_true",
                   help="exit 1 if the committed file drifted from the "
                        "generated content (CI gate)")

    args = parser.parse_args(argv)

    if args.cmd == "trace":
        # offline journal walk — no gateway connection
        return _trace(args)
    if args.cmd == "top":
        return _top(args)
    if args.cmd == "profile":
        return _profile(args)
    if args.cmd == "metrics-doc":
        return _metrics_doc(args)
    if args.cmd == "lint":
        # offline AST walk — stdlib only, never initializes jax
        return _lint(args)
    if args.cmd == "knobs-doc":
        return _knobs_doc(args)
    if args.cmd == "eligibility":
        # offline classification — no gateway connection, no device init
        return _eligibility(args)
    if args.cmd == "eligibility-doc":
        return _eligibility_doc(args)
    if args.cmd == "snapshots":
        # offline store walk — no gateway connection
        return _snapshots(args)

    from zeebe_tpu.client import JobWorker, ZeebeTpuClient

    client = ZeebeTpuClient(args.address)
    try:
        return _dispatch(client, args)
    finally:
        client.close()


def _trace(args) -> int:
    from pathlib import Path

    from zeebe_tpu.journal import SegmentedJournal
    from zeebe_tpu.logstreams import LogStream
    from zeebe_tpu.observability import collect_lineage, format_lineage
    from zeebe_tpu.protocol.keys import decode_partition_id

    partition_id = args.partition or decode_partition_id(args.key) or 1
    if args.journal_dir:
        journal_dir = Path(args.journal_dir)
    elif args.data_dir:
        journal_dir = (Path(args.data_dir)
                       / f"partition-{partition_id}" / "stream")
        if not journal_dir.exists():
            # EngineHarness/bench layout: one partition, journal at <dir>/log
            fallback = Path(args.data_dir) / "log"
            if fallback.exists():
                journal_dir = fallback
    else:
        print("trace requires --journal-dir or --data-dir", file=sys.stderr)
        return 2
    if not journal_dir.exists():
        print(f"no journal at {journal_dir}", file=sys.stderr)
        return 2
    journal = SegmentedJournal(journal_dir)
    try:
        stream = LogStream(journal, partition_id)
        lineage = collect_lineage(stream, args.key,
                                  exported_position=args.exported_position)
        if not lineage["roots"]:
            print(f"no records for instance {args.key} in {journal_dir}",
                  file=sys.stderr)
            return 1
        if args.pretty:
            print(format_lineage(lineage))
        else:
            _out(lineage)
    finally:
        journal.close()
    return 0


# -- top: live cluster view ----------------------------------------------------


def _render_top(status: dict) -> str:
    """One frame of the `top` view from a /cluster/status payload. Pure
    (testable): no terminal control, no I/O."""
    lines = []
    topo = status.get("topology", {})
    lines.append(
        f"zeebe-tpu cluster · {status.get('clusterSize', 0)} broker(s) · "
        f"{status.get('partitionsCount', '?')} partition(s) · "
        f"health {status.get('health', '?')} · "
        f"{status.get('alertsFiring', 0)} alert(s) firing")
    lines.append(
        f"append {status.get('appendPerSec', 0.0)}/s · "
        f"processed {status.get('processedPerSec', 0.0)}/s · "
        f"topology v{topo.get('version', '?')}"
        + (" · change in progress" if topo.get("changeInProgress") else ""))
    lines.append("")
    header = (f"{'NODE':<14} {'HEALTH':<10} {'ROLES':<22} "
              f"{'APPEND/S':>9} {'PROC/S':>9} {'EXPLAG':>7} "
              f"{'PARKED':>8} {'ALERTS':>6}")
    lines.append(header)
    for row in status.get("brokers", []):
        parts = row.get("partitions", {})
        roles = " ".join(
            f"{pid}:{info['role'][:1].upper()}"
            for pid, info in sorted(parts.items(), key=lambda kv: int(kv[0]))
        ) or "-"
        rates = row.get("rates", {})
        # parked instances spilled to the cold tier (state tiering, ISSUE 8)
        parked = sum(info.get("parkedCold", 0) for info in parts.values())
        lines.append(
            f"{row.get('nodeId', '?'):<14} {row.get('health', '?'):<10} "
            f"{roles:<22} "
            f"{rates.get('appendPerSec', 0.0):>9} "
            f"{rates.get('processedPerSec', 0.0):>9} "
            f"{int(rates.get('exportLagRecords', 0)):>7} "
            f"{parked:>8} "
            f"{row.get('alertsFiring', 0):>6}")
    coverage_rows = [
        (row.get("nodeId", "?"), pid, info["kernelCoverage"])
        for row in status.get("brokers", [])
        for pid, info in sorted(row.get("partitions", {}).items(),
                                key=lambda kv: int(kv[0]))
        if info.get("kernelCoverage")
    ]
    if coverage_rows:
        # kernel-path coverage (ISSUE 13): which records rode the device
        # plane vs host per partition — the first place to look when the
        # ROADMAP item 3 coverage metric moves
        lines.append("")
        lines.append(f"{'KERNEL':<14} {'PART':>4} {'COV%':>6} "
                     f"{'KERNEL':>9} {'HOST':>9} {'DEVICE':<12} "
                     f"{'SHADOW':>7} {'MISM':>5} DOMINANT HOST REASON")
        for node, pid, cov in coverage_rows:
            # device health ladder (ISSUE 15): a QUARANTINED device is the
            # first thing to look at when a partition's COV% drops
            dev = cov.get("device", {})
            lines.append(
                f"{node:<14} {pid:>4} "
                f"{cov.get('coverageRatio', 0.0) * 100:>5.1f}% "
                f"{cov.get('kernelRecords', 0):>9} "
                f"{cov.get('hostRecords', 0):>9} "
                f"{dev.get('state', '-'):<12} "
                f"{dev.get('shadowChecks', 0):>7} "
                f"{dev.get('shadowMismatches', 0):>5} "
                f"{cov.get('dominantHostReason', '-')}")
    latency_rows = [
        (row.get("nodeId", "?"), pid, info["criticalPath"])
        for row in status.get("brokers", [])
        for pid, info in sorted(row.get("partitions", {}).items(),
                                key=lambda kv: int(kv[0]))
        if info.get("criticalPath")
    ]
    if latency_rows:
        # latency observatory (ISSUE 19): the last window's critical-path
        # verdict per partition — WHERE the worst acks spent their time,
        # not just how long they took
        lines.append("")
        lines.append(f"{'LATENCY':<14} {'PART':>4} {'ACKS':>7} "
                     f"{'WORST':>9} TOP STAGES (p99)")
        for node, pid, cp in latency_rows:
            stages = " ".join(
                f"{s.get('stage', '?')}:{s.get('p99Us', 0) / 1000.0:.2f}ms"
                for s in cp.get("topStages", [])[:3]) or "-"
            lines.append(
                f"{node:<14} {pid:>4} {cp.get('windowAcks', 0):>7} "
                f"{cp.get('worstMs', 0.0):>7.2f}ms {stages}")
    admission = status.get("admission")
    if admission and (admission.get("tenants") or admission.get("shedLevel")):
        # tenant admission (ISSUE 11): per-tenant rate/shed/queue evidence —
        # the first place to look when one tenant's p99 moves
        lines.append("")
        lines.append(
            f"ADMISSION · shed level {admission.get('shedLevel', 0)} · "
            f"p99 {admission.get('observedP99Ms', 0.0)}ms "
            f"(target {admission.get('shedP99TargetMs', '?')}ms) · "
            f"in-flight {admission.get('inflight', 0)}"
            f"/{admission.get('maxInflight', '?')}"
            + (" · DRAINING" if admission.get("draining") else ""))
        lines.append(f"{'TENANT':<18} {'ADMITTED':>9} {'SHED':>7} "
                     f"{'INFLIGHT':>8} {'QUOTA/S':>8} {'WEIGHT':>6}")
        for tenant, row in sorted(admission.get("tenants", {}).items()):
            quota = row.get("quotaRate")
            lines.append(
                f"{tenant:<18} {row.get('admitted', 0):>9} "
                f"{row.get('shed', 0):>7} {row.get('inflight', 0):>8} "
                f"{(f'{quota:g}' if quota else '-'):>8} "
                f"{row.get('weight', 1.0):>6}")
    control_rows = [(row.get("nodeId", "?"), row["control"])
                    for row in status.get("brokers", [])
                    if row.get("control")]
    if not control_rows and status.get("control"):
        control_rows = [("-", status["control"])]
    if control_rows:
        # closed-loop control plane (ISSUE 12): EVERY feedback loop — the
        # control-plane actuators plus the aggregated snapshot-scheduler /
        # admission-ladder loops — in one place, with bounds + audit counts
        lines.append("")
        lines.append(f"{'CONTROL':<14} {'LOOP':<20} {'KNOB':<26} "
                     f"{'VALUE':>9} {'BOUNDS':>15} {'ADJ':>5}")
        for node, block in control_rows:
            for name, ctl in sorted(block.get("controllers", {}).items()):
                for act in ctl.get("actuators", []):
                    bounds = f"[{act.get('min'):g},{act.get('max'):g}]"
                    lines.append(
                        f"{node:<14} {name:<20} {act.get('knob', '?'):<26} "
                        f"{act.get('value', 0):>9g} {bounds:>15} "
                        f"{act.get('adjustments', 0):>5}")
            for name, loop in sorted(block.get("loops", {}).items()):
                value = loop.get("value", loop.get("adjustments", "-"))
                lines.append(
                    f"{node:<14} {name:<20} {loop.get('knob', '?'):<26} "
                    f"{value!s:>9} {'-':>15} "
                    f"{loop.get('adjustments', 0):>5}")
    audit_rows = [(row.get("nodeId", "?"), row["audit"])
                  for row in status.get("brokers", [])
                  if row.get("audit")]
    if audit_rows:
        # fleet auditor (ISSUE 20): per-broker burn-rate state, leak
        # verdict, and latched invariant violations — the online view the
        # fleet-day gate cross-checks against the offline checker
        lines.append("")
        lines.append(f"{'AUDIT':<14} {'BURN':<8} {'FAST':>7} {'SLOW':>7} "
                     f"{'LEAK':<6} {'VIOL':>5} TRENDING RESOURCES")
        for node, audit in audit_rows:
            burn = audit.get("burn", {})
            trending = " ".join(
                f"{name}:{v.get('state', '?')}"
                for name, v in sorted(audit.get("leaks", {}).items())
                if v.get("state") not in ("quiet", "insufficient")) or "-"
            lines.append(
                f"{node:<14} {burn.get('state', '?'):<8} "
                f"{burn.get('fast', 0.0):>7.2f} "
                f"{burn.get('slow', 0.0):>7.2f} "
                f"{audit.get('leakVerdict', '?'):<6} "
                f"{audit.get('violations', 0):>5} {trending}")
    workers = status.get("workers")
    if workers:
        # multi-process deployment: the supervisor's per-worker view —
        # restart counts are the first thing to look at when routing flaps
        lines.append("")
        lines.append(f"{'WORKER':<14} {'PID':>8} {'ALIVE':<6} "
                     f"{'RESTARTS':>8}")
        for name, info in sorted(workers.items()):
            lines.append(
                f"{name:<14} {str(info.get('pid', '-')):>8} "
                f"{'yes' if info.get('alive') else 'NO':<6} "
                f"{info.get('restarts', 0):>8}")
        if "routingEpoch" in status:
            lines.append(f"routing epoch v{status['routingEpoch']}")
    firing = [a for row in status.get("brokers", [])
              for a in row.get("alerts", [])]
    if firing:
        lines.append("")
        lines.append("firing alerts:")
        for alert in firing:
            lines.append(
                f"  [{alert.get('severity', '?')}] {alert.get('rule', '?')} "
                f"{alert.get('labels', '')} value={alert.get('value', '?')} "
                f"({alert.get('expr', '')})")
    return "\n".join(lines)


def _fetch_cluster_status(base_url: str) -> dict:
    import urllib.request

    url = base_url.rstrip("/") + "/cluster/status"
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


def _top(args) -> int:
    # ValueError covers json.JSONDecodeError: a proxy error page or a wrong
    # port answering 200 with HTML must not become a raw traceback
    try:
        frame = _render_top(_fetch_cluster_status(args.management))
    except (OSError, ValueError) as exc:
        print(f"cannot reach {args.management}: {exc}", file=sys.stderr)
        return 2
    if args.once:
        print(frame)
        return 0
    try:
        while True:
            # \x1b[H home + \x1b[2J clear: classic full-repaint refresh; \x1b[J
            # after the frame clears any leftover tail from a taller frame
            sys.stdout.write(f"\x1b[H\x1b[2J{frame}\n\x1b[J")
            sys.stdout.flush()
            time.sleep(args.interval)
            frame = _render_top(_fetch_cluster_status(args.management))
    except KeyboardInterrupt:
        return 0
    except (OSError, ValueError) as exc:
        print(f"\nlost {args.management}: {exc}", file=sys.stderr)
        return 2


# -- profile: live-node profiling over the management server -------------------


def _profile(args) -> int:
    import urllib.error
    import urllib.request

    base = args.management.rstrip("/")
    if args.continuous:
        url = f"{base}/profile/continuous?since={args.since}"
    else:
        url = f"{base}/profile?seconds={args.seconds}"
    if args.folded:
        url += "&format=folded"
    # one-shot blocks server-side for the whole window: time the client
    # timeout off the requested seconds, not a constant
    timeout = 10.0 + (0 if args.continuous else args.seconds)
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            body = resp.read().decode()
    except urllib.error.HTTPError as exc:
        # the server WAS reached and its JSON body says what went wrong
        # (e.g. 404 "continuous profiler disabled (profiling_hz=0)") —
        # surface that, not a generic unreachable message
        detail = exc.read().decode(errors="replace").strip() or exc.reason
        print(f"{args.management} answered {exc.code}: {detail}",
              file=sys.stderr)
        return 2
    except (OSError, ValueError) as exc:
        print(f"cannot reach {args.management}: {exc}", file=sys.stderr)
        return 2
    if args.output:
        from pathlib import Path

        out_path = Path(args.output)
        out_path.write_text(body if body.endswith("\n") else body + "\n")
        lines = body.count("\n") + 1
        print(f"wrote {out_path} ({lines} line(s))", file=sys.stderr)
    else:
        print(body)
    return 0


# -- metrics-doc: generated metric reference -----------------------------------

_METRICS_DOC_HEADER = """\
# Metrics reference

> Auto-generated by `python -m zeebe_tpu.cli metrics-doc` from the live
> metric registry after a representative single-broker scenario (boot,
> deploy, process, snapshot, checkpoint, exporter/gateway/DMN component
> construction). **Do not edit by hand** — regenerate with
> `python -m zeebe_tpu.cli metrics-doc` and commit; CI fails on drift.
>
> Conventions: histograms additionally expose `_bucket`/`_sum`/`_count`
> series on `/metrics`; every series is retained as history by the
> in-memory time-series store (`GET /timeseries`, counters as rates,
> histograms as p50/p99) while the broker's sampler is enabled.
"""


def _register_metrics_scenario() -> None:
    """Run the representative scenario whose side effect is registering
    every metric family: a single-broker deterministic cluster processing a
    deployment, a snapshot, a checkpoint, plus the components that register
    at construction (ES exporter, gateway rpc wrappers, DMN counter,
    process self-metrics)."""
    import tempfile

    from zeebe_tpu.backup.checkpoint import CheckpointState
    from zeebe_tpu.broker.broker import InProcessCluster
    from zeebe_tpu.exporters import ElasticsearchExporter
    from zeebe_tpu.exporters.api import Exporter
    from zeebe_tpu.models.bpmn import Bpmn, to_bpmn_xml
    from zeebe_tpu.protocol import ValueType, command
    from zeebe_tpu.protocol.intent import DeploymentIntent
    from zeebe_tpu.utils.metrics import install_process_metrics

    class _SinkExporter(Exporter):
        def export(self, record) -> None:
            self.controller.update_last_exported_position(record.position)

    with tempfile.TemporaryDirectory() as tmp:
        cluster = InProcessCluster(
            broker_count=1, partition_count=1, replication_factor=1,
            directory=tmp,
            exporters_factory=lambda: {"recording": _SinkExporter()})
        try:
            cluster.await_leaders()
            model = (Bpmn.create_executable_process("metrics_doc")
                     .start_event("s").end_event("e").done())
            cluster.write_command(1, command(
                ValueType.DEPLOYMENT, DeploymentIntent.CREATE,
                {"resources": [{"resourceName": "m.bpmn",
                                "resource": to_bpmn_xml(model)}]}))
            cluster.run(500)
            partition = cluster.leader(1)
            partition.take_snapshot()
            with partition.db.transaction():
                CheckpointState(partition.db).put(1, 1)
        finally:
            cluster.close()
    ElasticsearchExporter(sink=lambda payload: None)
    import zeebe_tpu.engine.decision  # noqa: F401 — registers the DMN counter
    # ISSUE 7 families: killable device probe + worker supervision
    from zeebe_tpu.multiproc.supervisor import WorkerSupervisor
    from zeebe_tpu.utils import backend_probe

    backend_probe._probe_metric()
    WorkerSupervisor([])
    # ISSUE 9 family: the gateway's bounded-resend deadline counter lives
    # at module level in the multi-process runtime
    import zeebe_tpu.multiproc.runtime  # noqa: F401
    # ISSUE 12 families: the control_adjust audit vocabulary — explicit so
    # the doc stays deterministic even with ZEEBE_CONTROL_ENABLED=0
    import zeebe_tpu.control.audit  # noqa: F401
    # ISSUE 11 families: tenant admission (module-level) + one controller so
    # the labeled gauges/histogram exist; messaging's zombie-client counter
    import zeebe_tpu.cluster.messaging  # noqa: F401
    from zeebe_tpu.gateway.admission import AdmissionCfg, AdmissionController

    AdmissionController(AdmissionCfg(), node_id="gateway")
    from zeebe_tpu.gateway.gateway import _wrap

    def Topology(request, context):  # noqa: N802 — rpc-shaped name
        return None

    _wrap(Topology)
    install_process_metrics()


def _render_metrics_doc() -> str:
    from zeebe_tpu.utils.metrics import REGISTRY

    def cell(text: str) -> str:
        return text.replace("|", "\\|").replace("\n", " ")

    families = REGISTRY.describe()
    lines = [_METRICS_DOC_HEADER]
    lines.append(f"{len(families)} metric families.\n")
    lines.append("| name | type | labels | help |")
    lines.append("| --- | --- | --- | --- |")
    for fam in families:
        labels = ", ".join(f"`{n}`" for n in fam["labels"]) or "—"
        lines.append(
            f"| `{fam['name']}` | {fam['type']} | {labels} "
            f"| {cell(fam['help']) or '—'} |")
    return "\n".join(lines) + "\n"


def _metrics_doc(args) -> int:
    import os
    from pathlib import Path

    # the scenario boots a broker, which may initialize JAX: never let the
    # doc generator hang on an unreachable accelerator tunnel
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _register_metrics_scenario()
    content = _render_metrics_doc()
    path = Path(args.output)
    if args.check:
        committed = path.read_text() if path.exists() else ""
        if committed != content:
            print(f"{path} drifted from the registry — regenerate with "
                  f"`python -m zeebe_tpu.cli metrics-doc`", file=sys.stderr)
            import difflib

            diff = difflib.unified_diff(
                committed.splitlines(), content.splitlines(),
                fromfile=str(path), tofile="generated", lineterm="", n=1)
            for line in list(diff)[:40]:
                print(line, file=sys.stderr)
            return 1
        print(f"{path} is up to date ({content.count(chr(10))} lines)")
        return 0
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(content)
    print(f"wrote {path}")
    return 0


# -- lint: zlint, the AST invariant linter (ISSUE 10) --------------------------


def _repo_root(arg: str | None):
    from pathlib import Path

    if arg:
        return Path(arg)
    # the tree this package was imported from: zeebe_tpu/cli.py -> repo root
    return Path(__file__).resolve().parent.parent


def _lint(args) -> int:
    from zeebe_tpu.analysis import (
        BASELINE_FILENAME, format_baseline, load_baseline, run_lint,
        split_findings)

    root = _repo_root(args.root)
    baseline_path = root / BASELINE_FILENAME
    findings = run_lint(root)
    baseline = load_baseline(baseline_path)
    new, stale = split_findings(findings, baseline)

    if args.update_baseline:
        baseline_path.write_text(format_baseline(findings, baseline))
        todo = sum(1 for f in findings
                   if baseline.get(f.baseline_key, "").strip()
                   in ("", "TODO: justify"))
        print(f"wrote {baseline_path} ({len({f.baseline_key for f in findings})}"
              f" entries, {todo} needing justification)")
        return 0

    for f in new:
        print(f.render())
    for key in stale:
        print(f"stale baseline entry (no longer matches anything — remove "
              f"it): {chr(9).join(key)}", file=sys.stderr)
    covered = len(findings) - len(new)
    summary = (f"zlint: {len(findings)} finding(s) — {len(new)} new, "
               f"{covered} baselined, {len(stale)} stale baseline entr(ies)")
    # stale entries fail the gate too: a fixed violation must shrink the
    # baseline in the same change, or the dedicated lint job and the tier-1
    # tree-gate test would disagree about the same tree state
    if (new or stale) and args.check:
        print(f"{summary}\nfix the findings above, suppress inline with "
              f"`# zlint: disable=<rule>` next to a justification, or "
              f"refresh {BASELINE_FILENAME} via `cli lint --update-baseline` "
              f"(new entries need a one-line justification; stale entries "
              f"are dropped)", file=sys.stderr)
        return 1
    print(summary)
    return 1 if (new or stale) else 0


def _knobs_doc(args) -> int:
    from pathlib import Path

    from zeebe_tpu.analysis import render_knobs_doc, scan_knobs
    from zeebe_tpu.analysis.knobs import undocumented

    root = _repo_root(args.root)
    knobs = scan_knobs(root)
    content = render_knobs_doc(knobs)
    path = Path(args.output)
    if not path.is_absolute():
        path = root / path
    if args.check:
        from zeebe_tpu.analysis.knobs import KNOB_NOTES

        missing = undocumented(knobs)
        if missing:
            print(f"undocumented env knob(s): {', '.join(missing)} — add a "
                  f"one-liner to zeebe_tpu/analysis/knobs.py::KNOB_NOTES and "
                  f"regenerate with `python -m zeebe_tpu.cli knobs-doc`",
                  file=sys.stderr)
            return 1
        stale_notes = sorted(set(KNOB_NOTES) - {k.name for k in knobs})
        if stale_notes:
            print(f"stale KNOB_NOTES entr(ies) with no in-tree read: "
                  f"{', '.join(stale_notes)} — the knob was removed/renamed; "
                  f"drop the note and regenerate", file=sys.stderr)
            return 1
        committed = path.read_text() if path.exists() else ""
        if committed != content:
            print(f"{path} drifted from the env-knob scan — regenerate with "
                  f"`python -m zeebe_tpu.cli knobs-doc`", file=sys.stderr)
            import difflib

            diff = difflib.unified_diff(
                committed.splitlines(), content.splitlines(),
                fromfile=str(path), tofile="generated", lineterm="", n=1)
            for line in list(diff)[:40]:
                print(line, file=sys.stderr)
            return 1
        print(f"{path} is up to date ({len(knobs)} knobs)")
        return 0
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(content)
    print(f"wrote {path} ({len(knobs)} knobs)")
    return 0


# -- eligibility: static kernel-path classification (ISSUE 13) -----------------


class _OfflineProcesses:
    """Minimal ProcessState shim over journal-harvested deployments, so the
    classifier's call-activity inlining resolves against what is actually
    deployed (the two methods _inline_call_activities consults)."""

    def __init__(self, defs: dict[str, dict]) -> None:
        # bpmnProcessId → {"meta": …, "exe": ExecutableProcess}
        self._defs = defs
        self._by_key = {d["meta"]["processDefinitionKey"]: d
                       for d in defs.values()}

    def get_latest_by_id(self, process_id: str, tenant=None):
        entry = self._defs.get(process_id)
        return entry["meta"] if entry else None

    def executable(self, key: int):
        entry = self._by_key.get(key)
        return entry["exe"] if entry else None


def _harvest_deployed(data_dir) -> dict[str, dict]:
    """Latest deployed definition per bpmnProcessId, read offline from the
    stream journals' PROCESS CREATED events (the resource XML rides the
    event — no state load, no device init, safe on a live broker dir)."""
    from zeebe_tpu.journal import SegmentedJournal
    from zeebe_tpu.logstreams import LogStream
    from zeebe_tpu.models.bpmn import parse_bpmn_xml
    from zeebe_tpu.models.bpmn.executable import transform
    from zeebe_tpu.protocol import RecordType, ValueType
    from zeebe_tpu.protocol.intent import ProcessIntent

    # broker layout (<dir>/partition-N/stream), standalone layout
    # (<dir>/broker-N/partition-N/stream), or one partition's dir
    journal_dirs = sorted(data_dir.glob("partition-*/stream")) or sorted(
        data_dir.glob("*/partition-*/stream"))
    if not journal_dirs:
        # EngineHarness/bench layout: one partition, journal at <dir>/log
        for candidate in (data_dir / "log", data_dir / "stream", data_dir):
            if candidate.is_dir() and any(candidate.glob("journal-*.log")):
                journal_dirs = [candidate]
                break
    defs: dict[str, dict] = {}
    for journal_dir in journal_dirs:
        journal = SegmentedJournal(journal_dir)
        try:
            stream = LogStream(journal, partition_id=1)
            for view in stream.scan_filtered(
                    1, int(RecordType.EVENT), int(ValueType.PROCESS),
                    int(ProcessIntent.CREATED)):
                value = view.value
                pid = value.get("bpmnProcessId")
                if not pid or "resource" not in value:
                    continue
                known = defs.get(pid)
                if known and known["meta"]["version"] >= value.get("version", 1):
                    continue
                model = next((m for m in parse_bpmn_xml(value["resource"])
                              if m.process_id == pid), None)
                if model is None:
                    continue
                defs[pid] = {
                    "meta": {
                        "bpmnProcessId": pid,
                        "version": value.get("version", 1),
                        "processDefinitionKey":
                            value.get("processDefinitionKey", view.key),
                    },
                    "exe": transform(model),
                }
        finally:
            journal.close()
    return defs


def _render_eligibility(reports: list[dict]) -> str:
    """Human-readable view of classification reports (``--pretty``)."""
    lines = []
    for report in reports:
        counts = report.get("counts", {})
        verdict = ("KERNEL-ELIGIBLE" if report.get("eligible")
                   else "HOST-FORCED "
                        f"({', '.join(report.get('definitionReasons', []))})")
        lines.append(f"{report.get('bpmnProcessId', '?')}: {verdict} · "
                     f"{counts.get('kernel', 0)} kernel / "
                     f"{counts.get('host', 0)} host element(s)")
        for el in report.get("elements", []):
            if el.get("path") == "host":
                lines.append(f"  host   {el.get('id', '?'):<24} "
                             f"{el.get('type', '?'):<26} "
                             f"{el.get('reason', '')}")
        lines.append("")
    lines.append("runtime-only reasons (never statically predictable): "
                 + ", ".join(reports[0].get("runtimeOnlyReasons", []))
                 if reports else "no definitions found")
    return "\n".join(lines)


def _eligibility(args) -> int:
    from pathlib import Path

    from zeebe_tpu.engine.eligibility import classify_definition

    reports: list[dict] = []
    if args.deployed:
        if not args.data_dir:
            print("eligibility --deployed requires --data-dir",
                  file=sys.stderr)
            return 2
        data_dir = Path(args.data_dir)
        if not data_dir.exists():
            print(f"no data dir at {data_dir}", file=sys.stderr)
            return 2
        defs = _harvest_deployed(data_dir)
        if not defs:
            print(f"no deployed definitions found under {data_dir}",
                  file=sys.stderr)
            return 1
        from zeebe_tpu.engine.kernel_backend import KernelRegistry

        processes = _OfflineProcesses(defs)
        # ONE shared registry across the whole deployment set: the report
        # must see what runtime admission will — joint SlotMap clashes and
        # registry capacity (table-set-full) are invisible to solo passes
        registry = KernelRegistry()
        for pid in sorted(defs):
            entry = defs[pid]
            reports.append(classify_definition(
                entry["exe"], processes=processes,
                definition_key=entry["meta"]["processDefinitionKey"],
                registry=registry))
    else:
        if not args.definition:
            print("eligibility requires a .bpmn file or --deployed "
                  "--data-dir", file=sys.stderr)
            return 2
        path = Path(args.definition)
        if not path.exists():
            print(f"no such file: {path}", file=sys.stderr)
            return 2
        from zeebe_tpu.models.bpmn import parse_bpmn_xml
        from zeebe_tpu.models.bpmn.executable import (
            ProcessValidationError,
            transform,
        )

        for model in parse_bpmn_xml(path.read_text()):
            try:
                reports.append(classify_definition(transform(model)))
            except ProcessValidationError as exc:
                print(f"{model.process_id}: not deployable ({exc})",
                      file=sys.stderr)
                return 1
        if not reports:
            print(f"no process definitions in {path}", file=sys.stderr)
            return 1
    payload = {"definitions": reports}
    if args.output:
        Path(args.output).write_text(json.dumps(payload, indent=1) + "\n")
        print(f"wrote {args.output} ({len(reports)} definition(s))",
              file=sys.stderr)
    if args.pretty:
        print(_render_eligibility(reports))
    elif not args.output:
        _out(payload)
    return 0


def _eligibility_doc(args) -> int:
    from pathlib import Path

    from zeebe_tpu.analysis.eligibility_notes import (
        REASON_NOTES,
        render_eligibility_doc,
        stale_reason_notes,
        undocumented_reasons,
    )

    root = _repo_root(args.root)
    content = render_eligibility_doc()
    path = Path(args.output)
    if not path.is_absolute():
        path = root / path
    if args.check:
        missing = undocumented_reasons()
        if missing:
            print(f"unexplained eligibility reason(s): {', '.join(missing)} "
                  f"— add a one-liner to zeebe_tpu/analysis/"
                  f"eligibility_notes.py::REASON_NOTES and regenerate with "
                  f"`python -m zeebe_tpu.cli eligibility-doc`",
                  file=sys.stderr)
            return 1
        stale = stale_reason_notes()
        if stale:
            print(f"stale REASON_NOTES entr(ies) for retired code(s): "
                  f"{', '.join(stale)} — drop the note and regenerate",
                  file=sys.stderr)
            return 1
        committed = path.read_text() if path.exists() else ""
        if committed != content:
            print(f"{path} drifted from the reason catalog — regenerate "
                  f"with `python -m zeebe_tpu.cli eligibility-doc`",
                  file=sys.stderr)
            import difflib

            diff = difflib.unified_diff(
                committed.splitlines(), content.splitlines(),
                fromfile=str(path), tofile="generated", lineterm="", n=1)
            for line in list(diff)[:40]:
                print(line, file=sys.stderr)
            return 1
        print(f"{path} is up to date ({len(REASON_NOTES)} reasons)")
        return 0
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(content)
    print(f"wrote {path} ({len(REASON_NOTES)} reasons)")
    return 0


# -- snapshots: offline chain inspection ---------------------------------------


# mirror of broker/partition.py DEFAULT_REPLAY_RATE_RPS (kept local: the
# partition module pulls the engine/jax stack, which an offline inspection
# tool must never initialize)
_REPLAY_RATE_RPS = 10_000.0


def _snapshot_stores(root) -> list[tuple[str, "Path", "Path | None"]]:
    """Resolve ``(label, store_root, stream_journal_dir)`` triples from any
    of the accepted layouts: a broker data dir (``partition-*/`` children),
    one partition's dir, or a bare snapshot store root."""
    partitions = sorted(p for p in root.glob("partition-*") if p.is_dir())
    if partitions:
        return [(p.name, p / "snapshots", p / "stream") for p in partitions
                if (p / "snapshots").is_dir()]
    # a partition dir holds the store root at <dir>/snapshots (which itself
    # holds the committed snapshots at <store>/snapshots/<id>/)
    if (root / "snapshots" / "snapshots").is_dir():
        return [(root.name, root / "snapshots", root / "stream")]
    if (root / "snapshots").is_dir():
        return [(root.name, root, None)]
    return []


def _inspect_partition(label: str, store_root, stream_dir) -> dict:
    from zeebe_tpu.journal import read_only_records
    from zeebe_tpu.logstreams.log_stream import _py_scan_batch_headers
    from zeebe_tpu.state.snapshot import inspect_store

    snapshots = inspect_store(store_root)
    # the recovery anchor is the NEWEST snapshot whose whole chain
    # validates — exactly what partition recovery would install
    anchor = next((s for s in reversed(snapshots) if s["chainValid"]), None)
    anchor_processed = anchor["processedPosition"] if anchor else -1
    journal_end = None
    debt = None
    if stream_dir is not None and stream_dir.is_dir():
        journal_end, debt = -1, 0
        for jrec in read_only_records(stream_dir):
            try:
                _, _, records = _py_scan_batch_headers(jrec.data)
            except Exception:  # noqa: BLE001 — stop at the torn tail
                break
            for rec in records:
                position = rec[1]
                journal_end = max(journal_end, position)
                if position > anchor_processed:
                    debt += 1
    out = {
        "partition": label,
        "store": str(store_root),
        "snapshots": snapshots,
        "recoveryAnchor": None if anchor is None else {
            "id": anchor["id"],
            "chainLength": anchor["chainLength"],
            "processedPosition": anchor["processedPosition"],
        },
        "journalEndPosition": journal_end,
        "replayDebtRecords": debt,
    }
    if debt is not None:
        out["projectedReplayMs"] = round(debt * 1000.0 / _REPLAY_RATE_RPS, 1)
    return out


def _render_snapshots(report: dict) -> str:
    lines = []
    for part in report["partitions"]:
        anchor = part["recoveryAnchor"]
        lines.append(f"{part['partition']} · {part['store']}")
        lines.append(
            f"  recovery anchor: "
            + (f"{anchor['id']} (chain {anchor['chainLength']})"
               if anchor else "none — full replay from log start"))
        if part["replayDebtRecords"] is not None:
            lines.append(
                f"  journal end {part['journalEndPosition']} · replay debt "
                f"{part['replayDebtRecords']} records "
                f"(~{part['projectedReplayMs']}ms at "
                f"{int(_REPLAY_RATE_RPS)} rec/s)")
        header = (f"  {'id':<24} {'kind':<14} {'processed':>9} "
                  f"{'exported':>9} {'bytes':>10} {'chain':>5} valid")
        lines.append(header)
        for s in part["snapshots"]:
            valid = ("ok" if s["chainValid"]
                     else ("torn" if not s["valid"] else "broken-chain"))
            lines.append(
                f"  {s['id']:<24} {s['kind']:<14} "
                f"{s['processedPosition']:>9} {s['exportedPosition']:>9} "
                f"{s['sizeBytes']:>10} {s['chainLength']:>5} {valid}")
        if not part["snapshots"]:
            lines.append("  (no snapshots)")
        lines.append("")
    return "\n".join(lines).rstrip()


def _snapshots(args) -> int:
    from pathlib import Path

    root = Path(args.data_dir)
    if not root.is_dir():
        print(f"no such directory: {root}", file=sys.stderr)
        return 2
    stores = _snapshot_stores(root)
    if not stores:
        print(f"no snapshot stores under {root} (expected partition-*/ "
              f"children, a partition dir, or a store root)", file=sys.stderr)
        return 2
    report = {"dataDir": str(root), "partitions": [
        _inspect_partition(label, store_root, stream_dir)
        for label, store_root, stream_dir in stores
    ]}
    if args.pretty:
        print(_render_snapshots(report))
    else:
        _out(report)
    return 0


def _dispatch(client, args) -> int:
    if args.cmd == "status":
        topo = client.topology()
        _out({"clusterSize": topo.cluster_size,
              "partitionsCount": topo.partitions_count,
              "replicationFactor": topo.replication_factor,
              "gatewayVersion": topo.gateway_version,
              "brokers": topo.brokers})
    elif args.cmd == "deploy":
        _out(client.deploy_resource(*args.files))
    elif args.cmd == "create" and args.what == "instance":
        variables = json.loads(args.variables)
        if args.with_result:
            result = client.create_instance_with_result(
                args.process_id, version=args.version, variables=variables)
            _out({"processInstanceKey": result.process_instance_key,
                  "variables": result.variables})
        else:
            instance = client.create_instance(
                args.process_id, version=args.version, variables=variables)
            _out({"processDefinitionKey": instance.process_definition_key,
                  "bpmnProcessId": instance.bpmn_process_id,
                  "version": instance.version,
                  "processInstanceKey": instance.process_instance_key})
    elif args.cmd == "create" and args.what == "worker":
        handler_expr = args.handler or "{}"

        def handler(job):
            return eval(handler_expr, {"job": job, "json": json})  # noqa: S307

        from zeebe_tpu.client import JobWorker

        worker = JobWorker(client, args.job_type, handler,
                           max_jobs_active=args.max_jobs).start()
        print(f"worker on '{args.job_type}' started; ctrl-c to stop",
              file=sys.stderr)
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            worker.stop()
    elif args.cmd == "cancel":
        client.cancel_instance(args.key)
        _out({"canceled": args.key})
    elif args.cmd == "activate":
        jobs = client.activate_jobs(args.job_type, max_jobs=args.max_jobs,
                                    worker=args.worker)
        _out({"jobs": [vars(j) for j in jobs]})
    elif args.cmd == "complete":
        client.complete_job(args.key, json.loads(args.variables))
        _out({"completed": args.key})
    elif args.cmd == "fail":
        client.fail_job(args.key, args.retries, args.message)
        _out({"failed": args.key, "retries": args.retries})
    elif args.cmd == "publish":
        key = client.publish_message(args.name, args.correlation_key,
                                     json.loads(args.variables), args.ttl,
                                     args.message_id)
        _out({"messageKey": key})
    elif args.cmd == "broadcast":
        key = client.broadcast_signal(args.name, json.loads(args.variables))
        _out({"signalKey": key})
    elif args.cmd == "resolve":
        client.resolve_incident(args.key)
        _out({"resolved": args.key})
    elif args.cmd == "set":
        key = client.set_variables(args.key, json.loads(args.variables),
                                   local=args.local)
        _out({"key": key})
    return 0


if __name__ == "__main__":
    sys.exit(main())
