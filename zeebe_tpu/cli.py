"""zbctl-parity CLI.

Reference: clients/go/cmd/zbctl/internal/commands/*.go — status, deploy,
create instance/worker, activate jobs, complete/fail job, publish message,
broadcast signal, resolve incident, set variables. JSON in, JSON out.

Usage: python -m zeebe_tpu.cli --address host:port <command> …
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _out(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="zbctl",
                                     description="tpu-zeebe cluster CLI")
    parser.add_argument("--address", default="127.0.0.1:26500",
                        help="gateway address (host:port)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="cluster topology")

    p = sub.add_parser("deploy", help="deploy BPMN resources")
    p.add_argument("files", nargs="+")

    p = sub.add_parser("create", help="create resources")
    create_sub = p.add_subparsers(dest="what", required=True)
    ci = create_sub.add_parser("instance")
    ci.add_argument("process_id")
    ci.add_argument("--variables", default="{}")
    ci.add_argument("--version", type=int, default=0)
    ci.add_argument("--with-result", action="store_true")
    cw = create_sub.add_parser("worker")
    cw.add_argument("job_type")
    cw.add_argument("--handler", default="",
                    help="python expression over `job` returning variables dict")
    cw.add_argument("--max-jobs", type=int, default=32)

    p = sub.add_parser("cancel", help="cancel instance")
    p.add_argument("what", choices=["instance"])
    p.add_argument("key", type=int)

    p = sub.add_parser("activate", help="activate jobs")
    p.add_argument("what", choices=["jobs"])
    p.add_argument("job_type")
    p.add_argument("--max-jobs", type=int, default=32)
    p.add_argument("--worker", default="zbctl")

    p = sub.add_parser("complete", help="complete job")
    p.add_argument("what", choices=["job"])
    p.add_argument("key", type=int)
    p.add_argument("--variables", default="{}")

    p = sub.add_parser("fail", help="fail job")
    p.add_argument("what", choices=["job"])
    p.add_argument("key", type=int)
    p.add_argument("--retries", type=int, required=True)
    p.add_argument("--message", default="")

    p = sub.add_parser("publish", help="publish message")
    p.add_argument("what", choices=["message"])
    p.add_argument("name")
    p.add_argument("--correlation-key", required=True)
    p.add_argument("--variables", default="{}")
    p.add_argument("--ttl", type=int, default=3_600_000)
    p.add_argument("--message-id", default="")

    p = sub.add_parser("broadcast", help="broadcast signal")
    p.add_argument("what", choices=["signal"])
    p.add_argument("name")
    p.add_argument("--variables", default="{}")

    p = sub.add_parser("resolve", help="resolve incident")
    p.add_argument("what", choices=["incident"])
    p.add_argument("key", type=int)

    p = sub.add_parser("set", help="set variables")
    p.add_argument("what", choices=["variables"])
    p.add_argument("key", type=int)
    p.add_argument("--variables", required=True)
    p.add_argument("--local", action="store_true")

    p = sub.add_parser(
        "trace",
        help="reconstruct a process instance's causal record tree from a "
             "journal (offline; no gateway needed)")
    p.add_argument("key", type=int, help="process instance key")
    p.add_argument("--journal-dir", default=None,
                   help="path to a partition's stream journal directory "
                        "(e.g. <data>/partition-1/stream, or a harness's "
                        "<dir>/log)")
    p.add_argument("--data-dir", default=None,
                   help="broker data directory; the partition is derived "
                        "from the key unless --partition is given")
    p.add_argument("--partition", type=int, default=0,
                   help="partition id override (default: decoded from key)")
    p.add_argument("--exported-position", type=int, default=None,
                   help="an exporter's acked position; annotates each node "
                        "with whether it was exported")
    p.add_argument("--pretty", action="store_true",
                   help="ASCII tree instead of JSON")

    args = parser.parse_args(argv)

    if args.cmd == "trace":
        # offline journal walk — no gateway connection
        return _trace(args)

    from zeebe_tpu.client import JobWorker, ZeebeTpuClient

    client = ZeebeTpuClient(args.address)
    try:
        return _dispatch(client, args)
    finally:
        client.close()


def _trace(args) -> int:
    from pathlib import Path

    from zeebe_tpu.journal import SegmentedJournal
    from zeebe_tpu.logstreams import LogStream
    from zeebe_tpu.observability import collect_lineage, format_lineage
    from zeebe_tpu.protocol.keys import decode_partition_id

    partition_id = args.partition or decode_partition_id(args.key) or 1
    if args.journal_dir:
        journal_dir = Path(args.journal_dir)
    elif args.data_dir:
        journal_dir = (Path(args.data_dir)
                       / f"partition-{partition_id}" / "stream")
        if not journal_dir.exists():
            # EngineHarness/bench layout: one partition, journal at <dir>/log
            fallback = Path(args.data_dir) / "log"
            if fallback.exists():
                journal_dir = fallback
    else:
        print("trace requires --journal-dir or --data-dir", file=sys.stderr)
        return 2
    if not journal_dir.exists():
        print(f"no journal at {journal_dir}", file=sys.stderr)
        return 2
    journal = SegmentedJournal(journal_dir)
    try:
        stream = LogStream(journal, partition_id)
        lineage = collect_lineage(stream, args.key,
                                  exported_position=args.exported_position)
        if not lineage["roots"]:
            print(f"no records for instance {args.key} in {journal_dir}",
                  file=sys.stderr)
            return 1
        if args.pretty:
            print(format_lineage(lineage))
        else:
            _out(lineage)
    finally:
        journal.close()
    return 0


def _dispatch(client, args) -> int:
    if args.cmd == "status":
        topo = client.topology()
        _out({"clusterSize": topo.cluster_size,
              "partitionsCount": topo.partitions_count,
              "replicationFactor": topo.replication_factor,
              "gatewayVersion": topo.gateway_version,
              "brokers": topo.brokers})
    elif args.cmd == "deploy":
        _out(client.deploy_resource(*args.files))
    elif args.cmd == "create" and args.what == "instance":
        variables = json.loads(args.variables)
        if args.with_result:
            result = client.create_instance_with_result(
                args.process_id, version=args.version, variables=variables)
            _out({"processInstanceKey": result.process_instance_key,
                  "variables": result.variables})
        else:
            instance = client.create_instance(
                args.process_id, version=args.version, variables=variables)
            _out({"processDefinitionKey": instance.process_definition_key,
                  "bpmnProcessId": instance.bpmn_process_id,
                  "version": instance.version,
                  "processInstanceKey": instance.process_instance_key})
    elif args.cmd == "create" and args.what == "worker":
        handler_expr = args.handler or "{}"

        def handler(job):
            return eval(handler_expr, {"job": job, "json": json})  # noqa: S307

        from zeebe_tpu.client import JobWorker

        worker = JobWorker(client, args.job_type, handler,
                           max_jobs_active=args.max_jobs).start()
        print(f"worker on '{args.job_type}' started; ctrl-c to stop",
              file=sys.stderr)
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            worker.stop()
    elif args.cmd == "cancel":
        client.cancel_instance(args.key)
        _out({"canceled": args.key})
    elif args.cmd == "activate":
        jobs = client.activate_jobs(args.job_type, max_jobs=args.max_jobs,
                                    worker=args.worker)
        _out({"jobs": [vars(j) for j in jobs]})
    elif args.cmd == "complete":
        client.complete_job(args.key, json.loads(args.variables))
        _out({"completed": args.key})
    elif args.cmd == "fail":
        client.fail_job(args.key, args.retries, args.message)
        _out({"failed": args.key, "retries": args.retries})
    elif args.cmd == "publish":
        key = client.publish_message(args.name, args.correlation_key,
                                     json.loads(args.variables), args.ttl,
                                     args.message_id)
        _out({"messageKey": key})
    elif args.cmd == "broadcast":
        key = client.broadcast_signal(args.name, json.loads(args.variables))
        _out({"signalKey": key})
    elif args.cmd == "resolve":
        client.resolve_incident(args.key)
        _out({"resolved": args.key})
    elif args.cmd == "set":
        key = client.set_variables(args.key, json.loads(args.variables),
                                   local=args.local)
        _out({"key": key})
    return 0


if __name__ == "__main__":
    sys.exit(main())
