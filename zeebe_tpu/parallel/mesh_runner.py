"""MeshKernelRunner: N partitions' admitted groups on ONE device mesh.

This is SURVEY.md §2.13 row 1 made real in the serving stack: the reference
scales horizontally by adding Raft partitions (atomix/cluster/src/main/java/
io/atomix/raft/partition/RaftPartition.java:44, gateway round-robin
RequestDispatchStrategy); the TPU-native analogue shards the batch axis of
the automaton kernel over a ``jax.sharding.Mesh`` — **partition = shard of
the device batch**. Each partition's kernel backend builds its group arrays
exactly as for the single-device path; the runner packs up to ``n_shards``
groups into one shard-block-aligned batch, runs ONE sharded chunked
run_collect program (shard_map over the mesh, per-shard event tensors
assembled on axis 1), and hands each partition back its own per-step events.

Determinism: shards never interact — a group's step events are a pure
function of its own arrays, so a partition's materialized log is
byte-identical whether its group dispatched alone or coalesced with others
(the e2e byte-equality tests assert exactly this). Quiescence/overflow tails
stay per-shard for the same reason: one partition overflowing falls back
sequentially without poisoning co-dispatched partitions.

Thread model: partition ownership threads call ``submit()``; the first
submitter becomes the dispatch leader, drains the queue (coalescing whatever
other partitions enqueued — XLA execution releases the GIL, so groups pile
up naturally while the device is busy), and wakes the waiters. A
``run_groups()`` synchronous API underneath is the deterministic seam the
tests drive directly.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from zeebe_tpu.parallel.mesh import (
    BATCH_AXIS,
    make_mesh,
    shard_map_compat,
    state_specs,
)


@dataclass
class GroupRequest:
    """One partition's admitted group, in host (numpy) form.

    Arrays use the group's natural geometry (I, T); the runner pads to the
    dispatch's common geometry. ``tables_fingerprint`` gates coalescing:
    only groups compiled from identical table sets may share a dispatch
    (the sharded program takes ONE replicated DeviceTables argument)."""

    device_tables: Any  # DeviceTables (replicated input)
    config: Any  # KernelConfig (static)
    tables_fingerprint: Any
    arrays: dict[str, np.ndarray]  # elem/phase/inst/def_of/var_slots/join_counts/done
    num_instances: int  # I (padded bucket size)
    num_tokens: int  # T
    max_steps: int
    chunk_steps: int


@dataclass
class GroupResult:
    steps: list | None  # per-step unpacked event dicts; None → fall back
    overflow: bool = False
    quiesced: bool = True


@dataclass
class _Waiter:
    request: GroupRequest
    event: threading.Event = field(default_factory=threading.Event)
    result: GroupResult | None = None


def _pad_axis0(a: np.ndarray, n: int, fill) -> np.ndarray:
    if a.shape[0] == n:
        return a
    out = np.full((n, *a.shape[1:]), fill, a.dtype)
    out[: a.shape[0]] = a
    return out


class MeshKernelRunner:
    """Shared device-dispatch point for up to ``n_shards`` partitions."""

    def __init__(self, n_shards: int | None = None, mesh=None,
                 batch_window_s: float = 0.0, adaptive_window: bool = False) -> None:
        self.mesh = mesh if mesh is not None else make_mesh(n_shards)
        self.n_shards = self.mesh.devices.size
        # > 0: the dispatch leader waits this long before draining the queue,
        # trading a little latency for more coalescing (tests use it to make
        # multi-thread coalescing deterministic; serving leaves it 0 — groups
        # pile up naturally while the device is busy)
        self.batch_window_s = batch_window_s
        # adaptive gate (VERDICT r4 item 5): the window only pays off when
        # submitters actually overlap — sleep it only while recent drains
        # observed a backlog (dispatch queue non-empty when one finished).
        # With the gate on, an idle runner's window AUTO-DISABLES, so a
        # mis-set window cannot tax a non-contended deployment (round 4:
        # p8_windowed_300ms lost 40% throughput to an unconditional window).
        # Off by default: batch_window_s > 0 alone keeps its deterministic
        # always-sleep contract (tests coalesce concurrent submitters with
        # it; production serving opts into the adaptive gate).
        self.adaptive_window = adaptive_window
        self._recent_backlog = False
        self._lock = threading.Lock()
        self._queue: list[_Waiter] = []
        self._leader_active = False
        self._collect_cache: dict = {}
        # observability (tests assert coalescing happened)
        self.dispatches = 0
        self.groups_dispatched = 0
        self.coalesced_dispatches = 0
        self.windows_slept = 0
        self.windows_skipped = 0

    # -- the deterministic core: one sharded dispatch per compatible batch --

    def run_groups(self, requests: list[GroupRequest]) -> list[GroupResult]:
        """Execute every request; requests sharing a tables fingerprint ride
        one sharded dispatch (up to n_shards per dispatch)."""
        results: list[GroupResult | None] = [None] * len(requests)
        by_tables: dict[Any, list[int]] = {}
        for i, req in enumerate(requests):
            by_tables.setdefault(req.tables_fingerprint, []).append(i)
        for indices in by_tables.values():
            for start in range(0, len(indices), self.n_shards):
                batch = indices[start : start + self.n_shards]
                outs = self._dispatch([requests[i] for i in batch])
                for i, out in zip(batch, outs):
                    results[i] = out
        return results  # type: ignore[return-value]

    def _dispatch(self, requests: list[GroupRequest]) -> list[GroupResult]:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from zeebe_tpu.ops.automaton import unpack_events

        self.dispatches += 1
        self.groups_dispatched += len(requests)
        if len(requests) > 1:
            self.coalesced_dispatches += 1
        S = self.n_shards
        # common per-shard geometry: the max bucket over the batch (every
        # request was already bucket-padded by its backend, so this re-pads
        # only when buckets differ)
        I_c = max(r.num_instances for r in requests)
        T_c = max(r.num_tokens for r in requests)
        chunk = max(r.chunk_steps for r in requests)
        max_steps = max(r.max_steps for r in requests)
        lead = requests[0]

        def shard_arrays(name, fill):
            n = T_c if name in ("elem", "phase", "inst") else I_c
            blocks = [_pad_axis0(r.arrays[name], n, fill) for r in requests]
            while len(blocks) < S:
                blocks.append(np.full_like(blocks[0], fill))
            return np.concatenate(blocks, axis=0)

        elem = shard_arrays("elem", -1)
        phase = shard_arrays("phase", 0)
        inst = shard_arrays("inst", 0)
        def_of = shard_arrays("def_of", 0)
        var_slots = shard_arrays("var_slots", 0.0)
        join_counts = shard_arrays("join_counts", 0)
        mi_left = shard_arrays("mi_left", 0)
        # padding instances are done upfront so they never report newly_done
        done = shard_arrays("done", True)

        mesh = self.mesh
        specs = state_specs()

        def put(name, value):
            return jax.device_put(value, NamedSharding(mesh, specs[name]))

        row = NamedSharding(mesh, P(BATCH_AXIS))
        state = {
            "elem": put("elem", elem),
            "phase": put("phase", phase),
            "inst": put("inst", inst),
            "def_of": put("def_of", def_of),
            "var_slots": put("var_slots", var_slots),
            "join_counts": put("join_counts", join_counts),
            "mi_left": put("mi_left", mi_left),
            "done": put("done", done),
            "incident": put("incident", np.zeros(S * I_c, np.bool_)),
            # counters/overflow are per-shard rows (NOT psum'd: a partition's
            # overflow must fall back alone)
            "transitions": jax.device_put(np.zeros(S, np.int32), row),
            "jobs_created": jax.device_put(np.zeros(S, np.int32), row),
            "completed": jax.device_put(np.zeros(S, np.int32), row),
            "overflow": jax.device_put(np.zeros(S, np.bool_), row),
        }

        collect = self._sharded_collect(chunk, lead.config)
        FO = lead.device_tables.out_target.shape[2]
        row_len = T_c * (2 + FO) + 2
        n_req = len(requests)
        steps_per: list[list] = [[] for _ in range(n_req)]
        quiesced = [False] * n_req
        overflow = [False] * n_req
        for _ in range(max(1, max_steps // chunk)):
            state, packed = collect(lead.device_tables, state)
            flat = np.asarray(jax.device_get(packed))  # [chunk, S*row_len]
            for ri in range(n_req):
                if quiesced[ri]:
                    continue
                block = flat[:, ri * row_len : (ri + 1) * row_len]
                events = block[:, :-2].reshape(chunk, T_c, 2 + FO)
                active = block[:, -2]
                # overflow is cumulative in device state; run_collect's
                # early-exit loop leaves rows past quiescence as zeros, so
                # any written row carrying the bit is the signal
                overflow[ri] = overflow[ri] or bool(block[:, -1].any())
                qs = np.flatnonzero(active == 0)
                keep = int(qs[0]) + 1 if qs.size else chunk
                for s in range(keep):
                    steps_per[ri].append(unpack_events(events[s], I_c))
                if qs.size:
                    quiesced[ri] = True
            if all(quiesced):
                break
        return [
            GroupResult(steps=steps_per[ri], overflow=overflow[ri],
                        quiesced=quiesced[ri])
            for ri in range(n_req)
        ]

    def _sharded_collect(self, n_steps: int, config):
        key = (n_steps, config)
        fn = self._collect_cache.get(key)
        if fn is None:
            import jax
            from jax.sharding import PartitionSpec as P

            from zeebe_tpu.ops.automaton import DeviceTables, run_collect

            specs = state_specs()
            # per-shard scalar tails ride as length-S rows sharded on the
            # batch axis
            local_specs = dict(specs)
            for name in ("transitions", "jobs_created", "completed", "overflow"):
                local_specs[name] = P(BATCH_AXIS)

            def local(dt, state):
                # shard-local view: scalar counters for the kernel body
                local_state = dict(state)
                for name in ("transitions", "jobs_created", "completed",
                             "overflow"):
                    local_state[name] = state[name][0]
                new_state, packed = run_collect(dt, local_state,
                                                n_steps=n_steps, config=config)
                for name in ("transitions", "jobs_created", "completed",
                             "overflow"):
                    new_state[name] = new_state[name][None]
                return new_state, packed

            fn = jax.jit(shard_map_compat(
                local,
                mesh=self.mesh,
                in_specs=(
                    DeviceTables(**{
                        name: P() for name in DeviceTables.__dataclass_fields__
                    }),
                    local_specs,
                ),
                out_specs=(local_specs, P(None, BATCH_AXIS)),
                check_vma=False,
            ))
            self._collect_cache[key] = fn
        return fn

    # -- thread-safe opportunistic batching ---------------------------------

    def submit(self, request: GroupRequest) -> GroupResult:
        """Execute one group, coalescing with other threads' concurrently
        pending groups. The first submitter leads: it drains the queue (one
        sharded dispatch per compatible batch) until empty, then hands off."""
        waiter = _Waiter(request)
        with self._lock:
            self._queue.append(waiter)
            if self._leader_active:
                lead = False
            else:
                self._leader_active = True
                lead = True
        if not lead:
            waiter.event.wait()
            assert waiter.result is not None
            return waiter.result
        batch: list[_Waiter] = []
        try:
            if self.batch_window_s > 0:
                if not self.adaptive_window or self._recent_backlog:
                    import time

                    self.windows_slept += 1
                    time.sleep(self.batch_window_s)
                else:
                    self.windows_skipped += 1
            while True:
                with self._lock:
                    batch = self._queue
                    self._queue = []
                    if not batch:
                        self._leader_active = False
                        break
                results = self.run_groups([w.request for w in batch])
                with self._lock:
                    # device occupancy signal: others queued while we ran
                    self._recent_backlog = bool(self._queue)
                for w, res in zip(batch, results):
                    w.result = res
                    w.event.set()
        except BaseException:
            # wake EVERY waiter this leader was responsible for — the popped
            # batch and anything still queued — with a fallback result so no
            # partition thread hangs; their backends fall back sequentially
            with self._lock:
                stranded = batch + self._queue
                self._queue = []
                self._leader_active = False
            for w in stranded:
                if w.result is None:
                    w.result = GroupResult(steps=None)
                    w.event.set()
            if waiter.result is None:
                waiter.result = GroupResult(steps=None)
                waiter.event.set()
            raise
        assert waiter.result is not None
        return waiter.result
