"""Partition routing: correlation-key hashing + inter-partition command sender.

Reference: engine/…/message/command/SubscriptionCommandSender.java:43 +
SubscriptionUtil (correlation-key hash → partition), broker/…/partitionapi/
InterPartitionCommandSenderImpl.java:27-80 (topic "inter-partition-<id>"),
and the test-side TestInterPartitionCommandSender that loops sends back into
sibling in-process streams (SURVEY.md §4: the primary multi-node harness).
"""

from __future__ import annotations

from typing import Callable, Protocol

from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.keys import START_PARTITION_ID


def subscription_partition_id(correlation_key: str, partition_count: int) -> int:
    """Stable hash routing a correlation key to its message partition
    (reference: SubscriptionUtil.getSubscriptionPartitionId)."""
    h = 0
    for b in correlation_key.encode("utf-8"):
        h = (h * 31 + b) & 0x7FFFFFFF
    return START_PARTITION_ID + (h % partition_count)


class InterPartitionCommandSender(Protocol):
    """Ships a command record to another partition's log (at-least-once;
    receivers must deduplicate by key / state checks)."""

    def send_command(self, receiver_partition_id: int, record: Record) -> None: ...


class LoopbackCommandSender:
    """Single-partition deployment: inter-partition sends loop back into the
    local log (exactly what happens when sender == receiver in the reference)."""

    def __init__(self, write_local: Callable[[Record], None]) -> None:
        self._write_local = write_local

    def send_command(self, receiver_partition_id: int, record: Record) -> None:
        self._write_local(record)


class InProcessClusterSender:
    """Multi-partition in-process cluster: delivers into sibling partition
    logs synchronously (the TestInterPartitionCommandSender harness role).
    Registration happens as partitions boot."""

    def __init__(self) -> None:
        self._writers: dict[int, Callable[[Record], None]] = {}
        self.sent: list[tuple[int, Record]] = []

    def register(self, partition_id: int, write: Callable[[Record], None]) -> None:
        self._writers[partition_id] = write

    def send_command(self, receiver_partition_id: int, record: Record) -> None:
        self.sent.append((receiver_partition_id, record))
        writer = self._writers.get(receiver_partition_id)
        if writer is None:
            raise KeyError(f"no partition {receiver_partition_id} registered")
        writer(record.replace(partition_id=receiver_partition_id))
