"""Mesh sharding: partitions = shards of the instance/token axis.

The reference scales by hash-sharding process instances across Raft
partitions (SURVEY.md §2.13 data parallelism); here a partition maps to a
shard of the device mesh. Each shard owns a disjoint instance range and its
token pool, so the automaton step is embarrassingly parallel — the only
cross-shard traffic is the psum of global counters (and, later, message
correlation rides the same axis with an all_to_all). Collectives stay on ICI;
the host control plane (log, Raft, gRPC) never sees device internals.

Tables are replicated (they are small and read-only); state arrays shard on
axis 0. Works identically on a real TPU mesh and on the CPU host-device mesh
used in tests/dryrun.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zeebe_tpu.ops.automaton import DeviceTables, step


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """jax.shard_map across JAX versions: older releases ship it as
    jax.experimental.shard_map with the replication check named ``check_rep``
    instead of ``check_vma``."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


#: the mesh's single axis: partitions = shards of the batch axis
#: (SNIPPETS.md [1]: ``NamedSharding(mesh, PartitionSpec("batch"))``)
BATCH_AXIS = "batch"


def resolve_mesh_devices() -> list:
    """Device list for mesh construction — WITHOUT an unguarded in-process
    ``jax.devices()``: on this host class a wedged TPU tunnel hangs the
    default-backend query forever, and mesh construction runs on broker
    startup paths that must never block. When the platform is already
    pinned to cpu (tests, bench after its probe, drive scripts) the
    in-process query is safe; otherwise the default backend is probed in a
    killable subprocess (``utils/backend_probe``) and a wedged/failed probe
    DEGRADES to host devices — the broker keeps serving on the CPU mesh and
    the ``zeebe_device_probe_total{outcome="probe-killed"}`` counter carries
    the evidence."""
    if str(jax.config.jax_platforms or "").startswith("cpu"):
        return jax.devices()
    from zeebe_tpu.utils.backend_probe import pin_cpu_if_unreachable

    # probe (memoized per process), pin cpu on wedge/no-accelerator — the
    # shared rule lives in backend_probe; host devices are the degrade path
    pin_cpu_if_unreachable()
    return jax.devices()


def make_mesh(n_devices: int | None = None) -> Mesh:
    devices = resolve_mesh_devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            # truncating silently would mismatch callers' shard-block state
            # layout (num_shards=n) and corrupt instance indexing
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                "devices are available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (BATCH_AXIS,))


_SHARDED_KEYS = ("elem", "phase", "inst", "def_of", "var_slots", "join_counts",
                 "mi_left", "done", "incident")
_REPLICATED_KEYS = ("transitions", "jobs_created", "completed", "overflow")


def state_specs() -> dict:
    specs = {k: P(BATCH_AXIS) for k in _SHARDED_KEYS}
    specs.update({k: P() for k in _REPLICATED_KEYS})
    return specs


def shard_state(state: dict, mesh: Mesh) -> dict:
    """Place a host-built state dict onto the mesh (instances must already be
    grouped so each shard's tokens reference only its own instances — true
    for make_state's identity layout when I and T are multiples of the mesh)."""
    specs = state_specs()
    return {
        key: jax.device_put(value, NamedSharding(mesh, specs[key]))
        for key, value in state.items()
    }


def make_sharded_step(mesh: Mesh, auto_jobs: bool = True, config=None):
    """A pjit-compiled, shard_mapped step: per-shard automaton advance with
    psum'd global counters. Instances never cross shards (partition
    semantics), so the kernel body runs unchanged on local shapes."""

    specs = state_specs()

    def local_step(tables: DeviceTables, state: dict) -> dict:
        new_state, _ = step(tables, state, auto_jobs=auto_jobs, emit_events=False, config=config)
        # counters: psum the per-shard delta so the replicated value stays global
        for key in ("transitions", "jobs_created", "completed"):
            delta = new_state[key] - state[key]
            new_state[key] = state[key] + jax.lax.psum(delta, BATCH_AXIS)
        overflow_any = jax.lax.psum(
            new_state["overflow"].astype(jax.numpy.int32), BATCH_AXIS) > 0
        new_state["overflow"] = overflow_any
        return new_state

    sharded = shard_map_compat(
        local_step,
        mesh=mesh,
        in_specs=(
            DeviceTables(**{name: P() for name in DeviceTables.__dataclass_fields__}),
            specs,
        ),
        out_specs=specs,
        check_vma=False,
    )
    return jax.jit(sharded)
