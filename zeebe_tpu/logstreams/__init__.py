"""Per-partition log stream abstraction (SURVEY.md §2.5)."""

from zeebe_tpu.logstreams.log_stream import (
    LogAppendEntry,
    LoggedRecord,
    LogStream,
    LogStreamReader,
    LogStreamWriter,
    RecordView,
    patch_prepatched_batch,
)

__all__ = [
    "LogAppendEntry",
    "LoggedRecord",
    "LogStream",
    "LogStreamReader",
    "LogStreamWriter",
    "RecordView",
    "patch_prepatched_batch",
]
