"""Per-partition log stream: position-assigning writer + readers over the journal.

Reference: logstreams/src/main/java/io/camunda/zeebe/logstreams/log/LogStream.java,
impl/log/Sequencer.java:37 (position assignment, tryWrite :67-96),
impl/log/LogStorageAppender.java, impl/serializer/LogAppendEntrySerializer.java,
log/LogAppendEntry.java (ofProcessed).

One journal entry holds one *sequenced batch*: all follow-up records of a single
processing step, written atomically. Each record gets a monotonically increasing
stream position; the batch's first position is the journal entry's asqn, which
makes ``seek_to_position`` a journal asqn-seek. Entries marked ``processed``
(follow-ups already applied in the same processing step) are skipped by replay
— LogAppendEntry.ofProcessed semantics.
"""

from __future__ import annotations

import dataclasses
import struct
import threading
import time
from typing import Iterator

from zeebe_tpu import native as _native
from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.utils import evict_oldest_half as _evict_oldest_half
from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.enums import RecordType
from zeebe_tpu.protocol.msgpack import unpackb as msgpack_unpackb

_BATCH_HEADER = struct.Struct("<IqQ")  # record count, source position, timestamp ms
_ENTRY_HEADER = struct.Struct("<BqI")  # processed flag, position, record length
_PACK_LE_Q = struct.Struct("<q")
_FRAME_KEY = struct.Struct("<q")
_FRAME_HEADER_SIZE = 50  # protocol/record.py _HEADER.size
# hoisted for the scan hot loop (RecordView.is_event/is_command)
_RT_EVENT = int(RecordType.EVENT)
_RT_COMMAND = int(RecordType.COMMAND)


def _py_scan_batch_headers(payload: bytes):
    """Pure-Python mirror of the native scan_batch_headers: same tuples, and
    the same MsgPackError on every malformed-input shape the C scanner
    rejects (truncation, impossible lengths, trailing bytes)."""
    from zeebe_tpu.protocol.msgpack import MsgPackError

    n = len(payload)
    if n < _BATCH_HEADER.size:
        raise MsgPackError(f"batch payload truncated: {n} bytes")
    count, source_position, timestamp = _BATCH_HEADER.unpack_from(payload, 0)
    off = _BATCH_HEADER.size
    records = []
    for i in range(count):
        if off + _ENTRY_HEADER.size > n:
            raise MsgPackError(f"batch entry {i} truncated")
        processed, position, length = _ENTRY_HEADER.unpack_from(payload, off)
        off += _ENTRY_HEADER.size
        if off + length > n or length < _FRAME_HEADER_SIZE:
            raise MsgPackError(f"batch record {i} truncated")
        records.append((
            processed, position, payload[off], payload[off + 1],
            payload[off + 2], _FRAME_KEY.unpack_from(payload, off + 4)[0],
            off, length,
        ))
        off += length
    if off != n:
        raise MsgPackError(f"trailing bytes after batch: {n - off}")
    return source_position, timestamp, records


from zeebe_tpu.utils.metrics import REGISTRY as _METRICS

# sequencer/appender metrics (reference: logstreams impl/Sequencer +
# LogStorageAppender metrics); label-less children cached — the writer is hot
_M_SEQ_BATCH_SIZE = _METRICS.histogram(
    "sequencer_batch_size", "records per sequenced batch",
    (), buckets=(1, 2, 4, 8, 16, 32, 64, 128, 512)).labels()
_M_SEQ_BATCH_BYTES = _METRICS.histogram(
    "sequencer_batch_length_bytes", "bytes per sequenced batch",
    (), buckets=(256, 1024, 4096, 16384, 65536, 262144)).labels()
_M_APPEND_LATENCY = _METRICS.histogram(
    "log_appender_append_latency", "seconds per log append").labels()
_M_LAST_APPENDED = _METRICS.gauge(
    "log_appender_last_appended_position",
    "last record position appended").labels()
_M_LAST_COMMITTED = _METRICS.gauge(
    "log_appender_last_committed_position",
    "last record position committed/visible").labels()
_M_COMMIT_LATENCY = _METRICS.histogram(
    "log_appender_commit_latency",
    "seconds from sequencing to committed visibility").labels()
# the writer is synchronous (no sequencer ring buffer between ingress and
# the appender), so the queue depth is structurally 0 — registered for
# dashboard parity with the reference's sequencer_queue_size
_METRICS.gauge(
    "sequencer_queue_size",
    "sequenced batches queued for append (synchronous writer: 0)").set(0)

# append→ack latency stamping: one enabled-check per append when tracing is
# off (the singleton is mutated in place, never replaced)
from zeebe_tpu.observability.tracer import get_tracer as _get_tracer

_TRACER = _get_tracer()

_codec = _native.load_codec()
_scan_batch_headers = (
    _codec.scan_batch_headers
    if _codec is not None and hasattr(_codec, "scan_batch_headers")
    else _py_scan_batch_headers
)


def _py_scan_batch_headers_filtered(payload, record_type, value_type, intent):
    src, ts, headers = _scan_batch_headers(payload)
    return src, ts, [
        h for h in headers
        if h[2] == record_type and h[3] == value_type
        and (intent < 0 or h[4] == intent)
    ]


_scan_batch_headers_filtered = (
    _codec.scan_batch_headers_filtered
    if _codec is not None and hasattr(_codec, "scan_batch_headers_filtered")
    else _py_scan_batch_headers_filtered
)


class RecordView:
    """Header-only view of one record inside a sequenced batch.

    A filtering scan (job discovery, export filters, command scans) reads the
    fixed header fields — ``record_type``/``value_type``/``intent`` are the
    raw wire ints, comparable to the IntEnums by value — and pays for the full
    ``Record`` (rejection reason + msgpack value) only on first ``.record``
    access."""

    __slots__ = ("position", "processed", "source_position", "record_type",
                 "value_type", "intent", "key", "_payload", "_off", "_len",
                 "_timestamp", "_partition_id", "_record")

    def __init__(self, position, processed, source_position, record_type,
                 value_type, intent, key, payload, off, length, timestamp,
                 partition_id, record=None):
        self.position = position
        self.processed = processed
        self.source_position = source_position
        self.record_type = record_type
        self.value_type = value_type
        self.intent = intent
        self.key = key
        self._payload = payload
        self._off = off
        self._len = length
        self._timestamp = timestamp
        self._partition_id = partition_id
        self._record = record

    @property
    def is_event(self) -> bool:
        return self.record_type == _RT_EVENT

    @property
    def is_command(self) -> bool:
        return self.record_type == _RT_COMMAND

    @property
    def record(self) -> Record:
        if self._record is None:
            self._record = Record.from_bytes(
                self._payload[self._off : self._off + self._len],
                position=self.position, partition_id=self._partition_id,
                timestamp=self._timestamp,
            )
        return self._record

    @property
    def value(self):
        return self.record.value


@dataclasses.dataclass(frozen=True, slots=True)
class LogAppendEntry:
    """One record to append. ``processed=True`` marks a follow-up that the
    processing step already applied to state (replay must skip it)."""

    record: Record
    processed: bool = False

    @classmethod
    def of_processed(cls, record: Record) -> "LogAppendEntry":
        return cls(record, processed=True)


@dataclasses.dataclass(frozen=True, slots=True)
class LoggedRecord:
    """A record as read back from the stream."""

    record: Record
    position: int
    source_position: int
    processed: bool


class LogStreamWriter:
    """Assigns positions and appends batches — Sequencer + appender collapsed
    into one synchronous path (the actor pipeline between them in the reference
    exists to decouple network ingress threads from the io thread; here one
    writer thread per partition owns the log end-to-end)."""

    def __init__(self, stream: "LogStream") -> None:
        self._stream = stream
        self._lock = threading.Lock()
        # histogram sampling tick (1-in-16): per-writer, mutated under
        # self._lock — a module global would race across partitions' writers
        self._m_tick = 0

    def try_write(
        self, entries: list[LogAppendEntry], source_position: int = -1
    ) -> int:
        """Append a batch; returns the position of the last record (or -1 if
        entries is empty). Positions are contiguous within the batch."""
        if not entries:
            return -1
        stream = self._stream
        with self._lock:
            # histograms see a 1-in-16 sample (the reference's hot appenders
            # amortize metric updates the same way); position gauges stay exact
            self._m_tick += 1
            sampled = not (self._m_tick & 15)
            start = time.perf_counter() if sampled else 0.0
            first_position = stream._next_position
            timestamp = stream.clock_millis()
            payload, bodies = _serialize_batch_with_bodies(
                entries, first_position, source_position, timestamp
            )
            jrec = stream.journal.append(payload, asqn=first_position)
            stream._on_appended(first_position, jrec.index)
            stream._next_position = first_position + len(entries)
            last = first_position + len(entries) - 1
            _M_LAST_APPENDED.set(last)
            _M_LAST_COMMITTED.set(last)  # local log: visible on append
            if sampled:
                _M_SEQ_BATCH_SIZE.observe(len(entries))
                _M_SEQ_BATCH_BYTES.observe(len(payload))
                elapsed = time.perf_counter() - start
                _M_APPEND_LATENCY.observe(elapsed)
                _M_COMMIT_LATENCY.observe(elapsed)
            stream._batch_has_commands[jrec.index] = any(
                e.record.is_command and not e.processed for e in entries
            )
            if _TRACER.enabled:
                # stamp unprocessed commands' append time (resolved into
                # command_ack_latency at commit) and register the batch's
                # transitive trace roots so multi-hop chains keep one trace id
                pid = stream.partition_id
                _TRACER.register_batch(pid, first_position, len(entries),
                                       source_position)
                for i, e in enumerate(entries):
                    if e.record.is_command and not e.processed:
                        _TRACER.note_append(pid, first_position + i)
            # seed the decode cache from the in-memory entries: every local
            # append is read back at least twice (processing scan + export),
            # and the bytes round-trip is pure waste for records we hold.
            # The value is re-decoded from the body bytes just written
            # (tuple→list normalization etc.) so a cached read is
            # indistinguishable from a disk read. Oversized rejection reasons
            # are truncated on the wire (Record.encode) — skip seeding then so
            # the cached view never diverges from disk (cheap codepoint-count
            # precheck before paying for the utf-8 encode).
            if any(
                len(e.record.rejection_reason) > 0x3FFF
                and len(e.record.rejection_reason.encode("utf-8")) > 0xFFFF
                for e in entries
            ):
                return last
            pid = stream.partition_id
            seeded = []
            for i, entry in enumerate(entries):
                rec = entry.record
                # positional Record construction (field order = dataclass
                # order; arity drift fails loudly): replace()'s per-field
                # getattr/index plumbing is measurable at wave sizes
                seeded.append(LoggedRecord(
                    record=Record(
                        rec.record_type, rec.value_type, rec.intent,
                        msgpack_unpackb(bodies[i]), rec.key,
                        first_position + i, rec.source_record_position,
                        timestamp, pid, rec.rejection_type,
                        rec.rejection_reason, rec.request_stream_id,
                        rec.request_id, rec.operation_reference,
                    ),
                    position=first_position + i,
                    source_position=source_position,
                    processed=entry.processed,
                ))
            stream._cache_batch(jrec.index, seeded)
        return last

    def append_prepatched(
        self, buf: bytearray, pos_offsets: list[int], ts_offsets: list[int],
        count: int, has_pending_commands: bool = False,
    ) -> int:
        """Append a pre-serialized batch whose only unknown fields are the
        positions and timestamps (the burst-template fast path): patch them
        under the lock and hand the bytes straight to the journal. Returns the
        last record's position. The decode cache is NOT seeded — readers
        decode on demand — but the command-scan skip index is."""
        stream = self._stream
        with self._lock:
            self._m_tick += 1
            sampled = not (self._m_tick & 15)
            start = time.perf_counter() if sampled else 0.0
            first_position = stream._next_position
            timestamp = stream.clock_millis()
            patch_prepatched_batch(buf, pos_offsets, ts_offsets,
                                   first_position, timestamp)
            # the journal copies the buffer into its framed write buffer
            # synchronously, so the bytearray goes straight through — no
            # bytes() copy. Safe: every PreparedBurst buf is freshly built
            # per instantiation and never mutated after this append.
            jrec = stream.journal.append(buf, asqn=first_position)
            stream._on_appended(first_position, jrec.index)
            stream._next_position = first_position + count
            last = first_position + count - 1
            _M_LAST_APPENDED.set(last)
            _M_LAST_COMMITTED.set(last)
            if sampled:
                _M_SEQ_BATCH_SIZE.observe(count)
                _M_SEQ_BATCH_BYTES.observe(len(buf))
                elapsed = time.perf_counter() - start
                _M_APPEND_LATENCY.observe(elapsed)
                _M_COMMIT_LATENCY.observe(elapsed)
            stream._batch_has_commands[jrec.index] = has_pending_commands
        return last


_native_stamp_batch = _native.codec_fn("stamp_batch")


def patch_prepatched_batch(buf: bytearray, pos_offsets, ts_offsets,
                           first_position: int, timestamp: int) -> None:
    """Stamp the only two unknowns of a pre-serialized burst batch — record
    positions and the batch timestamp — at their captured byte offsets
    (shared by the local LogStreamWriter and the broker's Raft writer)."""
    if _native_stamp_batch is not None and type(buf) is bytearray:
        _native_stamp_batch(buf, pos_offsets, ts_offsets, first_position,
                            timestamp)
        return
    for i, off in enumerate(pos_offsets):
        _PACK_LE_Q.pack_into(buf, off, first_position + i)
    for off in ts_offsets:
        _PACK_LE_Q.pack_into(buf, off, timestamp)


def _serialize_batch(
    entries: list[LogAppendEntry], first_position: int, source_position: int, timestamp: int
) -> bytes:
    return _serialize_batch_with_bodies(entries, first_position, source_position, timestamp)[0]


# record-frame encode cache, keyed by Record object identity (stored records
# pin their ids against reuse): a record appended more than once — gateway
# command fan-out, retried scheduled commands, bench injection — serializes
# exactly once; the batch builder patches the timestamp into its own copy at
# the captured offset. Sound because Record is frozen and values are never
# mutated after reaching a writer (the same contract the decode-cache seeding
# in try_write already depends on). Frames are cached only on the SECOND
# encode of the same object: the dominant path appends each record exactly
# once, and caching those would pin thousands of dead frame copies for zero
# hits (the _frame_seen stage pins only the records themselves).
_TS_OFFSET = 20  # timestamp field offset inside the record frame header
_FRAME_CACHE_LIMIT = 4096
_FRAME_SEEN_LIMIT = 4096
_frame_cache: dict[int, tuple[Record, bytes, bytes]] = {}
_frame_seen: dict[int, Record] = {}


def _encoded_frame(record: Record) -> tuple[bytes, bytes]:
    rid = id(record)
    hit = _frame_cache.get(rid)
    if hit is not None and hit[0] is record:
        return hit[1], hit[2]
    frame, body = record.encode(0)  # timestamp patched per batch
    if _frame_seen.get(rid) is record:
        del _frame_seen[rid]
        _evict_oldest_half(_frame_cache, _FRAME_CACHE_LIMIT)
        _frame_cache[rid] = (record, frame, body)
    else:
        _evict_oldest_half(_frame_seen, _FRAME_SEEN_LIMIT)
        _frame_seen[rid] = record
    return frame, body


def _serialize_batch_with_bodies(
    entries: list[LogAppendEntry], first_position: int, source_position: int, timestamp: int
) -> tuple[bytes, list[bytes]]:
    """Serialize into ONE growing buffer; also returns each record's msgpack
    value body so the writer can seed the decode cache without re-encoding
    anything. Record frames come pre-encoded from the identity cache (with
    the batch timestamp patched in place at its fixed header offset) instead
    of a per-``LogAppendEntry`` encode per append."""
    buf = bytearray(_BATCH_HEADER.pack(len(entries), source_position, timestamp))
    bodies: list[bytes] = []
    pack_entry = _ENTRY_HEADER.pack
    pack_ts = _PACK_LE_Q.pack_into
    for i, entry in enumerate(entries):
        frame, body = _encoded_frame(entry.record)
        bodies.append(body)
        buf += pack_entry(1 if entry.processed else 0, first_position + i, len(frame))
        off = len(buf)
        buf += frame
        pack_ts(buf, off + _TS_OFFSET, timestamp)
    return bytes(buf), bodies


def _deserialize_batch(payload: bytes, partition_id: int) -> list[LoggedRecord]:
    count, source_position, timestamp = _BATCH_HEADER.unpack_from(payload, 0)
    off = _BATCH_HEADER.size
    out = []
    for _ in range(count):
        processed, position, length = _ENTRY_HEADER.unpack_from(payload, off)
        off += _ENTRY_HEADER.size
        record = Record.from_bytes(
            payload[off : off + length], position=position,
            partition_id=partition_id, timestamp=timestamp,
        )
        off += length
        out.append(
            LoggedRecord(
                record=record,
                position=position,
                source_position=source_position,
                processed=bool(processed),
            )
        )
    return out


def _record_at_or_after(batch: list["LoggedRecord"], position: int):
    """First record with record.position >= ``position`` in one decoded
    batch, or None past its end. Record positions within a sequenced batch
    are contiguous (first_position + i by construction), so this is direct
    indexing — the command scan over a wave-sized batch (thousands of
    commands in one append) would otherwise rescan the list per command and
    go quadratic. A non-contiguous batch (defensive: never produced by any
    writer) falls back to the linear walk."""
    if not batch:
        return None
    idx = position - batch[0].position
    if idx <= 0:
        return batch[0]
    if idx < len(batch):
        logged = batch[idx]
        if logged.position == position:
            return logged
    elif batch[-1].position < position:
        return None  # truly past the batch even if non-contiguous
    for logged in batch:
        if logged.position >= position:
            return logged
    return None


class LogStreamReader:
    """Sequential reader over the stream from a given position. Keeps a batch
    cursor hint so the sequential case (the only hot one: processing, replay,
    export all walk forward) costs one dict hit instead of a bisect + batch
    rescan per record."""

    def __init__(self, stream: "LogStream", from_position: int = 1) -> None:
        self._stream = stream
        self.seek(from_position)

    def seek(self, position: int) -> None:
        self._position = max(position, 1)
        self._hint = -1

    def seek_to_end(self) -> None:
        self._position = self._stream.last_position + 1
        self._hint = -1

    def __iter__(self) -> Iterator[LoggedRecord]:
        return self

    def __next__(self) -> LoggedRecord:
        rec, self._hint = self._stream.read_with_hint(self._position, self._hint)
        if rec is None:
            raise StopIteration
        self._position = rec.position + 1
        return rec

    def has_next(self) -> bool:
        rec, self._hint = self._stream.read_with_hint(self._position, self._hint)
        return rec is not None


class LogStream:
    """Per-partition log facade; creates readers and exactly one writer.

    Keeps an in-memory batch index — (first position, journal index) per
    sequenced batch, rebuilt from a header-only journal scan on open and
    appended on write — so position lookups are a bisect + one journal entry
    read instead of a log scan (2 ints per batch; a 1M-batch partition costs
    ~16 MB, and snapshots compact the journal long before that).
    """

    def __init__(self, journal: SegmentedJournal, partition_id: int, clock=None) -> None:
        self.journal = journal
        self.partition_id = partition_id
        self.clock_millis = clock or (lambda: int(time.time() * 1000))
        # parallel arrays: batch first positions (sorted) and journal indexes
        self._batch_positions: list[int] = []
        self._batch_indexes: list[int] = []
        # decoded-batch LRU keyed by journal index: the processing reader, the
        # kernel group scanner, and exporters all walk the same recent suffix
        # interleaved, so a single-slot cache thrashes (every read re-decodes
        # a batch); 1024 batches ≈ one processing burst window
        self._batch_cache: dict[int, list[LoggedRecord]] = {}
        # sized so one ingress burst window (thousands of single-command
        # batches) plus its follow-up reads stays decoded end-to-end
        self._batch_cache_limit = 8192
        # journal index → False when the batch is known to contain no
        # unprocessed commands (burst appends): the command scan skips such
        # batches without decoding them. Absent = unknown (must decode).
        self._batch_has_commands: dict[int, bool] = {}
        self.rebuild_index()
        self._writer = LogStreamWriter(self)

    def rebuild_index(self) -> None:
        """Recompute the batch index and next position from the journal
        (call after external journal mutation, e.g. Raft truncation)."""
        self._batch_positions.clear()
        self._batch_indexes.clear()
        self._batch_cache.clear()
        self._batch_has_commands.clear()
        for index, asqn in self.journal.entries_meta():
            if asqn >= 0:
                self._batch_positions.append(asqn)
                self._batch_indexes.append(index)
        if self._batch_positions:
            last_batch = self._read_batch_at(self._batch_indexes[-1])
            self._next_position = last_batch[-1].position + 1
        else:
            self._next_position = 1

    def _read_batch_at(self, journal_index: int) -> list[LoggedRecord]:
        cache = self._batch_cache
        batch = cache.get(journal_index)
        if batch is not None:
            return batch
        jrec = self.journal.read_entry(journal_index)
        if jrec is None:
            return []
        batch = _deserialize_batch(jrec.data, self.partition_id)
        self._cache_batch(journal_index, batch)
        if journal_index not in self._batch_has_commands:
            self._batch_has_commands[journal_index] = any(
                r.record.is_command and not r.processed for r in batch
            )
        return batch

    def _on_appended(self, first_position: int, journal_index: int) -> None:
        self._batch_positions.append(first_position)
        self._batch_indexes.append(journal_index)

    def _cache_batch(self, journal_index: int, batch: list[LoggedRecord]) -> None:
        _evict_oldest_half(self._batch_cache, self._batch_cache_limit)
        self._batch_cache[journal_index] = batch

    @property
    def writer(self) -> LogStreamWriter:
        return self._writer

    def append_committed_payload(self, payload: bytes, first_position: int,
                                 has_pending_commands: bool | None = None) -> None:
        """Materialize a batch that was sequenced elsewhere (the Raft leader)
        and is now committed: the payload embeds its record positions, assigned
        at ingress. Used by the broker partition on leaders AND followers — the
        stream journal holds exactly the committed prefix of the Raft log
        (reference: AtomixLogStorage reads committed Raft entries; we
        materialize them so readers/recovery are identical on every role)."""
        if first_position < self._next_position:
            return  # already materialized (e.g. re-delivered commit)
        jrec = self.journal.append(payload, asqn=first_position)
        self._on_appended(first_position, jrec.index)
        if has_pending_commands is not None:
            # burst batches carry the command-scan skip flag from the leader's
            # append (absent = unknown = decode on demand)
            self._batch_has_commands[jrec.index] = has_pending_commands
        batch = self._read_batch_at(jrec.index)
        self._next_position = batch[-1].position + 1 if batch else first_position + 1
        if _TRACER.enabled and batch:
            # the broker materialization path (leader AND follower): register
            # trace roots so processor/exporter spans resolve transitively
            _TRACER.register_batch(self.partition_id, first_position,
                                   len(batch), batch[0].source_position)

    def serialize_batch(self, entries: list[LogAppendEntry], first_position: int,
                        source_position: int = -1) -> bytes:
        """Sequencer half of the write path: assign positions into a payload
        without appending (the Raft path appends only after quorum commit)."""
        return _serialize_batch(entries, first_position, source_position,
                                self.clock_millis())

    @property
    def last_position(self) -> int:
        return self._next_position - 1

    def compact_to_position(self, position: int) -> int:
        """Compact the backing journal so records whose positions are all
        <= ``position`` can be deleted (whole segments only; the journal's
        ``compact_guard`` — min of snapshot position and exporter cursors —
        clamps further). The batch index arrays are intentionally NOT pruned:
        reader hints are slots into them, and a prune would silently shift
        every live hint; stale leading entries cost 2 ints per batch and
        resolve to empty reads nobody issues (all consumers are past the
        bound by construction). Decoded-batch caches for compacted indexes
        ARE dropped. Returns the journal's new first index."""
        idx = self.journal.seek_to_asqn(position)
        if idx > 1:
            self.journal.compact(idx)
        first = self.journal.first_index
        for stale in [k for k in self._batch_cache if k < first]:
            del self._batch_cache[stale]
        for stale in [k for k in self._batch_has_commands if k < first]:
            del self._batch_has_commands[stale]
        return first

    def new_reader(self, from_position: int = 1) -> LogStreamReader:
        return LogStreamReader(self, from_position)

    def _batch_slot_for(self, position: int) -> int:
        """Index into the batch arrays of the batch that would hold
        ``position`` (greatest first_position <= position), or -1."""
        from bisect import bisect_right

        return bisect_right(self._batch_positions, position) - 1

    def read_at_or_after(self, position: int) -> LoggedRecord | None:
        """First record with record.position >= position, or None."""
        return self.read_with_hint(position, -1)[0]

    def next_command_with_hint(
        self, position: int, hint: int
    ) -> tuple[LoggedRecord | None, int, int]:
        """Like read_with_hint, but for the command scan: whole batches known
        to contain no unprocessed commands (``_batch_has_commands`` is False)
        are skipped without decoding. Returns (record, hint, scan_position):
        the first record at-or-after ``position`` that MAY be an unprocessed
        command (the caller still filters — the skip is an optimization, not a
        contract), and the position the scan safely advanced to (when no
        record is returned the caller may resume from scan_position and never
        rescan the skipped batches)."""
        while True:
            if position > self.last_position:
                return None, hint, position
            slot = self._locate_slot(position, hint)
            has = self._batch_has_commands.get(self._batch_indexes[slot])
            if has is False:
                hint = slot
                if slot + 1 < len(self._batch_positions):
                    position = self._batch_positions[slot + 1]
                    continue
                return None, slot, self.last_position + 1
            batch = self._read_batch_at(self._batch_indexes[slot])
            logged = _record_at_or_after(batch, position)
            if logged is not None:
                return logged, slot, logged.position
            if slot + 1 < len(self._batch_indexes):
                position = self._batch_positions[slot + 1]
                hint = slot + 1
                continue
            return None, slot, self.last_position + 1

    def _locate_slot(self, position: int, hint: int) -> int:
        positions = self._batch_positions
        n = len(positions)
        if 0 <= hint < n and positions[hint] <= position:
            if hint + 1 >= n or positions[hint + 1] > position:
                return hint
            if hint + 2 >= n or positions[hint + 2] > position:
                return hint + 1
        slot = self._batch_slot_for(position)
        return 0 if slot < 0 else slot

    def read_with_hint(self, position: int, hint: int) -> tuple[LoggedRecord | None, int]:
        """``read_at_or_after`` with a batch-slot cursor: ``hint`` is the slot
        the caller last read from (-1 = unknown); returns (record, slot) so
        sequential readers skip the bisect. A stale hint (e.g. after
        rebuild_index truncated the arrays) is detected and falls back."""
        if position > self.last_position:
            return None, hint
        slot = self._locate_slot(position, hint)
        while True:
            batch = self._read_batch_at(self._batch_indexes[slot])
            logged = _record_at_or_after(batch, position)
            if logged is not None:
                return logged, slot
            # position falls in a gap after this batch — or the batch was
            # compacted away (journal read returns empty; the stale index
            # entry is kept so hints stay valid): first record of the next
            if slot + 1 >= len(self._batch_indexes):
                return None, slot
            slot += 1

    def _scan_batches(self, from_position: int):
        """Shared scan skeleton: yields (cached_records, payload) per
        sequenced batch from the one holding ``from_position`` — exactly one
        of the two is non-None. One streaming journal read (a single seek +
        bulk read per segment) instead of a random-access read per batch;
        batches appended after the scan started are excluded."""
        last = self.last_position
        if from_position > last:
            return
        slot = self._batch_slot_for(from_position)
        if slot < 0:
            slot = 0
        cache = self._batch_cache
        for jrec in self.journal.read_from(self._batch_indexes[slot]):
            if jrec.asqn < 0:
                continue
            if jrec.asqn > last:
                return  # appended after this scan started
            cached = cache.get(jrec.index)
            yield (cached, None) if cached is not None else (None, jrec.data)

    def scan(self, from_position: int = 1) -> Iterator[RecordView]:
        """Header-only forward scan from ``from_position``: yields
        ``RecordView``s whose full records (msgpack values) decode lazily on
        first access. The cheap path for filtering consumers — job discovery,
        export filters, metrics sweeps — that inspect header fields of every
        record but need the value of few. Batches already decoded in the cache
        are served from it; undecoded batches are scanned natively without
        populating the cache."""
        from_position = max(from_position, 1)
        pid = self.partition_id
        for cached, payload in self._scan_batches(from_position):
            if cached is not None:
                for logged in cached:
                    if logged.position < from_position:
                        continue
                    rec = logged.record
                    yield RecordView(
                        logged.position, logged.processed,
                        logged.source_position, int(rec.record_type),
                        int(rec.value_type), int(rec.intent), rec.key,
                        None, 0, 0, rec.timestamp, pid, record=rec,
                    )
                continue
            source_position, timestamp, headers = _scan_batch_headers(payload)
            for (processed, position, record_type, value_type, intent, key,
                 off, length) in headers:
                if position < from_position:
                    continue
                yield RecordView(
                    position, bool(processed), source_position, record_type,
                    value_type, intent, key, payload, off, length, timestamp,
                    pid,
                )

    def scan_filtered(self, from_position: int, record_type: int,
                      value_type: int, intent: int | None = None
                      ) -> Iterator[RecordView]:
        """``scan`` that filters on the raw header ints BEFORE building a
        ``RecordView`` — a discovery sweep (job scan, transition count) over
        N records with k matches costs k view objects, not N (uncached
        batches filter inside the native scanner). ``intent=None`` matches
        any intent."""
        from_position = max(from_position, 1)
        pid = self.partition_id
        for cached, payload in self._scan_batches(from_position):
            if cached is not None:
                for logged in cached:
                    if logged.position < from_position:
                        continue
                    rec = logged.record
                    if (int(rec.record_type) != record_type
                            or int(rec.value_type) != value_type
                            or (intent is not None and int(rec.intent) != intent)):
                        continue
                    yield RecordView(
                        logged.position, logged.processed,
                        logged.source_position, record_type,
                        value_type, int(rec.intent), rec.key,
                        None, 0, 0, rec.timestamp, pid, record=rec,
                    )
                continue
            source_position, timestamp, headers = _scan_batch_headers_filtered(
                payload, record_type, value_type,
                -1 if intent is None else intent)
            for (processed, position, rt, vt, it, key, off, length) in headers:
                if position < from_position:
                    continue
                yield RecordView(
                    position, bool(processed), source_position, rt,
                    vt, it, key, payload, off, length, timestamp, pid,
                )

    def read_batch_containing(self, position: int) -> list[LoggedRecord]:
        """The whole sequenced batch holding ``position`` (for batch replay)."""
        slot = self._batch_slot_for(position)
        if slot < 0:
            return []
        batch = self._read_batch_at(self._batch_indexes[slot])
        if batch and batch[0].position <= position <= batch[-1].position:
            return batch
        return []
