"""Exporter SPI: the plugin seam for shipping committed records to sinks.

Reference: exporter-api/src/main/java/io/camunda/zeebe/exporter/api/
Exporter.java — lifecycle ``configure(context) → open(controller) →
export(record)* → close()``; the Controller exposes
``updateLastExportedRecordPosition`` (bounds log compaction) and
``scheduleCancellableTask`` (flush timers).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from zeebe_tpu.logstreams import LoggedRecord


@dataclass
class ExporterContext:
    """Configuration handed to the exporter before open (reference:
    Exporter#configure(Context) — id, configuration map, record filter)."""

    exporter_id: str
    configuration: dict[str, Any] = field(default_factory=dict)
    # optional record filter: (record_type_name, value_type_name) -> bool
    record_filter: Callable[[LoggedRecord], bool] | None = None


class ExporterController:
    """Hands the exporter its position-acknowledgement, durable metadata, and
    task scheduling (reference: exporter-api Controller —
    updateLastExportedRecordPosition(position, metadata) and readMetadata;
    ExporterContainer implements it)."""

    def __init__(self, on_position: Callable[[int], None],
                 schedule: Callable[[int, Callable[[], None]], Any] | None = None,
                 on_metadata: Callable[[bytes], None] | None = None,
                 read_metadata: Callable[[], bytes | None] | None = None) -> None:
        self._on_position = on_position
        self._schedule = schedule
        self._on_metadata = on_metadata
        self._read_metadata = read_metadata

    def update_last_exported_position(self, position: int,
                                      metadata: bytes | None = None) -> None:
        if metadata is not None and self._on_metadata is not None:
            self._on_metadata(metadata)
        self._on_position(position)

    def read_metadata(self) -> bytes | None:
        """Durable exporter-private state persisted with the position acks
        (reference: Controller#readMetadata — the ES exporter keeps its
        record-sequence counters here so restarts do not reset sequences)."""
        return self._read_metadata() if self._read_metadata is not None else None

    def schedule_task(self, delay_millis: int, task: Callable[[], None]) -> Any:
        if self._schedule is None:
            raise RuntimeError("scheduling not available in this context")
        return self._schedule(delay_millis, task)


class Exporter:
    """Base class; subclasses override what they need (reference default
    methods on the Exporter interface)."""

    def configure(self, context: ExporterContext) -> None:
        self.context = context

    def open(self, controller: ExporterController) -> None:
        self.controller = controller

    def export(self, record: LoggedRecord) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass
