"""Exporter SPI: the plugin seam for shipping committed records to sinks.

Reference: exporter-api/src/main/java/io/camunda/zeebe/exporter/api/
Exporter.java — lifecycle ``configure(context) → open(controller) →
export(record)* → close()``; the Controller exposes
``updateLastExportedRecordPosition`` (bounds log compaction) and
``scheduleCancellableTask`` (flush timers).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from zeebe_tpu.logstreams import LoggedRecord


@dataclass
class ExporterContext:
    """Configuration handed to the exporter before open (reference:
    Exporter#configure(Context) — id, configuration map, record filter)."""

    exporter_id: str
    configuration: dict[str, Any] = field(default_factory=dict)
    # optional record filter: (record_type_name, value_type_name) -> bool
    record_filter: Callable[[LoggedRecord], bool] | None = None


class ExporterController:
    """Hands the exporter its position-acknowledgement, durable metadata, and
    task scheduling (reference: exporter-api Controller —
    updateLastExportedRecordPosition(position, metadata) and readMetadata;
    ExporterContainer implements it)."""

    def __init__(self, on_position: Callable[..., None],
                 schedule: Callable[[int, Callable[[], None]], Any] | None = None,
                 on_metadata: Callable[[bytes], None] | None = None,
                 read_metadata: Callable[[], bytes | None] | None = None) -> None:
        import inspect

        self._on_position = on_position
        # a two-parameter on_position receives (position, metadata) in ONE
        # call so the host can persist both atomically; single-parameter
        # callbacks (tests / custom hosts) keep the split delivery
        try:
            params = inspect.signature(on_position).parameters.values()
            positional = [
                p for p in params
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                              p.VAR_POSITIONAL)
            ]
            # only positional capacity counts: `lambda p, **kw` is still a
            # one-arg callback under the old Callable[[int], None] contract
            self._atomic = (len(positional) >= 2
                            or any(p.kind == p.VAR_POSITIONAL for p in positional))
        except (TypeError, ValueError):
            self._atomic = False
        self._schedule = schedule
        self._on_metadata = on_metadata
        self._read_metadata = read_metadata

    def update_last_exported_position(self, position: int,
                                      metadata: bytes | None = None) -> None:
        # position and metadata must land atomically where the host supports
        # it: a crash between two separate writes would leave sequence
        # counters ahead of the acked position, and re-exported records would
        # re-index with different sequences (reference: ExporterContainer
        # persists both in the ExportersState in one transaction)
        if self._atomic:
            self._on_position(position, metadata)
            return
        if metadata is not None and self._on_metadata is not None:
            self._on_metadata(metadata)
        self._on_position(position)

    def read_metadata(self) -> bytes | None:
        """Durable exporter-private state persisted with the position acks
        (reference: Controller#readMetadata — the ES exporter keeps its
        record-sequence counters here so restarts do not reset sequences)."""
        return self._read_metadata() if self._read_metadata is not None else None

    def schedule_task(self, delay_millis: int, task: Callable[[], None]) -> Any:
        if self._schedule is None:
            raise RuntimeError("scheduling not available in this context")
        return self._schedule(delay_millis, task)


class Exporter:
    """Base class; subclasses override what they need (reference default
    methods on the Exporter interface)."""

    def configure(self, context: ExporterContext) -> None:
        self.context = context

    def open(self, controller: ExporterController) -> None:
        self.controller = controller

    def export(self, record: LoggedRecord) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass
