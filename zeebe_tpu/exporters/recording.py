"""RecordingExporter: the behavioral-assertion test harness.

Reference: test-util/src/main/java/io/camunda/zeebe/test/util/record/
RecordingExporter.java:77 — every record written to the stream is captured and
tests assert on filtered record streams (``records().process_instance()
.with_intent(ELEMENT_COMPLETED).first()``). This is the parity oracle: the
same scenario run on the reference and here must produce equivalent streams.
"""

from __future__ import annotations

from typing import Callable, Iterator

from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol import Record, RecordType, ValueType
from zeebe_tpu.protocol.intent import Intent


class RecordStream:
    """Chainable filter over captured records."""

    def __init__(self, records: list[LoggedRecord]) -> None:
        self._records = records
        self._filters: list[Callable[[LoggedRecord], bool]] = []

    def _with(self, predicate: Callable[[LoggedRecord], bool]) -> "RecordStream":
        clone = RecordStream(self._records)
        clone._filters = self._filters + [predicate]
        return clone

    def with_value_type(self, value_type: ValueType) -> "RecordStream":
        return self._with(lambda r: r.record.value_type == value_type)

    def with_intent(self, intent: Intent) -> "RecordStream":
        return self._with(lambda r: r.record.intent == intent)

    def with_record_type(self, record_type: RecordType) -> "RecordStream":
        return self._with(lambda r: r.record.record_type == record_type)

    def events(self) -> "RecordStream":
        return self._with(lambda r: r.record.is_event)

    def commands(self) -> "RecordStream":
        return self._with(lambda r: r.record.is_command)

    def rejections(self) -> "RecordStream":
        return self._with(lambda r: r.record.is_rejection)

    def with_element_id(self, element_id: str) -> "RecordStream":
        return self._with(lambda r: r.record.value.get("elementId") == element_id)

    def with_element_type(self, element_type) -> "RecordStream":
        return self._with(lambda r: r.record.value.get("bpmnElementType") == element_type.name)

    def with_process_instance_key(self, key: int) -> "RecordStream":
        return self._with(lambda r: r.record.value.get("processInstanceKey") == key)

    def with_key(self, key: int) -> "RecordStream":
        return self._with(lambda r: r.record.key == key)

    def with_value(self, **fields) -> "RecordStream":
        return self._with(
            lambda r: all(r.record.value.get(k) == v for k, v in fields.items())
        )

    # terminals

    def __iter__(self) -> Iterator[LoggedRecord]:
        for rec in self._records:
            if all(f(rec) for f in self._filters):
                yield rec

    def to_list(self) -> list[LoggedRecord]:
        return list(self)

    def first(self) -> LoggedRecord:
        for rec in self:
            return rec
        raise AssertionError(f"no record matched (captured {len(self._records)} records)")

    def exists(self) -> bool:
        return next(iter(self), None) is not None

    def count(self) -> int:
        return sum(1 for _ in self)

    def intent_sequence(self) -> list[str]:
        """Intent names in stream order — the shape used in parity assertions."""
        return [r.record.intent.name for r in self]


class RecordingExporter:
    def __init__(self) -> None:
        self.records: list[LoggedRecord] = []

    def export(self, record: LoggedRecord) -> None:
        self.records.append(record)

    def clear(self) -> None:
        self.records.clear()

    # filtered views (naming mirrors the reference's static accessors)

    def all(self) -> RecordStream:
        return RecordStream(self.records)

    def process_instance_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.PROCESS_INSTANCE)

    def job_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.JOB)

    def job_batch_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.JOB_BATCH)

    def deployment_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.DEPLOYMENT)

    def process_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.PROCESS)

    def variable_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.VARIABLE)

    def incident_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.INCIDENT)

    def timer_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.TIMER)

    def message_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.MESSAGE)

    def signal_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.SIGNAL)

    def signal_subscription_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.SIGNAL_SUBSCRIPTION)

    def escalation_records(self) -> RecordStream:
        return self.all().with_value_type(ValueType.ESCALATION)
