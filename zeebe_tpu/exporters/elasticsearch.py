"""Elasticsearch/OpenSearch-compatible exporter.

Reference: exporters/elasticsearch-exporter/src/main/java/io/camunda/zeebe/
exporter/ElasticsearchExporter.java — converts records to JSON documents,
batches them into a bulk request (one action line + one source line per
record, the ES `_bulk` NDJSON format), indexes per value-type-and-date
(``zeebe-record_<valueType>_<version>_<date>``), flushes on bulk size/memory/
interval, acks the last flushed position.

No network egress in this environment, so the bulk sink is pluggable: the
default writes NDJSON bulk files to a directory (one file per flush); a
callable sink receives the raw NDJSON payload and can POST it to a real
cluster. The document shape matches the reference's record JSON (camelCase
fields via ``Record.to_json_dict``).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.logstreams import LoggedRecord

INDEX_PREFIX = "zeebe-record"
VERSION = "8.4.0"


class ElasticsearchExporter(Exporter):
    def __init__(self, sink: Callable[[str], None] | None = None,
                 directory: str | Path | None = None,
                 bulk_size: int = 1_000) -> None:
        if sink is None and directory is None:
            raise ValueError("need a sink callable or a bulk-file directory")
        self._directory = Path(directory) if directory else None
        if self._directory is not None:
            self._directory.mkdir(parents=True, exist_ok=True)
        self._sink = sink
        self.bulk_size = bulk_size
        self._bulk: list[str] = []
        self._bulk_last_position = -1
        self._flush_count = 0

    # -- lifecycle -------------------------------------------------------------

    def configure(self, context: ExporterContext) -> None:
        super().configure(context)
        self.bulk_size = context.configuration.get("bulkSize", self.bulk_size)

    def export(self, record: LoggedRecord) -> None:
        doc = record.record.to_json_dict()
        doc["position"] = record.position
        index = self._index_for(record)
        doc_id = f"{record.position}-{doc.get('partitionId', 1)}"
        self._bulk.append(json.dumps(
            {"index": {"_index": index, "_id": doc_id}}, separators=(",", ":")
        ))
        self._bulk.append(json.dumps(doc, separators=(",", ":"), default=_json_default))
        self._bulk_last_position = record.position
        if len(self._bulk) // 2 >= self.bulk_size:
            self.flush()

    def flush(self) -> None:
        if not self._bulk:
            return
        payload = "\n".join(self._bulk) + "\n"
        from zeebe_tpu.utils.metrics import REGISTRY

        REGISTRY.histogram(
            "bulk_size", "records per exporter bulk flush",
            buckets=(1, 10, 100, 500, 1000, 5000)).observe(len(self._bulk) // 2)
        REGISTRY.histogram(
            "bulk_memory_size", "bytes per exporter bulk flush",
            buckets=(1024, 16384, 262144, 1 << 20, 16 << 20)
        ).observe(len(payload))
        if self._sink is not None:
            self._sink(payload)
        if self._directory is not None:
            path = self._directory / f"bulk-{self._flush_count:08d}.ndjson"
            path.write_text(payload)
        self._flush_count += 1
        self._bulk.clear()
        self.controller.update_last_exported_position(self._bulk_last_position)

    def close(self) -> None:
        self.flush()

    # -- helpers ---------------------------------------------------------------

    def _index_for(self, record: LoggedRecord) -> str:
        value_type = record.record.value_type.name.lower().replace("_", "-")
        day = _day_of(record.record.timestamp)
        return f"{INDEX_PREFIX}_{value_type}_{VERSION}_{day}"


def _day_of(timestamp_millis: int) -> str:
    import datetime

    dt = datetime.datetime.fromtimestamp(timestamp_millis / 1000, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%d")


def _json_default(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")
