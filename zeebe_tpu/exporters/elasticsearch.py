"""Elasticsearch exporter with authentication, index templating, and
ILM-based retention, plus the OpenSearch variant.

Reference: exporters/elasticsearch-exporter/src/main/java/io/camunda/zeebe/
exporter/ — ElasticsearchExporter.java (bulk NDJSON flush on size/memory/
delay, record counters for the ``sequence`` field), RecordIndexRouter.java
(index ``<prefix>_<valueType>_<version>_<date>``, id ``<partition>-<position>``,
alias ``<prefix>-<valueType>``), TemplateReader.java (component + per-value-
type index templates, shard/replica/ILM substitution),
ElasticsearchExporterConfiguration.java:26-33,305-333 (IndexConfiguration
record/value-type toggles, BulkConfiguration, AuthenticationConfiguration
basic auth, RetentionConfiguration ILM policy), ElasticsearchClient.java:210
(PUT /_ilm/policy with a delete phase at minimum_age);
exporters/opensearch-exporter/ (same surface minus ILM, plus AWS request
signing).

No network egress in this environment, so transport is pluggable: every HTTP
request the exporter would issue (templates, policy, bulks) goes through a
``transport(method, path, headers, body)`` callable. The default directory
transport writes bulk NDJSON files plus ``setup-*.json`` request captures; a
real deployment supplies an HTTP transport. The legacy ``sink(payload)``
callable still receives raw bulk payloads.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from pathlib import Path
from typing import Any, Callable

from zeebe_tpu.exporters.api import Exporter, ExporterContext
from zeebe_tpu.logstreams import LoggedRecord
from zeebe_tpu.protocol.enums import RecordType, ValueType

INDEX_PREFIX = "zeebe-record"
VERSION = "8.4.0"

# value types exported by default (reference IndexConfiguration defaults:
# ElasticsearchExporterConfiguration.java:154-185 — jobBatch, messageBatch,
# processInstanceBatch, checkpoint, and processEvent default to false)
_DEFAULT_OFF = {
    ValueType.JOB_BATCH,
    ValueType.PROCESS_INSTANCE_BATCH,
    ValueType.CHECKPOINT,
    ValueType.PROCESS_EVENT,
}


@dataclasses.dataclass
class IndexConfiguration:
    """Which record/value types to export, and template/index settings
    (reference: IndexConfiguration)."""

    prefix: str = INDEX_PREFIX
    create_template: bool = True
    # record types
    command: bool = False
    event: bool = True
    rejection: bool = False
    # value-type toggles: absent → reference default
    value_types: dict[ValueType, bool] = dataclasses.field(default_factory=dict)
    number_of_shards: int | None = None
    number_of_replicas: int | None = None

    def should_index_value_type(self, value_type: ValueType) -> bool:
        override = self.value_types.get(value_type)
        if override is not None:
            return override
        return value_type not in _DEFAULT_OFF

    def should_index_record_type(self, record_type: RecordType) -> bool:
        if record_type == RecordType.EVENT:
            return self.event
        if record_type == RecordType.COMMAND:
            return self.command
        if record_type == RecordType.COMMAND_REJECTION:
            return self.rejection
        return False


@dataclasses.dataclass
class BulkConfiguration:
    """Flush thresholds (reference: BulkConfiguration — delay seconds,
    record count, memory bytes)."""

    delay_seconds: int = 5
    size: int = 1_000
    memory_limit: int = 10 * 1024 * 1024


@dataclasses.dataclass
class AuthenticationConfiguration:
    """Basic (username/password) or API-key auth; becomes an Authorization
    header on every request (reference: AuthenticationConfiguration +
    RestClientFactory basic-auth wiring)."""

    username: str | None = None
    password: str | None = None
    api_key: str | None = None

    def is_present(self) -> bool:
        return bool(self.username and self.password) or bool(self.api_key)

    def header(self) -> dict[str, str]:
        if self.api_key:
            return {"Authorization": f"ApiKey {self.api_key}"}
        if self.username and self.password:
            token = base64.b64encode(
                f"{self.username}:{self.password}".encode()
            ).decode()
            return {"Authorization": f"Basic {token}"}
        return {}


@dataclasses.dataclass
class RetentionConfiguration:
    """Index lifecycle: delete indices older than minimum_age via an ILM
    policy referenced from every index template (reference:
    RetentionConfiguration + ElasticsearchClient.putIndexLifecycleManagementPolicy)."""

    enabled: bool = False
    minimum_age: str = "30d"
    policy_name: str = "zeebe-record-retention-policy"


@dataclasses.dataclass
class AwsConfiguration:
    """OpenSearch-only: SigV4-sign requests for Amazon OpenSearch Service
    (reference: OpensearchExporterConfiguration.AwsConfiguration)."""

    enabled: bool = False
    region: str = "eu-west-1"
    service_name: str = "es"
    access_key: str = ""
    secret_key: str = ""


class ElasticsearchExporter(Exporter):
    def __init__(self, sink: Callable[[str], None] | None = None,
                 directory: str | Path | None = None,
                 bulk_size: int | None = None,
                 transport: Callable[[str, str, dict, str], Any] | None = None,
                 index: IndexConfiguration | None = None,
                 bulk: BulkConfiguration | None = None,
                 authentication: AuthenticationConfiguration | None = None,
                 retention: RetentionConfiguration | None = None) -> None:
        if sink is None and directory is None and transport is None:
            raise ValueError("need a sink callable, transport, or a bulk-file directory")
        self._directory = Path(directory) if directory else None
        if self._directory is not None:
            self._directory.mkdir(parents=True, exist_ok=True)
        self._sink = sink
        self._transport = transport
        self.index = index or IndexConfiguration()
        self.bulk = bulk or BulkConfiguration()
        if bulk_size is not None:
            self.bulk.size = bulk_size
        self.authentication = authentication or AuthenticationConfiguration()
        self.retention = retention or RetentionConfiguration()
        self._bulk: list[str] = []
        self._bulk_bytes = 0
        self._bulk_last_position = -1
        self._flush_count = 0
        self._setup_count = 0
        self._setup_done = False
        # per-value-type record counters feeding the `sequence` field
        # (reference: ElasticsearchRecordCounters + RecordSequence —
        # sequence = (partitionId << 51) + counter)
        self._counters: dict[str, int] = {}
        # bounded request capture for tests/diagnostics: bulk BODIES are
        # elided (they already reach the sink/directory/transport) so a
        # long-running broker does not accumulate payload strings
        from collections import deque

        self.requests: deque[tuple[str, str, str]] = deque(maxlen=256)
        from zeebe_tpu.utils.metrics import REGISTRY

        # registered at construction (reference: ElasticsearchMetrics is
        # created with the exporter, not on first flush)
        self._bulk_size_metric = REGISTRY.histogram(
            "bulk_size", "records per exporter bulk flush",
            buckets=(1, 10, 100, 500, 1000, 5000))
        self._bulk_memory_metric = REGISTRY.histogram(
            "bulk_memory_size", "bytes per exporter bulk flush",
            buckets=(1024, 16384, 262144, 1 << 20, 16 << 20))

    # convenience alias kept for existing callers/tests
    @property
    def bulk_size(self) -> int:
        return self.bulk.size

    @bulk_size.setter
    def bulk_size(self, v: int) -> None:
        self.bulk.size = v

    # -- lifecycle -------------------------------------------------------------

    def configure(self, context: ExporterContext) -> None:
        super().configure(context)
        # filtering happens DIRECTOR-side via the context filter (reference:
        # ElasticsearchExporter.configure → context.setFilter): skipped
        # records still advance the exporter position, so compaction and
        # re-delivery never stall on a run of filtered records
        context.record_filter = self._should_index
        cfg = context.configuration
        self.bulk.size = cfg.get("bulkSize", self.bulk.size)
        self.bulk.delay_seconds = cfg.get("bulkDelay", self.bulk.delay_seconds)
        self.bulk.memory_limit = cfg.get("bulkMemoryLimit", self.bulk.memory_limit)
        auth = cfg.get("authentication", {})
        if auth:
            self.authentication = AuthenticationConfiguration(
                username=auth.get("username"), password=auth.get("password"),
                api_key=auth.get("apiKey"),
            )
        ret = cfg.get("retention", {})
        if ret:
            self.retention = RetentionConfiguration(
                enabled=ret.get("enabled", False),
                minimum_age=ret.get("minimumAge", self.retention.minimum_age),
                policy_name=ret.get("policyName", self.retention.policy_name),
            )

    def open(self, controller) -> None:
        super().open(controller)
        # restore the per-value-type sequence counters persisted alongside
        # position acks, so a restart continues sequences instead of
        # restarting at 1 (reference: ElasticsearchExporterMetadata)
        meta = controller.read_metadata()
        if meta:
            try:
                self._counters = {
                    str(k): int(v) for k, v in json.loads(meta.decode()).items()
                }
            except (ValueError, AttributeError):
                pass  # unreadable metadata: keep fresh counters
        self._schedule_delayed_flush()

    def _schedule_delayed_flush(self) -> None:
        """Periodic flush (reference: ElasticsearchExporter.scheduleDelayedFlush);
        a no-op when the hosting context offers no scheduler (tests driving
        flush() directly)."""
        try:
            self.controller.schedule_task(
                self.bulk.delay_seconds * 1000, self._flush_and_reschedule
            )
        except (RuntimeError, AttributeError):
            pass

    def _flush_and_reschedule(self) -> None:
        try:
            self.flush()
        finally:
            self._schedule_delayed_flush()

    def _should_index(self, record: LoggedRecord) -> bool:
        rec = record.record
        return (self.index.should_index_record_type(rec.record_type)
                and self.index.should_index_value_type(rec.value_type))

    def export(self, record: LoggedRecord) -> None:
        if not self._setup_done:
            self._setup()
        rec = record.record
        if not self._should_index(record):
            # direct callers without a director-side filter: drop but ack
            self._bulk_last_position = record.position
            if not self._bulk:
                self.controller.update_last_exported_position(record.position)
            return
        doc = rec.to_json_dict()
        doc["position"] = record.position
        vt = rec.value_type.name
        counter = self._counters.get(vt, 0) + 1
        self._counters[vt] = counter
        doc["sequence"] = (doc.get("partitionId", 1) << 51) + counter
        index = self._index_for(record)
        doc_id = f"{doc.get('partitionId', 1)}-{record.position}"
        action = json.dumps(
            {"index": {"_index": index, "_id": doc_id,
                       "routing": str(doc.get("partitionId", 1))}},
            separators=(",", ":"),
        )
        source = json.dumps(doc, separators=(",", ":"), default=_json_default)
        self._bulk.append(action)
        self._bulk.append(source)
        self._bulk_bytes += len(action) + len(source) + 2
        self._bulk_last_position = record.position
        if (len(self._bulk) // 2 >= self.bulk.size
                or self._bulk_bytes >= self.bulk.memory_limit):
            self.flush()

    def flush(self) -> None:
        if not self._bulk:
            return
        payload = "\n".join(self._bulk) + "\n"
        self._bulk_size_metric.observe(len(self._bulk) // 2)
        self._bulk_memory_metric.observe(len(payload))
        if self._sink is not None:
            self._sink(payload)
        if self._directory is not None:
            path = self._directory / f"bulk-{self._flush_count:08d}.ndjson"
            path.write_text(payload)
        self._request("POST", "/_bulk", payload)
        self._flush_count += 1
        self._bulk.clear()
        self._bulk_bytes = 0
        self.controller.update_last_exported_position(
            self._bulk_last_position,
            metadata=json.dumps(self._counters, separators=(",", ":")).encode(),
        )

    def close(self) -> None:
        self.flush()

    # -- index/template management --------------------------------------------

    def _setup(self) -> None:
        """One-time index plumbing before the first export (reference:
        ElasticsearchExporter.export → createIndexTemplates once):
        retention policy, shared component template, one index template per
        exported value type. `_setup_done` flips only after every request
        went through — a transport failure leaves setup pending so the
        director's retry re-attempts it."""
        if self.index.create_template:
            if self.retention.enabled:
                self._put_retention_policy()
            self._put_request(
                f"/_component_template/{self.index.prefix}",
                {"template": {"settings": self._index_settings()}},
            )
            for vt in ValueType:
                if self.index.should_index_value_type(vt):
                    self._put_index_template(vt)
        self._setup_done = True

    def _index_settings(self) -> dict:
        settings: dict[str, Any] = {}
        if self.index.number_of_shards is not None:
            settings["number_of_shards"] = self.index.number_of_shards
        if self.index.number_of_replicas is not None:
            settings["number_of_replicas"] = self.index.number_of_replicas
        if self.retention.enabled:
            settings["index.lifecycle.name"] = self.retention.policy_name
        return settings

    def _put_index_template(self, value_type: ValueType) -> None:
        vt = value_type.name.lower().replace("_", "-")
        search_pattern = f"{self.index.prefix}_{vt}_*"
        alias = f"{self.index.prefix}-{vt}"
        template = {
            "index_patterns": [search_pattern],
            "composed_of": [self.index.prefix],
            "priority": 20,
            "template": {
                "aliases": {alias: {}},
                "settings": self._index_settings(),
            },
        }
        self._put_request(f"/_index_template/{self.index.prefix}_{vt}", template)

    def _put_retention_policy(self) -> None:
        policy = {
            "policy": {
                "phases": {
                    "delete": {
                        "min_age": self.retention.minimum_age,
                        "actions": {"delete": {}},
                    }
                }
            }
        }
        self._put_request(f"/_ilm/policy/{self.retention.policy_name}", policy)

    def _put_request(self, path: str, body: dict) -> None:
        payload = json.dumps(body, separators=(",", ":"))
        if self._directory is not None:
            name = f"setup-{self._setup_count:04d}{path.replace('/', '_')}.json"
            (self._directory / name).write_text(payload)
            self._setup_count += 1
        self._request("PUT", path, payload)

    def _request(self, method: str, path: str, body: str) -> None:
        self.requests.append((method, path, "" if path == "/_bulk" else body))
        if self._transport is not None:
            self._transport(method, path, self._headers(method, path, body), body)

    def _headers(self, method: str, path: str, body: str) -> dict[str, str]:
        headers = {"Content-Type": "application/x-ndjson" if path == "/_bulk"
                   else "application/json"}
        headers.update(self.authentication.header())
        return headers

    # -- helpers ---------------------------------------------------------------

    def _index_for(self, record: LoggedRecord) -> str:
        value_type = record.record.value_type.name.lower().replace("_", "-")
        day = _day_of(record.record.timestamp)
        return f"{self.index.prefix}_{value_type}_{VERSION}_{day}"


class OpensearchExporter(ElasticsearchExporter):
    """OpenSearch variant (reference: exporters/opensearch-exporter/) —
    identical bulk/index/template surface, no ILM (OpenSearch uses ISM
    plugins; the reference variant ships no retention either), optional AWS
    SigV4 request signing for Amazon OpenSearch Service."""

    def __init__(self, *args, aws: AwsConfiguration | None = None, **kw) -> None:
        if kw.get("retention") is not None and kw["retention"].enabled:
            raise ValueError(
                "OpenSearch retention is managed by ISM plugins, not ILM; "
                "the opensearch exporter accepts no retention configuration"
            )
        kw["retention"] = RetentionConfiguration(enabled=False)
        super().__init__(*args, **kw)
        self.aws = aws or AwsConfiguration()

    def configure(self, context: ExporterContext) -> None:
        if context.configuration.get("retention", {}).get("enabled"):
            raise ValueError(
                "OpenSearch retention is managed by ISM plugins, not ILM; "
                "remove the retention block from the exporter configuration"
            )
        super().configure(context)

    def _headers(self, method: str, path: str, body: str) -> dict[str, str]:
        headers = super()._headers(method, path, body)
        if self.aws.enabled:
            import datetime
            import hashlib

            from zeebe_tpu.backup.s3 import sign_v4

            amz_date = datetime.datetime.now(datetime.timezone.utc).strftime(
                "%Y%m%dT%H%M%SZ"
            )
            payload_hash = hashlib.sha256(body.encode()).hexdigest()
            host = f"{self.aws.service_name}.{self.aws.region}.amazonaws.com"
            headers["x-amz-date"] = amz_date
            headers["x-amz-content-sha256"] = payload_hash
            headers["Authorization"] = sign_v4(
                method, host, path, {},
                {"x-amz-date": amz_date, "x-amz-content-sha256": payload_hash},
                payload_hash, self.aws.region, self.aws.service_name,
                self.aws.access_key, self.aws.secret_key, amz_date,
            )
        return headers


def _day_of(timestamp_millis: int) -> str:
    import datetime

    dt = datetime.datetime.fromtimestamp(timestamp_millis / 1000, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%d")


def _json_default(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    raise TypeError(f"not JSON serializable: {type(obj).__name__}")
