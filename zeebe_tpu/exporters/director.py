"""ExporterDirector: drives all configured exporters over committed records.

Reference: broker/src/main/java/io/camunda/zeebe/broker/exporter/stream/
ExporterDirector.java:51 — an actor per partition reading the log *after*
commit (readNextEvent/exportEvent :389-431), wrapping each exporter in an
ExporterContainer, persisting exporter positions into the EXPORTER column
family (ExportersState), and reporting the minimum acknowledged position so
log compaction never deletes unexported records.

Here the director is pump-driven like the stream processor (the broker pump
calls ``export_available()`` after each processing round); an exporter that
throws is retried on the same record forever (reference behavior: export is
at-least-once, the director does not skip)."""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.logstreams import LogStream
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF


class ExporterContainer:
    def __init__(self, exporter_id: str, exporter: Exporter,
                 state: "ExportersState",
                 configuration: dict | None = None,
                 partition_id: int = 0) -> None:
        self.exporter_id = exporter_id
        self.exporter = exporter
        self.state = state
        self.position = state.position(exporter_id)
        # highest position handed to the exporter but not yet acked; a skip may
        # only advance the persisted position when nothing is pending, or a
        # crash-before-flush loses the buffered records to compaction
        # (reference: ExporterContainer.updateLastExportedRecordPosition)
        self.last_delivered = self.position
        exporter.configure(ExporterContext(exporter_id, configuration or {}))
        exporter.open(ExporterController(self._update_position))
        from zeebe_tpu.utils.metrics import REGISTRY

        # labeled per (exporter, partition): each child is incremented by
        # exactly one partition ownership thread, so the non-atomic
        # Counter.inc never races
        self._m_exported = REGISTRY.counter(
            "exporter_events_exported_total",
            "records handed to an exporter", ("exporter", "partition")
        ).labels(exporter_id, str(partition_id))

    def deliver(self, record) -> None:
        self.last_delivered = record.position
        self.exporter.export(record)
        self._m_exported.inc()

    def skip(self, position: int) -> None:
        if self.last_delivered <= self.position:  # nothing unacked in flight
            self._update_position(position)
        self.last_delivered = max(self.last_delivered, position)

    def _update_position(self, position: int) -> None:
        if position > self.position:
            self.position = position
            self.state.set_position(self.exporter_id, position)


class ExportersState:
    """Exporter positions in the EXPORTER column family (reference:
    broker/…/exporter/stream/ExportersState.java)."""

    def __init__(self, db: ZbDb) -> None:
        self.db = db
        self._cf = db.column_family(CF.EXPORTER)

    def position(self, exporter_id: str) -> int:
        with self.db.transaction():
            return self._cf.get((exporter_id,)) or 0

    def set_position(self, exporter_id: str, position: int) -> None:
        with self.db.transaction():
            self._cf.put((exporter_id,), position)

    def remove(self, exporter_id: str) -> None:
        with self.db.transaction():
            if self._cf.exists((exporter_id,)):
                self._cf.delete((exporter_id,))

    def lowest_position(self) -> int:
        with self.db.transaction():
            positions = list(self._cf.values())
        return min(positions) if positions else -1


class ExporterDirector:
    def __init__(self, stream: LogStream, db: ZbDb,
                 exporters: dict[str, Exporter],
                 configurations: dict[str, dict] | None = None,
                 commit_position: Callable[[], int] | None = None) -> None:
        self.stream = stream
        self.state = ExportersState(db)
        self.containers = [
            ExporterContainer(eid, exp, self.state,
                              (configurations or {}).get(eid),
                              partition_id=stream.partition_id)
            for eid, exp in exporters.items()
        ]
        # committed-position supplier: records past it are not yet safe to
        # export (Raft quorum); None = everything in the log is committed
        self.commit_position = commit_position
        # resume from the lowest acknowledged position (a restarted exporter
        # re-sees records after its last ack — at-least-once)
        self._next_position = min(
            (c.position for c in self.containers), default=0
        ) + 1

    def export_available(self, max_records: int = 10_000) -> int:
        """Export committed records not yet seen; returns how many."""
        count = 0
        limit = self.commit_position() if self.commit_position else None
        for logged in self.stream.new_reader(self._next_position):
            if limit is not None and logged.position > limit:
                break
            for container in self.containers:
                if logged.position <= container.position:
                    continue  # already acked by this exporter (restart resume)
                ctx = container.exporter.context
                if ctx.record_filter is not None and not ctx.record_filter(logged):
                    container.skip(logged.position)
                    continue
                container.deliver(logged)
            self._next_position = logged.position + 1
            count += 1
            if count >= max_records:
                break
        return count

    def lowest_exporter_position(self) -> int:
        """Log compaction bound (reference: min exporter position vs snapshot
        position, AsyncSnapshotDirector). Uses the containers' in-memory
        positions (0 until first ack) so a bulk exporter that never flushed
        still pins the log."""
        if not self.containers:
            return 2**62
        return min(c.position for c in self.containers)

    def close(self) -> None:
        for container in self.containers:
            container.exporter.close()
