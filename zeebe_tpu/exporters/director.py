"""ExporterDirector: drives all configured exporters over committed records.

Reference: broker/src/main/java/io/camunda/zeebe/broker/exporter/stream/
ExporterDirector.java:51 — an actor per partition reading the log *after*
commit (readNextEvent/exportEvent :389-431), wrapping each exporter in an
ExporterContainer, persisting exporter positions into the EXPORTER column
family (ExportersState), and reporting the minimum acknowledged position so
log compaction never deletes unexported records.

Here the director is pump-driven like the stream processor (the broker pump
calls ``export_available()`` after each processing round); an exporter that
throws is retried on the same record forever (reference behavior: export is
at-least-once, the director does not skip)."""

from __future__ import annotations

from typing import Callable

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.logstreams import LogStream
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF


class ExecutionLatencyObserver:
    """Creation→completion latency metrics, computed on the committed
    record stream like the reference's broker exporter metrics (reference:
    broker/…/exporter/metrics/ExecutionLatencyMetrics.java) — so the kernel
    burst-template path is counted exactly like the sequential path."""

    _MAX_TRACKED = 32_768

    def __init__(self, partition_id: int) -> None:
        from zeebe_tpu.utils.metrics import REGISTRY

        pid = str(partition_id)
        self._partition = pid
        buckets = (0.005, 0.025, 0.1, 0.5, 1, 5, 30, 120, 600)
        self._m_pi_time = REGISTRY.histogram(
            "process_instance_execution_time",
            "seconds from instance activation to completion",
            ("partition",), buckets=buckets).labels(pid)
        self._m_creations = REGISTRY.counter(
            "process_instance_creations_total",
            "process instances created", ("partition",)).labels(pid)
        self._m_job_life = REGISTRY.histogram(
            "job_life_time", "seconds from job creation to completion",
            ("partition",), buckets=buckets).labels(pid)
        self._m_job_activation = REGISTRY.histogram(
            "job_activation_time", "seconds from job creation to activation",
            ("partition",), buckets=buckets).labels(pid)
        self._pi_started: dict[int, int] = {}
        self._job_created: dict[int, int] = {}
        self._m_pending_incidents = REGISTRY.gauge(
            "pending_incidents_total", "incidents created minus resolved",
            ("partition",)).labels(pid)
        self._m_buffered_messages = REGISTRY.gauge(
            "buffered_messages_count", "published messages minus expired",
            ("partition",)).labels(pid)

    def _remember(self, store: dict, key: int, ts: int) -> None:
        if len(store) >= self._MAX_TRACKED:
            store.pop(next(iter(store)))
        store[key] = ts

    def observe(self, logged) -> None:
        from zeebe_tpu.protocol import ValueType
        from zeebe_tpu.protocol.intent import (
            JobBatchIntent,
            JobIntent,
            ProcessInstanceCreationIntent,
            ProcessInstanceIntent,
        )

        rec = logged.record
        if not rec.is_event:
            return
        vt = rec.value_type
        intent = int(rec.intent)
        if vt == ValueType.PROCESS_INSTANCE:
            if rec.value.get("bpmnElementType") != "PROCESS":
                return
            if intent == int(ProcessInstanceIntent.ELEMENT_ACTIVATING):
                self._remember(self._pi_started, rec.key, rec.timestamp)
            elif intent in (int(ProcessInstanceIntent.ELEMENT_COMPLETED),
                            int(ProcessInstanceIntent.ELEMENT_TERMINATED)):
                started = self._pi_started.pop(rec.key, None)
                if started is not None:
                    self._m_pi_time.observe((rec.timestamp - started) / 1000.0)
        elif vt == ValueType.PROCESS_INSTANCE_CREATION:
            if intent == int(ProcessInstanceCreationIntent.CREATED):
                self._m_creations.inc()
        elif vt == ValueType.JOB:
            if intent == int(JobIntent.CREATED):
                self._remember(self._job_created, rec.key, rec.timestamp)
            elif intent in (int(JobIntent.COMPLETED), int(JobIntent.CANCELED)):
                created = self._job_created.pop(rec.key, None)
                if created is not None:
                    self._m_job_life.observe((rec.timestamp - created) / 1000.0)
        elif vt == ValueType.JOB_BATCH:
            if intent == int(JobBatchIntent.ACTIVATED):
                for job_key in rec.value.get("jobKeys", ()) or ():
                    created = self._job_created.get(job_key)
                    if created is not None:
                        self._m_job_activation.observe(
                            (rec.timestamp - created) / 1000.0)
        elif vt == ValueType.INCIDENT:
            from zeebe_tpu.protocol.intent import IncidentIntent

            if intent == int(IncidentIntent.CREATED):
                self._m_pending_incidents.inc()
            elif intent == int(IncidentIntent.RESOLVED):
                self._m_pending_incidents.dec()
        elif vt == ValueType.MESSAGE:
            from zeebe_tpu.protocol.intent import MessageIntent

            if intent == int(MessageIntent.PUBLISHED):
                self._m_buffered_messages.inc()
            elif intent == int(MessageIntent.EXPIRED):
                self._m_buffered_messages.dec()
        elif vt == ValueType.MESSAGE_BATCH:
            from zeebe_tpu.protocol.intent import MessageBatchIntent

            if intent == int(MessageBatchIntent.EXPIRED):
                self._m_buffered_messages.dec(
                    len(rec.value.get("messageKeys", ()) or ()))


class ExporterContainer:
    def __init__(self, exporter_id: str, exporter: Exporter,
                 state: "ExportersState",
                 configuration: dict | None = None,
                 partition_id: int = 0) -> None:
        self.exporter_id = exporter_id
        self.exporter = exporter
        self.state = state
        self.position = state.position(exporter_id)
        # highest position handed to the exporter but not yet acked; a skip may
        # only advance the persisted position when nothing is pending, or a
        # crash-before-flush loses the buffered records to compaction
        # (reference: ExporterContainer.updateLastExportedRecordPosition)
        self.last_delivered = self.position
        exporter.configure(ExporterContext(exporter_id, configuration or {}))
        exporter.open(ExporterController(
            self._update_position,  # (position, metadata): atomic persist
            on_metadata=lambda data: state.set_metadata(exporter_id, data),
            read_metadata=lambda: state.metadata(exporter_id),
        ))
        from zeebe_tpu.utils.metrics import REGISTRY

        # labeled per (exporter, partition): each child is incremented by
        # exactly one partition ownership thread, so the non-atomic
        # Counter.inc never races
        self._m_exported = REGISTRY.counter(
            "exporter_events_exported_total",
            "records handed to an exporter", ("exporter", "partition")
        ).labels(exporter_id, str(partition_id))

    def deliver(self, record) -> None:
        self.last_delivered = record.position
        self.exporter.export(record)
        self._m_exported.inc()

    def skip(self, position: int) -> None:
        if self.last_delivered <= self.position:  # nothing unacked in flight
            self._update_position(position)
        self.last_delivered = max(self.last_delivered, position)

    def _update_position(self, position: int,
                         metadata: bytes | None = None) -> None:
        if position > self.position:
            self.position = position
            self.state.set_position_and_metadata(
                self.exporter_id, position, metadata)
        elif metadata is not None:
            self.state.set_metadata(self.exporter_id, metadata)


class ExportersState:
    """Exporter positions in the EXPORTER column family (reference:
    broker/…/exporter/stream/ExportersState.java)."""

    def __init__(self, db: ZbDb) -> None:
        self.db = db
        self._cf = db.column_family(CF.EXPORTER)

    def position(self, exporter_id: str) -> int:
        with self.db.transaction():
            return self._cf.get((exporter_id,)) or 0

    def set_position(self, exporter_id: str, position: int) -> None:
        with self.db.transaction():
            self._cf.put((exporter_id,), position)

    def set_position_and_metadata(self, exporter_id: str, position: int,
                                  metadata: bytes | None) -> None:
        """Both rows in ONE transaction: a crash must never persist advanced
        sequence counters without the position they were advanced for."""
        with self.db.transaction():
            self._cf.put((exporter_id,), position)
            if metadata is not None:
                self._cf.put(("__meta__", exporter_id), metadata)

    def metadata(self, exporter_id: str) -> bytes | None:
        with self.db.transaction():
            return self._cf.get(("__meta__", exporter_id))

    def set_metadata(self, exporter_id: str, data: bytes) -> None:
        with self.db.transaction():
            self._cf.put(("__meta__", exporter_id), data)

    def remove(self, exporter_id: str) -> None:
        with self.db.transaction():
            if self._cf.exists((exporter_id,)):
                self._cf.delete((exporter_id,))
            if self._cf.exists(("__meta__", exporter_id)):
                self._cf.delete(("__meta__", exporter_id))

    def lowest_position(self) -> int:
        with self.db.transaction():
            # metadata rows (key prefix "__meta__") share the CF; only the
            # single-part position keys carry int positions
            positions = [v for v in self._cf.values() if isinstance(v, int)]
        return min(positions) if positions else -1


class ExporterDirector:
    def __init__(self, stream: LogStream, db: ZbDb,
                 exporters: dict[str, "Exporter | tuple[Exporter, dict]"],
                 configurations: dict[str, dict] | None = None,
                 commit_position: Callable[[], int] | None = None) -> None:
        self.stream = stream
        self.state = ExportersState(db)
        # an entry may be (exporter, configuration) — the shape the
        # env-driven external-artifact loader produces (utils/external_code);
        # normalizing HERE keeps every construction site shape-agnostic
        configurations = dict(configurations or {})
        normalized: dict[str, Exporter] = {}
        for eid, entry in exporters.items():
            if isinstance(entry, tuple):
                normalized[eid], configurations[eid] = entry
            else:
                normalized[eid] = entry
        self.containers = [
            ExporterContainer(eid, exp, self.state,
                              configurations.get(eid),
                              partition_id=stream.partition_id)
            for eid, exp in normalized.items()
        ]
        # committed-position supplier: records past it are not yet safe to
        # export (Raft quorum); None = everything in the log is committed
        self.commit_position = commit_position
        # resume from the lowest acknowledged position (a restarted exporter
        # re-sees records after its last ack — at-least-once)
        self._next_position = min(
            (c.position for c in self.containers), default=0
        ) + 1
        from zeebe_tpu.utils.metrics import REGISTRY

        pid = str(stream.partition_id)
        self._latency = ExecutionLatencyObserver(stream.partition_id)
        self._m_events = REGISTRY.counter(
            "exporter_events_total", "records visited by the director",
            ("partition",)).labels(pid)
        # exporter_last_exported_position is owned by the broker metrics
        # (node+partition labels) — not re-registered here
        self._m_last_updated = REGISTRY.gauge(
            "exporter_last_updated_exported_position",
            "lowest acknowledged exporter position", ("partition",)).labels(pid)

    def export_available(self, max_records: int = 10_000) -> int:
        """Export committed records not yet seen; returns how many."""
        count = 0
        limit = self.commit_position() if self.commit_position else None
        for logged in self.stream.new_reader(self._next_position):
            if limit is not None and logged.position > limit:
                break
            for container in self.containers:
                if logged.position <= container.position:
                    continue  # already acked by this exporter (restart resume)
                ctx = container.exporter.context
                if ctx.record_filter is not None and not ctx.record_filter(logged):
                    container.skip(logged.position)
                    continue
                container.deliver(logged)
            self._latency.observe(logged)
            self._m_events.inc()
            self._next_position = logged.position + 1
            count += 1
            if count >= max_records:
                break
        if count:
            self._m_last_updated.set(
                min((c.position for c in self.containers), default=-1))
        return count

    def lowest_exporter_position(self) -> int:
        """Log compaction bound (reference: min exporter position vs snapshot
        position, AsyncSnapshotDirector). Uses the containers' in-memory
        positions (0 until first ack) so a bulk exporter that never flushed
        still pins the log."""
        if not self.containers:
            return 2**62
        return min(c.position for c in self.containers)

    def close(self) -> None:
        for container in self.containers:
            container.exporter.close()
