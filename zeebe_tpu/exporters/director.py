"""ExporterDirector: drives all configured exporters over committed records.

Reference: broker/src/main/java/io/camunda/zeebe/broker/exporter/stream/
ExporterDirector.java:51 — an actor per partition reading the log *after*
commit (readNextEvent/exportEvent :389-431), wrapping each exporter in an
ExporterContainer, persisting exporter positions into the EXPORTER column
family (ExportersState), and reporting the minimum acknowledged position so
log compaction never deletes unexported records.

Here the director is pump-driven like the stream processor (the broker pump
calls ``export_available()`` after each processing round). Fault isolation is
per exporter: a throwing exporter pauses ITSELF with exponential retry backoff
(position pinned on the failed record — export stays at-least-once, the
director never skips), reports DEGRADED to the health monitor, and the other
exporters keep draining; each container owns its own read cursor so one
failing sink never stalls the rest (reference behavior: ExporterContainer
retries forever, but the reference runs one actor per exporter — isolation is
what the shared pump must reproduce)."""

from __future__ import annotations

import time as _time_mod

from typing import Callable

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.logstreams import LogStream
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.db import ColumnFamilyCode as CF
from zeebe_tpu.utils.health import HealthStatus
from zeebe_tpu.utils.zlogging import Loggers

# exponential retry backoff for a failing exporter (reference: the ES
# exporter's own client retries; here the seam is generic per container)
INITIAL_BACKOFF_MS = 100
MAX_BACKOFF_MS = 10_000


class ExecutionLatencyObserver:
    """Creation→completion latency metrics, computed on the committed
    record stream like the reference's broker exporter metrics (reference:
    broker/…/exporter/metrics/ExecutionLatencyMetrics.java) — so the kernel
    burst-template path is counted exactly like the sequential path."""

    _MAX_TRACKED = 32_768

    def __init__(self, partition_id: int) -> None:
        from zeebe_tpu.utils.metrics import REGISTRY

        pid = str(partition_id)
        self._partition = pid
        buckets = (0.005, 0.025, 0.1, 0.5, 1, 5, 30, 120, 600)
        self._m_pi_time = REGISTRY.histogram(
            "process_instance_execution_time",
            "seconds from instance activation to completion",
            ("partition",), buckets=buckets).labels(pid)
        self._m_creations = REGISTRY.counter(
            "process_instance_creations_total",
            "process instances created", ("partition",)).labels(pid)
        self._m_job_life = REGISTRY.histogram(
            "job_life_time", "seconds from job creation to completion",
            ("partition",), buckets=buckets).labels(pid)
        self._m_job_activation = REGISTRY.histogram(
            "job_activation_time", "seconds from job creation to activation",
            ("partition",), buckets=buckets).labels(pid)
        self._pi_started: dict[int, int] = {}
        self._job_created: dict[int, int] = {}
        self._m_pending_incidents = REGISTRY.gauge(
            "pending_incidents_total", "incidents created minus resolved",
            ("partition",)).labels(pid)
        self._m_buffered_messages = REGISTRY.gauge(
            "buffered_messages_count", "published messages minus expired",
            ("partition",)).labels(pid)

    def _remember(self, store: dict, key: int, ts: int) -> None:
        if len(store) >= self._MAX_TRACKED:
            store.pop(next(iter(store)))
        store[key] = ts

    def observe(self, logged) -> None:
        from zeebe_tpu.protocol import ValueType
        from zeebe_tpu.protocol.intent import (
            JobBatchIntent,
            JobIntent,
            ProcessInstanceCreationIntent,
            ProcessInstanceIntent,
        )

        rec = logged.record
        if not rec.is_event:
            return
        vt = rec.value_type
        intent = int(rec.intent)
        if vt == ValueType.PROCESS_INSTANCE:
            if rec.value.get("bpmnElementType") != "PROCESS":
                return
            if intent == int(ProcessInstanceIntent.ELEMENT_ACTIVATING):
                self._remember(self._pi_started, rec.key, rec.timestamp)
            elif intent in (int(ProcessInstanceIntent.ELEMENT_COMPLETED),
                            int(ProcessInstanceIntent.ELEMENT_TERMINATED)):
                started = self._pi_started.pop(rec.key, None)
                if started is not None:
                    self._m_pi_time.observe((rec.timestamp - started) / 1000.0)
        elif vt == ValueType.PROCESS_INSTANCE_CREATION:
            if intent == int(ProcessInstanceCreationIntent.CREATED):
                self._m_creations.inc()
        elif vt == ValueType.JOB:
            if intent == int(JobIntent.CREATED):
                self._remember(self._job_created, rec.key, rec.timestamp)
            elif intent in (int(JobIntent.COMPLETED), int(JobIntent.CANCELED)):
                created = self._job_created.pop(rec.key, None)
                if created is not None:
                    self._m_job_life.observe((rec.timestamp - created) / 1000.0)
        elif vt == ValueType.JOB_BATCH:
            if intent == int(JobBatchIntent.ACTIVATED):
                for job_key in rec.value.get("jobKeys", ()) or ():
                    created = self._job_created.get(job_key)
                    if created is not None:
                        self._m_job_activation.observe(
                            (rec.timestamp - created) / 1000.0)
        elif vt == ValueType.INCIDENT:
            from zeebe_tpu.protocol.intent import IncidentIntent

            if intent == int(IncidentIntent.CREATED):
                self._m_pending_incidents.inc()
            elif intent == int(IncidentIntent.RESOLVED):
                self._m_pending_incidents.dec()
        elif vt == ValueType.MESSAGE:
            from zeebe_tpu.protocol.intent import MessageIntent

            if intent == int(MessageIntent.PUBLISHED):
                self._m_buffered_messages.inc()
            elif intent == int(MessageIntent.EXPIRED):
                self._m_buffered_messages.dec()
        elif vt == ValueType.MESSAGE_BATCH:
            from zeebe_tpu.protocol.intent import MessageBatchIntent

            if intent == int(MessageBatchIntent.EXPIRED):
                self._m_buffered_messages.dec(
                    len(rec.value.get("messageKeys", ()) or ()))


class ExporterContainer:
    def __init__(self, exporter_id: str, exporter: Exporter,
                 state: "ExportersState",
                 configuration: dict | None = None,
                 partition_id: int = 0,
                 on_health: Callable[[str, HealthStatus, str], None] | None = None) -> None:
        self.exporter_id = exporter_id
        self.exporter = exporter
        self.state = state
        self.position = state.position(exporter_id)
        # the cursor as RECOVERED from state at open, before any delivery —
        # test oracles use it to tell a legitimately-ahead recovered cursor
        # (stream not re-materialized yet) from an export past commit
        self.recovered_position = self.position
        # highest position handed to the exporter AND exported without error
        # but not yet acked; a skip may only advance the persisted position
        # when nothing is pending, or a crash-before-flush loses the buffered
        # records to compaction (reference:
        # ExporterContainer.updateLastExportedRecordPosition)
        self.last_delivered = self.position
        # per-container read cursor: restart resumes after the last ack
        # (at-least-once — unacked records are re-seen), and a backing-off
        # container catches up from here without stalling its siblings
        self.next_position = self.position + 1
        # retry-with-backoff state: consecutive failures and the millis
        # timestamp before which deliveries are suspended
        self.consecutive_failures = 0
        self.paused_until_ms: int | None = None
        self.last_error = ""
        self._on_health = on_health
        exporter.configure(ExporterContext(exporter_id, configuration or {}))
        exporter.open(ExporterController(
            self._update_position,  # (position, metadata): atomic persist
            on_metadata=lambda data: state.set_metadata(exporter_id, data),
            read_metadata=lambda: state.metadata(exporter_id),
        ))
        from zeebe_tpu.utils.metrics import REGISTRY

        # labeled per (exporter, partition): each child is incremented by
        # exactly one partition ownership thread, so the non-atomic
        # Counter.inc never races
        self._m_exported = REGISTRY.counter(
            "exporter_events_exported_total",
            "records handed to an exporter", ("exporter", "partition")
        ).labels(exporter_id, str(partition_id))
        self._m_failures = REGISTRY.counter(
            "exporter_failures_total",
            "export calls that raised", ("exporter", "partition")
        ).labels(exporter_id, str(partition_id))

    @property
    def paused(self) -> bool:
        return self.paused_until_ms is not None

    def maybe_resume(self, now_millis: int) -> None:
        """Open the retry window once the backoff expired; the failure count
        is kept so the NEXT failure backs off longer."""
        if self.paused_until_ms is not None and now_millis >= self.paused_until_ms:
            self.paused_until_ms = None

    def deliver(self, record, now_millis: int = 0) -> bool:
        """Hand one record to the exporter. On failure the position is pinned
        (``last_delivered``/``next_position`` stay put so the SAME record is
        retried), the container backs off exponentially, and health goes
        DEGRADED; returns False so the director moves on to the siblings."""
        try:
            self.exporter.export(record)
        except Exception as exc:  # noqa: BLE001 — exporter plugins are
            # third-party code; one bad sink must not poison the export loop
            self.consecutive_failures += 1
            backoff = min(
                INITIAL_BACKOFF_MS * (2 ** (self.consecutive_failures - 1)),
                MAX_BACKOFF_MS,
            )
            self.paused_until_ms = now_millis + backoff
            self.last_error = f"{type(exc).__name__}: {exc}"
            self._m_failures.inc()
            Loggers.exporter_logger(self.exporter_id).exception(
                "exporter %s failed on record %d (failure #%d) — backing off "
                "%d ms", self.exporter_id, record.position,
                self.consecutive_failures, backoff)
            self._report_health(
                HealthStatus.DEGRADED,
                f"retry #{self.consecutive_failures} in {backoff}ms after "
                f"{self.last_error}",
            )
            return False
        # the watermark advances ONLY after a successful export: a failed
        # export must not let skip() treat the record as pending-acked (the
        # stale watermark would corrupt the pending-ack accounting)
        self.last_delivered = record.position
        self.next_position = record.position + 1
        self._m_exported.inc()
        if self.consecutive_failures:
            self.consecutive_failures = 0
            self.last_error = ""
            self._report_health(HealthStatus.HEALTHY, "recovered")
        return True

    def skip(self, position: int) -> None:
        if self.last_delivered <= self.position:  # nothing unacked in flight
            self._update_position(position)
        self.last_delivered = max(self.last_delivered, position)
        self.next_position = max(self.next_position, position + 1)

    def _report_health(self, status: HealthStatus, message: str) -> None:
        if self._on_health is not None:
            self._on_health(self.exporter_id, status, message)

    def _update_position(self, position: int,
                         metadata: bytes | None = None) -> None:
        if position > self.position:
            self.position = position
            self.state.set_position_and_metadata(
                self.exporter_id, position, metadata)
        elif metadata is not None:
            self.state.set_metadata(self.exporter_id, metadata)


class ExportersState:
    """Exporter positions in the EXPORTER column family (reference:
    broker/…/exporter/stream/ExportersState.java)."""

    def __init__(self, db: ZbDb) -> None:
        self.db = db
        self._cf = db.column_family(CF.EXPORTER)

    def position(self, exporter_id: str) -> int:
        with self.db.transaction():
            return self._cf.get((exporter_id,)) or 0

    def set_position(self, exporter_id: str, position: int) -> None:
        with self.db.transaction():
            self._cf.put((exporter_id,), position)

    def set_position_and_metadata(self, exporter_id: str, position: int,
                                  metadata: bytes | None) -> None:
        """Both rows in ONE transaction: a crash must never persist advanced
        sequence counters without the position they were advanced for."""
        with self.db.transaction():
            self._cf.put((exporter_id,), position)
            if metadata is not None:
                self._cf.put(("__meta__", exporter_id), metadata)

    def metadata(self, exporter_id: str) -> bytes | None:
        with self.db.transaction():
            return self._cf.get(("__meta__", exporter_id))

    def set_metadata(self, exporter_id: str, data: bytes) -> None:
        with self.db.transaction():
            self._cf.put(("__meta__", exporter_id), data)

    def remove(self, exporter_id: str) -> None:
        with self.db.transaction():
            if self._cf.exists((exporter_id,)):
                self._cf.delete((exporter_id,))
            if self._cf.exists(("__meta__", exporter_id)):
                self._cf.delete(("__meta__", exporter_id))

    def lowest_position(self) -> int:
        with self.db.transaction():
            # metadata rows (key prefix "__meta__") share the CF; only the
            # single-part position keys carry int positions
            positions = [v for v in self._cf.values() if isinstance(v, int)]
        return min(positions) if positions else -1


class ExporterDirector:
    def __init__(self, stream: LogStream, db: ZbDb,
                 exporters: dict[str, "Exporter | tuple[Exporter, dict]"],
                 configurations: dict[str, dict] | None = None,
                 commit_position: Callable[[], int] | None = None,
                 clock_millis: Callable[[], int] | None = None,
                 on_health: Callable[[str, HealthStatus, str], None] | None = None) -> None:
        self.stream = stream
        self.state = ExportersState(db)
        self.clock_millis = clock_millis or (
            lambda: int(_time_mod.time() * 1000))
        # an entry may be (exporter, configuration) — the shape the
        # env-driven external-artifact loader produces (utils/external_code);
        # normalizing HERE keeps every construction site shape-agnostic
        configurations = dict(configurations or {})
        normalized: dict[str, Exporter] = {}
        for eid, entry in exporters.items():
            if isinstance(entry, tuple):
                normalized[eid], configurations[eid] = entry
            else:
                normalized[eid] = entry
        self.containers = [
            ExporterContainer(eid, exp, self.state,
                              configurations.get(eid),
                              partition_id=stream.partition_id,
                              on_health=on_health)
            for eid, exp in normalized.items()
        ]
        # committed-position supplier: records past it are not yet safe to
        # export (Raft quorum); None = everything in the log is committed
        self.commit_position = commit_position
        # director-level bookkeeping cursor (latency metrics observe each
        # record once); starts at the lowest acknowledged position — a
        # restarted exporter re-sees records after its last ack
        # (at-least-once)
        self._next_position = min(
            (c.position for c in self.containers), default=0
        ) + 1
        from zeebe_tpu.utils.metrics import REGISTRY

        pid = str(stream.partition_id)
        self._latency = ExecutionLatencyObserver(stream.partition_id)
        self._m_events = REGISTRY.counter(
            "exporter_events_total", "records visited by the director",
            ("partition",)).labels(pid)
        # exporter_last_exported_position is owned by the broker metrics
        # (node+partition labels) — not re-registered here
        self._m_last_updated = REGISTRY.gauge(
            "exporter_last_updated_exported_position",
            "lowest acknowledged exporter position", ("partition",)).labels(pid)
        # per-container lag (log end - acked position): the quantitative
        # face of a DEGRADED/backing-off exporter — a paused container's lag
        # grows on /metrics while its siblings' stays ~0
        lag = REGISTRY.gauge(
            "exporter_container_lag_records",
            "records between the log end and this exporter's acked position",
            ("exporter", "partition"))
        self._lag_children = {
            c.exporter_id: lag.labels(c.exporter_id, pid)
            for c in self.containers
        }
        from zeebe_tpu.observability.tracer import get_tracer

        self._tracer = get_tracer()

    def _offer(self, container: "ExporterContainer", logged, now: int) -> None:
        """Hand one due record to a container (filter-skip or deliver; a
        failed delivery pauses the container and pins its cursor)."""
        if logged.position <= container.position:
            # already acked (restart resume): advance the cursor only
            container.next_position = logged.position + 1
            return
        ctx = container.exporter.context
        if ctx.record_filter is not None and not ctx.record_filter(logged):
            container.skip(logged.position)
            return
        tracer = self._tracer
        if not tracer.enabled:
            container.deliver(logged, now)
            return
        # sample FIRST: at low rates the common case must not pay the span
        # timing — only the trace-id resolution + one crc32
        pid = self.stream.partition_id
        fallback = (logged.source_position if logged.source_position >= 0
                    else logged.position)
        root = tracer.resolve_root(pid, logged.position, fallback)
        trace_id = f"{pid}:{root}"
        if not tracer.sampled(trace_id):
            container.deliver(logged, now)
            return
        t0 = _time_mod.perf_counter()
        ok = container.deliver(logged, now)
        dur = _time_mod.perf_counter() - t0
        # mark_exported dedupes re-delivery — export is at-least-once across
        # restarts, but the span stream must stay exactly-once; marked only
        # on SUCCESS so a retried failure still gets its span
        if ok and tracer.mark_exported(
                (container.exporter_id, pid, logged.position)):
            tracer.emit(trace_id, "exporter.export", dur, pid,
                        attrs={"position": logged.position,
                               "exporter": container.exporter_id})

    def export_available(self, max_records: int = 10_000) -> int:
        """Export committed records not yet seen; returns the work done this
        round (max of new records visited and per-container catch-up
        deliveries — a container draining backlog after backoff is work even
        when the director cursor is already at the head, or drain loops would
        stop pumping with backlog still pending). A failing exporter backs
        off alone while the rest advance. Steady state (all cursors at the
        head) is ONE reader pass; a lagging container (resumed from backoff
        or restart) gets its own bounded catch-up scan."""
        now = self.clock_millis()
        limit = self.commit_position() if self.commit_position else None
        for container in self.containers:
            container.maybe_resume(now)
        # catch-up: containers whose cursor fell behind the director cursor
        max_catch_up = 0
        for container in self.containers:
            if container.paused or container.next_position >= self._next_position:
                continue
            n = 0
            for logged in self.stream.new_reader(container.next_position):
                if logged.position >= self._next_position:
                    break  # reached the head: the shared pass takes over
                if limit is not None and logged.position > limit:
                    break
                self._offer(container, logged, now)
                if container.paused:
                    break
                n += 1
                if n >= max_records:
                    break
            max_catch_up = max(max_catch_up, n)
        # shared head pass: containers at (or beyond) the head when the pass
        # starts, plus the director-level bookkeeping (latency observation +
        # event count, once per record). Cursor comparisons are ranges, not
        # exact matches — materialized positions may gap where a position
        # range was consumed by a raft entry that never committed
        eligible = [c for c in self.containers
                    if not c.paused and c.next_position >= self._next_position]
        count = 0
        for logged in self.stream.new_reader(self._next_position):
            if limit is not None and logged.position > limit:
                break
            for container in eligible:
                if not container.paused and container.next_position <= logged.position:
                    self._offer(container, logged, now)
            self._latency.observe(logged)
            self._m_events.inc()
            self._next_position = logged.position + 1
            count += 1
            if count >= max_records:
                break
        if count or max_catch_up:
            self._m_last_updated.set(
                min((c.position for c in self.containers), default=-1))
        log_end = self.stream.last_position
        for container in self.containers:
            self._lag_children[container.exporter_id].set(
                log_end - container.position)
        return max(count, max_catch_up)

    def lowest_exporter_position(self) -> int:
        """Log compaction bound (reference: min exporter position vs snapshot
        position, AsyncSnapshotDirector). Uses the containers' in-memory
        positions (0 until first ack) so a bulk exporter that never flushed
        still pins the log."""
        if not self.containers:
            return 2**62
        return min(c.position for c in self.containers)

    def close(self) -> None:
        for container in self.containers:
            try:
                container.exporter.close()
            except Exception:  # noqa: BLE001 — one exporter's close failure
                # must not leak the remaining exporters' buffered flushes
                Loggers.exporter_logger(container.exporter_id).exception(
                    "exporter %s failed to close", container.exporter_id)
