"""Exporter SPI + built-in exporters (SURVEY.md §2.13 exporters)."""

from zeebe_tpu.exporters.recording import RecordingExporter, RecordStream

__all__ = ["RecordingExporter", "RecordStream"]
