"""Exporter SPI + built-in exporters (SURVEY.md §2.13 exporters)."""

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.exporters.director import ExporterDirector, ExportersState
from zeebe_tpu.exporters.elasticsearch import ElasticsearchExporter
from zeebe_tpu.exporters.recording import RecordingExporter, RecordStream

__all__ = [
    "Exporter",
    "ExporterContext",
    "ExporterController",
    "ExporterDirector",
    "ExportersState",
    "ElasticsearchExporter",
    "RecordingExporter",
    "RecordStream",
]
