"""Exporter SPI + built-in exporters (SURVEY.md §2.13 exporters)."""

from zeebe_tpu.exporters.api import Exporter, ExporterContext, ExporterController
from zeebe_tpu.exporters.director import (
    ExporterContainer,
    ExporterDirector,
    ExportersState,
)
from zeebe_tpu.exporters.elasticsearch import (
    AuthenticationConfiguration,
    AwsConfiguration,
    BulkConfiguration,
    ElasticsearchExporter,
    IndexConfiguration,
    OpensearchExporter,
    RetentionConfiguration,
)
from zeebe_tpu.exporters.recording import RecordingExporter, RecordStream

__all__ = [
    "AuthenticationConfiguration",
    "AwsConfiguration",
    "BulkConfiguration",
    "Exporter",
    "ExporterContainer",
    "ExporterContext",
    "ExporterController",
    "ExporterDirector",
    "ExportersState",
    "ElasticsearchExporter",
    "IndexConfiguration",
    "OpensearchExporter",
    "RetentionConfiguration",
    "RecordingExporter",
    "RecordStream",
]
