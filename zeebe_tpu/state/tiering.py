"""Tiered partition state: a disk-backed cold store for parked instances
(ISSUE 8, ROADMAP item 4).

A real tenant parks millions of process instances — waiting on timers,
messages, human tasks — for days. The hot ``ZbDb`` dict holds every value as
a decoded Python object, so resident memory grows with the parked backlog
even though parked state is never read until its wake event. Gorilla
(PAPERS.md) showed the tier shape that works: a bounded in-memory hot tier
with whole-block eviction over a durable cold layer.

The split here exploits this engine's own durability invariant — **the
replicated log + snapshot chain are the durability source of truth** (state
is always recomputable), so the cold tier is a *memory-extension cache*, not
a durability layer:

- **Spill** moves a parked instance's state records (element-instance tree,
  variables, message subscriptions, timers, jobs) out of the hot dict into a
  CRC-framed append-only segment file, leaving a ~56-byte ``ColdRef`` stub
  behind. Keys stay resident in the sorted index, so prefix iteration and
  existence checks are unchanged.
- **Fault-in** is transparent: any committed read of a ``ColdRef`` (the wake
  path — timer fire, message correlate, job activate — or a query) resolves
  the frame, CRC-checks it, and promotes the value back to hot.
- **Crash safety**: cold segments are wiped on every open. A spilled value
  is resolved from its frame whenever a snapshot or delta serializes it, so
  the persisted chain is byte-identical to an unspilled partition's — after
  a crash, recovery rebuilds the instance from the chain + replay exactly as
  before (the scale soak crashes mid-spill to prove it), and the manager
  simply re-spills once the instance re-parks.
- **Reclamation**: a segment whose entries all faulted back in (or were
  deleted) unlinks; a mostly-dead segment's survivors are rewritten into the
  current segment on the pump thread (``compact_cold``), so cold disk tracks
  live parked bytes.

Spill *candidates* arrive through the physical ``ZbDb.note_parked`` seam the
state facades fire when an instance enters a wait state; the
``TieringManager`` (driven from the partition pump, between transactions)
spills candidates that stayed parked past ``park_after_ms``. Both seams are
observation-only: a lost candidate just stays hot, a stale one costs one
no-op pass — determinism and replay parity are untouched.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import zlib
from collections import OrderedDict
from pathlib import Path
from typing import Any, Callable

from zeebe_tpu.protocol import msgpack
from zeebe_tpu.state.db import ZbDb, encode_key
from zeebe_tpu.utils import storage_io
from zeebe_tpu.utils.metrics import REGISTRY as _REG

#: cold frame: total length, crc32(key+value), key length
_FRAME = struct.Struct("<IIH")

_M_SPILLED_INSTANCES = _REG.gauge(
    "state_parked_cold_instances",
    "parked process instances currently resident in the cold tier",
    ("partition",))
_M_SPILLS = _REG.counter(
    "state_spill_total", "state records spilled to the cold tier",
    ("partition",))
_M_FAULTS = _REG.counter(
    "state_fault_in_total",
    "cold state records faulted back into the hot tier", ("partition",))
_M_COLD_SEGMENTS = _REG.gauge(
    "state_cold_segments", "cold-tier segment files", ("partition",))
_M_TIER_WRITE_ERRORS = _REG.counter(
    "state_tier_write_errors_total",
    "cold-tier write failures (ENOSPC/EIO during spill or compaction); "
    "tiering degrades to hot-only instead of poisoning the pump",
    ("partition",))
_M_TIER_READ_ERRORS = _REG.counter(
    "state_tier_read_errors_total",
    "cold-tier read failures (CRC mismatch / EIO on fault-in); the "
    "partition latches DEGRADED and rebuilds from chain + log (ISSUE 14)",
    ("partition",))


def note_cold_read_error(partition_id: int) -> None:
    """Read-side degradation metric seam (the partition's cold-corruption
    repair calls this; one metric home next to its write-side sibling)."""
    _M_TIER_READ_ERRORS.labels(str(partition_id)).inc()


class ColdCorruptionError(ValueError):
    """A cold-store read hit a CRC mismatch, short read, or IO error
    (ISSUE 14). Typed so the partition pump can catch it ABOVE the stream
    processor's blanket failure containment and repair — latch tiering
    DEGRADED + transition (state rebuilds from chain + log; cold is a
    cache) — instead of poisoning the pump or failing the partition.
    Subclasses ValueError: pre-existing corrupt-frame handling keeps
    matching."""

    def __init__(self, message: str, ref: "ColdRef | None" = None) -> None:
        super().__init__(message)
        self.ref = ref


class ColdRef:
    """A committed value demoted to disk: (segment, offset, frame length).
    ``tag`` carries the owning process-instance key so the first fault-in of
    an instance can notify the tiering manager (wake observation)."""

    __slots__ = ("seg", "off", "length", "tag")

    def __init__(self, seg: int, off: int, length: int, tag: int = -1) -> None:
        self.seg = seg
        self.off = off
        self.length = length
        self.tag = tag

    def __repr__(self) -> str:  # debugging/postmortem friendliness
        return f"ColdRef(seg={self.seg}, off={self.off}, len={self.length})"


class _Segment:
    __slots__ = ("seg_id", "path", "write_f", "read_fd", "size",
                 "live", "live_bytes", "keys")

    def __init__(self, seg_id: int, path: Path) -> None:
        self.seg_id = seg_id
        self.path = path
        self.write_f = storage_io.open_file(path, "wb")
        self.read_fd = storage_io.os_open(path, os.O_RDONLY)
        self.size = 0
        self.live = 0
        self.live_bytes = 0
        # off → (encoded db key, frame length) per LIVE entry (compaction
        # moves these; release drops them)
        self.keys: dict[int, tuple[bytes, int]] = {}


class ColdStore:
    """Append-only CRC-framed segment files holding spilled state values.

    No fsync anywhere: the store is a cache (see module docstring) — a torn
    frame after a crash is impossible to even observe because open() wipes
    the directory. Reads go through ``os.pread`` (thread-safe, no shared
    file position) and only ever see flushed bytes: ``append`` buffers, and
    the spiller installs refs into the db strictly after ``flush()``.
    """

    def __init__(self, directory: str | Path,
                 segment_max_bytes: int = 32 << 20) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        for stale in self.directory.glob("cold-*.seg"):
            try:
                stale.unlink()
            except OSError:
                pass
        self.segment_max_bytes = segment_max_bytes
        self._segments: dict[int, _Segment] = {}
        self._next_seg = 0
        self._current: _Segment | None = None
        self.bytes_written = 0

    # -- write side ------------------------------------------------------------

    def _roll(self) -> _Segment:
        self._next_seg += 1
        seg = _Segment(self._next_seg,
                       self.directory / f"cold-{self._next_seg:08d}.seg")
        self._segments[seg.seg_id] = seg
        self._current = seg
        return seg

    def append(self, key: bytes, packed: bytes, tag: int = -1) -> ColdRef:
        seg = self._current
        if seg is None or seg.size >= self.segment_max_bytes:
            if seg is not None:
                seg.write_f.flush()
            seg = self._roll()
        crc = zlib.crc32(packed, zlib.crc32(key)) & 0xFFFFFFFF
        frame_len = _FRAME.size + len(key) + len(packed)
        seg.write_f.write(_FRAME.pack(frame_len, crc, len(key)))
        seg.write_f.write(key)
        seg.write_f.write(packed)
        ref = ColdRef(seg.seg_id, seg.size, frame_len, tag)
        seg.keys[seg.size] = (key, frame_len)
        seg.size += frame_len
        seg.live += 1
        seg.live_bytes += frame_len
        self.bytes_written += frame_len
        return ref

    def flush(self) -> None:
        if self._current is not None:
            self._current.write_f.flush()

    # -- read side -------------------------------------------------------------

    def read_value(self, ref: ColdRef) -> bytes:
        seg = self._segments.get(ref.seg)
        if seg is None:
            raise ValueError(f"cold segment {ref.seg} is gone ({ref!r})")
        try:
            raw = storage_io.pread(seg.read_fd, ref.length, ref.off)
        except OSError as exc:
            # EIO on fault-in: same degradation class as corruption — the
            # frame is unreadable, the value must rebuild from chain + log
            raise ColdCorruptionError(
                f"cold read failed at {ref!r}: {exc}", ref=ref) from exc
        if len(raw) != ref.length:
            raise ColdCorruptionError(f"short cold read at {ref!r}", ref=ref)
        frame_len, crc, key_len = _FRAME.unpack_from(raw)
        payload = raw[_FRAME.size:]
        if frame_len != ref.length or \
                zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise ColdCorruptionError(
                f"corrupt cold frame at {ref!r} (crc mismatch)", ref=ref)
        return payload[key_len:]

    def scrub(self, cursor: tuple[int, int], max_bytes: int
              ) -> tuple[tuple[int, int], int, dict | None]:
        """CRC-walk sealed segments' frames from ``cursor=(seg_id, off)``
        for up to ``max_bytes`` (ISSUE 14 scrubber). Returns ``(next_cursor,
        scanned_bytes, corruption)``; a ``(0, 0)`` next cursor means the
        walk wrapped. Sealed segments only — the current segment still has
        a buffered tail. Pump-thread only (segments roll/drop there)."""
        seg_ids = sorted(s for s, seg in self._segments.items()
                         if seg is not self._current)
        scanned = 0
        seg_id, off = cursor
        for sid in seg_ids:
            if sid < seg_id:
                continue
            seg = self._segments.get(sid)
            if seg is None:
                continue
            pos = off if sid == seg_id else 0
            while pos < seg.size and scanned < max_bytes:
                head = storage_io.pread(seg.read_fd, _FRAME.size, pos)
                if len(head) < _FRAME.size:
                    return ((sid, pos), scanned,
                            {"segment": sid, "offset": pos,
                             "reason": "short-header"})
                frame_len, crc, _key_len = _FRAME.unpack_from(head)
                if frame_len < _FRAME.size or pos + frame_len > seg.size:
                    return ((sid, pos), scanned,
                            {"segment": sid, "offset": pos,
                             "reason": "bad-frame-length"})
                payload = storage_io.pread(
                    seg.read_fd, frame_len - _FRAME.size, pos + _FRAME.size)
                scanned += frame_len
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    return ((sid, pos), scanned,
                            {"segment": sid, "offset": pos,
                             "reason": "crc-mismatch"})
                pos += frame_len
            if scanned >= max_bytes:
                return (sid, pos), scanned, None
        return (0, 0), scanned, None

    # -- reclamation -----------------------------------------------------------

    def release(self, ref: ColdRef) -> None:
        """The ref's value faulted in, was overwritten, or was deleted."""
        seg = self._segments.get(ref.seg)
        if seg is None:
            return
        if seg.keys.pop(ref.off, None) is not None:
            seg.live -= 1
            seg.live_bytes -= ref.length
        if seg.live <= 0 and seg is not self._current:
            self._drop(seg)

    def _drop(self, seg: _Segment) -> None:
        self._segments.pop(seg.seg_id, None)
        try:
            seg.write_f.close()
        except OSError:
            pass
        try:
            os.close(seg.read_fd)
        except OSError:
            pass
        try:
            seg.path.unlink()
        except OSError:
            pass

    def worst_segment(self) -> _Segment | None:
        """The sealed segment with the most dead bytes (compaction pick)."""
        worst, worst_dead = None, 0
        for seg in self._segments.values():
            if seg is self._current:
                continue
            dead = seg.size - seg.live_bytes
            if dead > worst_dead:
                worst, worst_dead = seg, dead
        return worst

    # -- accounting ------------------------------------------------------------

    # accounting reads run on management HTTP threads while the pump thread
    # rolls/drops segments: snapshot the dict (list() is atomic under the
    # GIL) so iteration never races a size change

    @property
    def live_entries(self) -> int:
        return sum(seg.live for seg in list(self._segments.values()))

    @property
    def live_bytes(self) -> int:
        return sum(seg.live_bytes for seg in list(self._segments.values()))

    @property
    def disk_bytes(self) -> int:
        return sum(seg.size for seg in list(self._segments.values()))

    @property
    def segment_count(self) -> int:
        return len(self._segments)

    def close(self) -> None:
        for seg in list(self._segments.values()):
            self._drop(seg)
        self._current = None


class TieredZbDb(ZbDb):
    """ZbDb whose committed values may live in the cold store.

    Drop-in for the engine/processor: transactions, column families, FK
    checks, dirty-key delta tracking, and snapshot/delta serialization are
    inherited — serialization resolves cold values from their frames, so a
    tiered partition's snapshots are byte-identical to an untiered one's
    (the crash-safety argument in the module docstring rests on this).

    The native iterate/commit passes are disabled: iterate must resolve
    ``ColdRef`` values per read, and commit must release superseded refs —
    both per-key concerns the C passes don't know. Tiered mode trades that
    sliver of batch throughput for a bounded hot tier.
    """

    def __init__(self, directory: str | Path,
                 consistency_checks: bool = False,
                 segment_max_bytes: int = 32 << 20,
                 partition_id: int = 0) -> None:
        super().__init__(consistency_checks)
        self._native_iterate = None
        self._native_commit = None
        self.cold = ColdStore(directory, segment_max_bytes=segment_max_bytes)
        self.partition_id = partition_id
        # first-fault-of-an-instance observation (tiering manager wake seam)
        self.woken_listener: Callable[[int], None] | None = None
        self.spills_total = 0
        self.faults_total = 0
        self._m_spills = _M_SPILLS.labels(str(partition_id))
        self._m_faults = _M_FAULTS.labels(str(partition_id))
        # ewma of packed hot-value size (spill-time observations) for the
        # hot-tier byte estimate surfaced by zeebe_state_tier_bytes
        self._avg_packed = 128.0

    # -- committed-store internals (cold resolution) ---------------------------

    def _committed_value(self, key: bytes) -> Any:
        val = self._data.get(key)
        if type(val) is not ColdRef:
            return val
        obj = msgpack.unpackb(self.cold.read_value(val))
        # fault-in: promote back to hot — the instance is waking up
        self._data[key] = obj
        self.cold.release(val)
        self.faults_total += 1
        self._m_faults.inc()
        if val.tag >= 0 and self.woken_listener is not None:
            self.woken_listener(val.tag)
        return obj

    def _put_committed(self, key: bytes, value: Any) -> None:
        prev = self._data.get(key)
        if type(prev) is ColdRef:
            self.cold.release(prev)
        super()._put_committed(key, value)

    def _delete_committed(self, key: bytes) -> None:
        prev = self._data.get(key)
        if type(prev) is ColdRef:
            self.cold.release(prev)
        super()._delete_committed(key)

    def committed_get(self, code, key_parts) -> Any:
        """Cross-thread committed read (QueryService): resolves cold values
        WITHOUT promoting — no dict/LRU mutation off the owner thread.
        ``pread`` + an immutable ref make the read itself thread-safe; if a
        pump-thread compaction drops the ref's segment between our dict read
        and the pread, the retry sees the already-swapped new ref (the swap
        happens strictly before the release)."""
        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        key = encode_key(code, key_parts)
        for attempt in (0, 1):
            val = self._data.get(key)
            if type(val) is not ColdRef:
                return val
            try:
                return msgpack.unpackb(self.cold.read_value(val))
            except (OSError, ValueError, ColdCorruptionError):
                if attempt:
                    raise
        return None  # unreachable

    # -- spill (the tiering manager's write path) ------------------------------

    def spill_keys(self, keys: list[bytes], tag: int = -1) -> tuple[int, int]:
        """Demote the given committed keys' values to the cold store.
        Two-phase: every frame is appended and FLUSHED before any ``ColdRef``
        becomes visible in ``_data`` — a concurrent query-thread read of a
        ref can then always ``pread`` it. Values that are None (pure index
        entries), already cold, or not containers stay put. Returns
        (records spilled, packed bytes)."""
        if self.in_transaction:
            raise RuntimeError("cannot spill with an open transaction")
        staged: list[tuple[bytes, ColdRef]] = []
        spilled_bytes = 0
        data = self._data
        for key in keys:
            val = data.get(key)
            t = type(val)
            if val is None or t is ColdRef or not (t is dict or t is list):
                continue
            packed = msgpack.packb(val)
            staged.append((key, self.cold.append(key, packed, tag)))
            spilled_bytes += len(packed)
            self._avg_packed += (len(packed) - self._avg_packed) * 0.01
        if not staged:
            return 0, 0
        self.cold.flush()
        for key, ref in staged:
            data[key] = ref
        self.spills_total += len(staged)
        self._m_spills.inc(len(staged))
        return len(staged), spilled_bytes

    def compact_cold(self, max_moves: int = 4096,
                     min_dead_bytes: int = 4 << 20,
                     min_dead_fraction: float = 0.5) -> int:
        """Rewrite the worst sealed segment's survivors into the current
        segment and unlink it. Runs on the pump thread; each key's ref swaps
        atomically (one dict assignment), so concurrent query-thread reads
        see either the old frame (file still open) or the new one."""
        seg = self.cold.worst_segment()
        if seg is None:
            return 0
        dead = seg.size - seg.live_bytes
        if dead < min_dead_bytes or dead < seg.size * min_dead_fraction:
            return 0
        data = self._data
        # two-phase like spill_keys: append every survivor, ONE flush, then
        # swap the refs — frames are visible before any ref publishes, and
        # the pump pays one flush per pass instead of one per frame
        staged: list[tuple[bytes, ColdRef, ColdRef]] = []
        for off, (key, length) in list(seg.keys.items()):
            if len(staged) >= max_moves:
                break
            ref = data.get(key)
            if type(ref) is not ColdRef or ref.seg != seg.seg_id \
                    or ref.off != off:
                # the index lost track (overwritten without release — should
                # not happen, but never move a frame the db doesn't own)
                if seg.keys.pop(off, None) is not None:
                    seg.live -= 1
                    seg.live_bytes -= length
                continue
            packed = self.cold.read_value(ref)
            staged.append((key, ref, self.cold.append(key, packed, ref.tag)))
        if staged:
            self.cold.flush()
        for key, old_ref, new_ref in staged:
            data[key] = new_ref
            self.cold.release(old_ref)
        if seg.live <= 0:
            self.cold._drop(seg)
        return len(staged)

    # -- snapshot/delta serialization (cold values resolve) --------------------

    def _resolve(self, val: Any) -> Any:
        if type(val) is ColdRef:
            return msgpack.unpackb(self.cold.read_value(val))
        return val

    def to_snapshot_bytes(self) -> bytes:
        """Full serialization with cold frames resolved in place: the bytes
        are identical to an untiered db holding the same logical state (the
        chain a follower installs or recovery loads never knows tiers)."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        body = msgpack.packb(
            [[k, self._resolve(self._data[k])] for k in self._sorted_keys]
        )
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.SNAPSHOT_MAGIC + struct.pack("<I", crc) + body

    def to_delta_bytes(self) -> bytes:
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        if self._dirty_keys is None:
            raise RuntimeError("delta tracking is not active")
        data = self._data
        entries = []
        for key in sorted(self._dirty_keys):
            if key in data:
                entries.append([key, False, self._resolve(data[key])])
            else:
                entries.append([key, True, None])
        body = msgpack.packb(entries)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.DELTA_MAGIC + struct.pack("<I", crc) + body

    def content_equals(self, other: ZbDb) -> bool:
        if set(self._data) != set(other._data):
            return False
        other_resolve = getattr(other, "_resolve", lambda v: v)
        for key, val in self._data.items():
            if self._resolve(val) != other_resolve(other._data[key]):
                return False
        return True

    # -- accounting ------------------------------------------------------------

    def tier_stats(self) -> dict:
        cold_keys = self.cold.live_entries
        hot_keys = len(self._data) - cold_keys
        return {
            "hotKeys": hot_keys,
            "coldKeys": cold_keys,
            "coldBytes": self.cold.live_bytes,
            "coldDiskBytes": self.cold.disk_bytes,
            "coldSegments": self.cold.segment_count,
            # estimate: resident hot values × learned mean packed size (the
            # exact number would cost an O(hot) pack pass)
            "hotBytesEstimate": int(hot_keys * self._avg_packed),
            "spills": self.spills_total,
            "faults": self.faults_total,
        }

    def close(self) -> None:
        self.cold.close()


@dataclasses.dataclass
class TieringCfg:
    """Knobs (env: ``ZEEBE_BROKER_DATA_TIERING*``, broker/config.py)."""

    enabled: bool = False
    #: an instance must stay parked this long before it spills — short
    #: waits (job round-trips, immediate correlations) never touch disk
    park_after_ms: int = 30_000
    #: instances spilled per pump pass (bounds pump-stall per pass)
    spill_batch: int = 256
    #: tiering-manager pass cadence on the pump
    check_interval_ms: int = 1_000
    #: cold segment roll size
    segment_max_bytes: int = 32 << 20


class TieringManager:
    """Decides *what* parks and *when* it spills; the db does the moving.

    Candidates arrive via ``ZbDb.note_parked`` (timer created, message
    subscription opened, job created — fired on processing AND replay, so a
    promoted follower's manager is warm). A candidate that stays parked past
    ``park_after_ms`` has its whole instance subtree spilled: element
    instances (walked through the parent/child index), their variables,
    message subscriptions, timers, and jobs. Wake-ups are observed through
    the db's first-fault ``woken_listener`` so instance accounting stays
    honest without any read-path bookkeeping."""

    def __init__(self, db: TieredZbDb, clock_millis: Callable[[], int],
                 cfg: TieringCfg, partition_id: int = 0) -> None:
        self.db = db
        self.clock_millis = clock_millis
        self.cfg = cfg
        self.partition_id = partition_id
        self._candidates: OrderedDict[int, int] = OrderedDict()
        self._spilled: set[int] = set()
        self._last_check_ms = 0
        # write-error degradation (ISSUE 9 satellite): a persistent OSError
        # (ENOSPC/EIO) during spill/compaction latches DEGRADED — no new
        # spill batches are admitted, the pump thread survives, and cold
        # values already faulted-in (or still readable) keep serving. The
        # next partition transition rebuilds the manager (and wipes the
        # cold dir), which is the retry path.
        self.degraded = False
        self.degraded_reason: str | None = None
        db.park_listener = self.note_parked
        db.woken_listener = self.note_woken
        self._m_instances = _M_SPILLED_INSTANCES.labels(str(partition_id))
        self._m_segments = _M_COLD_SEGMENTS.labels(str(partition_id))

    # -- seams -----------------------------------------------------------------

    def note_parked(self, process_instance_key: int) -> None:
        if process_instance_key < 0 or process_instance_key in self._spilled:
            return
        if process_instance_key not in self._candidates:
            self._candidates[process_instance_key] = self.clock_millis()

    def note_woken(self, process_instance_key: int) -> None:
        if process_instance_key in self._spilled:
            self._spilled.discard(process_instance_key)
            self._m_instances.set(float(len(self._spilled)))

    @property
    def spilled_instances(self) -> int:
        return len(self._spilled)

    @property
    def pending_candidates(self) -> int:
        return len(self._candidates)

    # -- the pump hook ---------------------------------------------------------

    def maybe_run(self, now_ms: int | None = None) -> int:
        """One tiering pass (throttled): spill due candidates, reclaim cold
        garbage. Called from the partition pump between transactions."""
        now = self.clock_millis() if now_ms is None else now_ms
        if now - self._last_check_ms < self.cfg.check_interval_ms:
            return 0
        self._last_check_ms = now
        if self.degraded:
            return 0  # no new spill batches; reads/fault-ins stay servable
        spilled = 0
        horizon = now - self.cfg.park_after_ms
        try:
            while self._candidates and spilled < self.cfg.spill_batch:
                pi_key, noted_at = next(iter(self._candidates.items()))
                if noted_at > horizon:
                    break  # FIFO order: the rest are younger
                self._candidates.popitem(last=False)
                if self.spill_instance(pi_key):
                    spilled += 1
            if spilled:
                self._m_instances.set(float(len(self._spilled)))
            self.db.compact_cold()
            self._m_segments.set(float(self.db.cold.segment_count))
        except OSError as exc:
            # ENOSPC/EIO on the cold dir: a half-appended frame is harmless
            # (refs only publish after flush), but the segment write cursor
            # can no longer be trusted — latch DEGRADED instead of poisoning
            # the pump thread on every pass
            self.degraded = True
            self.degraded_reason = f"{type(exc).__name__}: {exc}"
            _M_TIER_WRITE_ERRORS.labels(str(self.partition_id)).inc()
            import logging

            logging.getLogger("zeebe_tpu.state.tiering").error(
                "partition %s cold-tier write failed (%s); tiering DEGRADED "
                "— parked instances stay hot, cold reads keep serving",
                self.partition_id, self.degraded_reason)
        return spilled

    # -- instance spilling -----------------------------------------------------

    def instance_keys(self, pi_key: int) -> list[bytes]:
        """The committed key set of one process instance's parked state:
        element-instance records (tree walk over the parent/child index),
        variables per scope, message subscriptions, timers, and jobs.
        Committed-read only (runs between transactions on the pump)."""
        from zeebe_tpu.engine.engine_state import _decode_trailing_i64
        from zeebe_tpu.state import ColumnFamilyCode as CF

        db = self.db
        data = db._data
        out: list[bytes] = []
        element_keys = [pi_key]
        frontier = [pi_key]
        while frontier:
            scope = frontier.pop()
            for enc in db.committed_keys_of(
                    CF.ELEMENT_INSTANCE_PARENT_CHILD, (scope,)):
                child = _decode_trailing_i64(enc)
                element_keys.append(child)
                frontier.append(child)
        for e in element_keys:
            ei_key = encode_key(CF.ELEMENT_INSTANCE_KEY, (e,))
            record = data.get(ei_key)
            out.append(ei_key)
            out.extend(db.committed_keys_of(CF.VARIABLES, (e,)))
            out.extend(db.committed_keys_of(
                CF.PROCESS_SUBSCRIPTION_BY_KEY, (e,)))
            for enc in db.committed_keys_of(CF.TIMER_BY_ELEMENT, (e,)):
                out.append(encode_key(CF.TIMERS,
                                      (_decode_trailing_i64(enc),)))
            if type(record) is dict:
                job_key = record.get("jobKey", -1)
                if job_key is not None and job_key >= 0:
                    out.append(encode_key(CF.JOBS, (job_key,)))
        return out

    def spill_instance(self, pi_key: int) -> bool:
        db = self.db
        from zeebe_tpu.state import ColumnFamilyCode as CF

        root = db._data.get(encode_key(CF.ELEMENT_INSTANCE_KEY, (pi_key,)))
        if root is None or type(root) is ColdRef:
            return False  # instance finished, or already cold
        n, _ = db.spill_keys(self.instance_keys(pi_key), tag=pi_key)
        if n == 0:
            return False
        self._spilled.add(pi_key)
        return True
