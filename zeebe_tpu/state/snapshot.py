"""File-based snapshot store: transient → persisted snapshot lifecycle.

Reference: snapshot/src/main/java/io/camunda/zeebe/snapshots/impl/
FileBasedSnapshotStore.java:51, FileBasedSnapshotId.java, SfvChecksumImpl.java,
FileBasedSnapshotChunkReader.java.

A snapshot is a directory of files identified by
``<index>-<term>-<processedPosition>-<exportedPosition>``; it is written into a
pending dir, checksummed (one CRC per file recorded in an SFV-style manifest),
then atomically renamed into place. Only the latest valid snapshot *chain* is
kept (older ones are purged on persist), except snapshots pinned by a
reservation (backup in progress). A chunk reader serves replication to
followers.

Incremental snapshots (ISSUE 6): a snapshot may be a **base** (full
``state.bin``) or a **delta** (``delta.bin`` holding the changed keys since
its parent, plus a ``chain.bin`` naming the parent snapshot id). Recovery
resolves the newest snapshot whose whole chain — base through tip — exists
and verifies; a torn or corrupt member invalidates every descendant, and
recovery falls back to the newest fully-valid ancestor chain (or an older
independent chain) instead of crashing. ``_purge_older_than`` keeps the kept
snapshot's ancestors alive, so a chain can never lose its base to the purge.
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import zlib
from pathlib import Path
from typing import Callable, Iterator

from zeebe_tpu.utils import storage_io

_ID_RE = re.compile(r"^(\d+)-(\d+)-(\d+)-(\d+)$")
_MANIFEST = "CHECKSUM.sfv"
_CHAIN_FILE = "chain.bin"
STATE_FILE = "state.bin"
DELTA_FILE = "delta.bin"


class InvalidSnapshotError(Exception):
    pass


@dataclasses.dataclass(frozen=True, order=True, slots=True)
class SnapshotId:
    """Ordering is by (index, term, processed_position, exported_position) —
    field order matters for comparisons (reference: FileBasedSnapshotId)."""

    index: int
    term: int
    processed_position: int
    exported_position: int

    def __str__(self) -> str:
        return f"{self.index}-{self.term}-{self.processed_position}-{self.exported_position}"

    @classmethod
    def parse(cls, name: str) -> "SnapshotId | None":
        m = _ID_RE.match(name)
        if not m:
            return None
        return cls(*(int(g) for g in m.groups()))


def _file_crc(path: Path) -> int:
    crc = 0
    with storage_io.open_file(path, "rb") as f:
        while chunk := f.read(1 << 20):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def file_crc(path: Path) -> int:
    """Public alias of the store's one file-CRC rule (the at-rest scrubber
    must compute exactly what the manifest verifier compares)."""
    return _file_crc(path)


def manifest_entries(directory: Path) -> dict[str, int] | None:
    """Parse a snapshot directory's manifest into {file name: crc}, or
    None when the manifest is missing/unreadable/malformed — the scrubber's
    per-file walk (verify ONE file per slice, not the whole chain) reads
    expectations through this so its CRC rule can never drift from
    ``_verify_manifest``."""
    manifest = directory / _MANIFEST
    try:
        expected: dict[str, int] = {}
        for line in manifest.read_text().splitlines():
            name, sep, crc = line.partition("\t")
            if not sep or not name:
                return None
            expected[name] = int(crc, 16)
        return expected
    except (OSError, ValueError):
        return None


def manifest_bytes(files: dict[str, bytes]) -> bytes:
    """The SFV-style manifest for a set of in-memory snapshot files — the
    ONE owner of the line format `_verify_manifest` checks (backup's
    materialized-chain path builds snapshots outside `persist()` and must
    stay restorable)."""
    return "".join(
        f"{name}\t{zlib.crc32(data) & 0xFFFFFFFF:08x}\n"
        for name, data in sorted(files.items())
        if name != _MANIFEST
    ).encode()


def _write_manifest(directory: Path) -> None:
    lines = []
    for p in sorted(directory.iterdir()):
        if p.name != _MANIFEST and p.is_file():
            lines.append(f"{p.name}\t{_file_crc(p):08x}\n")
    storage_io.write_text(directory / _MANIFEST, "".join(lines))


def _verify_manifest(directory: Path) -> bool:
    """True iff the directory's manifest exists, parses, and matches every
    file. Never raises: a torn/partially-written snapshot (power loss during
    commit) must be *skipped* by recovery, not crash it — a malformed
    manifest line, an unreadable file, or a vanished directory all read as
    "invalid"."""
    manifest = directory / _MANIFEST
    try:
        if not manifest.exists():
            return False
        expected = {}
        for line in manifest.read_text().splitlines():
            name, sep, crc = line.partition("\t")
            if not sep or not name:
                return False
            expected[name] = int(crc, 16)
        actual = {
            p.name: _file_crc(p)
            for p in directory.iterdir()
            if p.is_file() and p.name != _MANIFEST
        }
        return expected == actual
    except (OSError, ValueError):
        return False


@dataclasses.dataclass(frozen=True, slots=True)
class PersistedSnapshot:
    id: SnapshotId
    path: Path

    def files(self) -> list[Path]:
        return sorted(p for p in self.path.iterdir() if p.is_file())

    def read_file(self, name: str) -> bytes:
        return (self.path / name).read_bytes()

    def has_file(self, name: str) -> bool:
        return (self.path / name).is_file()

    @property
    def is_delta(self) -> bool:
        return self.has_file(DELTA_FILE)

    def parent_id(self) -> "SnapshotId | None":
        """Parent snapshot id for a delta snapshot (from chain.bin), None for
        a base snapshot or on any read/parse failure (the chain validator
        treats an unreadable link as a broken chain)."""
        try:
            raw = (self.path / _CHAIN_FILE).read_bytes()
        except OSError:
            return None
        try:
            from zeebe_tpu.protocol.msgpack import unpackb

            return SnapshotId.parse(unpackb(raw).get("parent", ""))
        except Exception:  # noqa: BLE001 — corrupt chain meta = no parent
            return None


@dataclasses.dataclass(frozen=True, slots=True)
class SnapshotChunk:
    """One replication unit (reference: SnapshotChunk SBE message)."""

    snapshot_id: str
    chunk_name: str
    offset: int
    total_size: int
    data: bytes
    checksum: int


class TransientSnapshot:
    """A snapshot being taken; becomes persisted (and visible) only on persist()."""

    def __init__(self, store: "FileBasedSnapshotStore", snap_id: SnapshotId) -> None:
        self._store = store
        self.id = snap_id
        self.path = store.pending_dir / str(snap_id)
        if self.path.exists():
            shutil.rmtree(self.path)
        self.path.mkdir(parents=True)
        self._taken = False

    def take(self, writer: Callable[[Path], None]) -> None:
        """Run ``writer(dir)`` to populate the snapshot directory."""
        writer(self.path)
        self._taken = True

    def write_file(self, name: str, data: bytes) -> None:
        storage_io.write_bytes(self.path / name, data)
        self._taken = True

    def link_parent(self, parent: PersistedSnapshot, depth: int) -> None:
        """Mark this transient as a delta on ``parent`` (chain.bin carries
        the parent id and the 1-based chain depth of this snapshot)."""
        from zeebe_tpu.protocol.msgpack import packb

        self.write_file(_CHAIN_FILE, packb(
            {"parent": str(parent.id), "depth": depth}))

    def persist(self) -> PersistedSnapshot:
        if not self._taken:
            raise InvalidSnapshotError("transient snapshot has no content")
        _write_manifest(self.path)
        return self._store._persist(self)

    def abort(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)


class FileBasedSnapshotStore:
    """Snapshot lifecycle manager for one partition's state directory."""

    def __init__(self, directory: str | Path) -> None:
        self.root = Path(directory)
        self.snapshots_dir = self.root / "snapshots"
        self.pending_dir = self.root / "pending"
        self.snapshots_dir.mkdir(parents=True, exist_ok=True)
        self.pending_dir.mkdir(parents=True, exist_ok=True)
        self._reservations: set[SnapshotId] = set()
        # clean pending leftovers from a crash
        for p in self.pending_dir.iterdir():
            shutil.rmtree(p, ignore_errors=True)
        # drop corrupt persisted snapshots (crash mid-rename etc.)
        for p in list(self.snapshots_dir.iterdir()):
            snap_id = SnapshotId.parse(p.name)
            if snap_id is None or not _verify_manifest(p):
                shutil.rmtree(p, ignore_errors=True)

    # -- queries -------------------------------------------------------------

    def latest_snapshot(self) -> PersistedSnapshot | None:
        best: SnapshotId | None = None
        for p in self.snapshots_dir.iterdir():
            snap_id = SnapshotId.parse(p.name)
            if snap_id is not None and (best is None or snap_id > best):
                best = snap_id
        if best is None:
            return None
        return PersistedSnapshot(best, self.snapshots_dir / str(best))

    def list_snapshots(self) -> list[PersistedSnapshot]:
        out = []
        for p in sorted(self.snapshots_dir.iterdir()):
            snap_id = SnapshotId.parse(p.name)
            if snap_id is not None:
                out.append(PersistedSnapshot(snap_id, p))
        return sorted(out, key=lambda s: s.id)

    # -- chains (incremental snapshots) --------------------------------------

    def snapshot_at(self, snap_id: SnapshotId) -> PersistedSnapshot | None:
        path = self.snapshots_dir / str(snap_id)
        return PersistedSnapshot(snap_id, path) if path.is_dir() else None

    def chain_of(self, snapshot: PersistedSnapshot
                 ) -> list[PersistedSnapshot] | None:
        """Resolve and validate ``snapshot``'s full chain, base → tip.

        Returns None when ANY member is torn (manifest mismatch), missing,
        structurally wrong (a delta without a parent file, a base without
        state), or the parent links cycle — the caller falls back to an
        older snapshot instead of recovering from a broken chain."""
        chain = [snapshot]
        seen = {snapshot.id}
        cur = snapshot
        while True:
            if not _verify_manifest(cur.path):
                return None
            parent_id = cur.parent_id()
            if parent_id is None:
                if cur.is_delta:
                    return None  # delta whose parent link is unreadable
                break  # a base: full state.bin or a durable marker
            if not cur.is_delta or parent_id in seen:
                return None
            parent = self.snapshot_at(parent_id)
            if parent is None:
                return None
            seen.add(parent_id)
            chain.append(parent)
            cur = parent
        chain.reverse()
        return chain

    def iter_valid_chains(self) -> Iterator[list[PersistedSnapshot]]:
        """Valid chains, newest tip first — recovery takes the first one it
        can actually load, so a corrupt tip falls back to the last fully-
        valid ancestor (which is itself a persisted snapshot)."""
        for snapshot in reversed(self.list_snapshots()):
            chain = self.chain_of(snapshot)
            if chain is not None:
                yield chain

    def latest_valid_chain(self) -> list[PersistedSnapshot] | None:
        return next(self.iter_valid_chains(), None)

    def _ancestor_ids(self, snap_id: SnapshotId) -> set[SnapshotId]:
        """``snap_id`` plus every ancestor reachable through parent links
        (validity not required here: the purge must err on keeping)."""
        out = {snap_id}
        cur = self.snapshot_at(snap_id)
        while cur is not None:
            parent_id = cur.parent_id()
            if parent_id is None or parent_id in out:
                break
            out.add(parent_id)
            cur = self.snapshot_at(parent_id)
        return out

    # -- take ----------------------------------------------------------------

    def new_transient_snapshot(
        self, index: int, term: int, processed_position: int, exported_position: int
    ) -> TransientSnapshot:
        snap_id = SnapshotId(index, term, processed_position, exported_position)
        latest = self.latest_snapshot()
        if latest is not None and snap_id <= latest.id:
            raise InvalidSnapshotError(
                f"snapshot {snap_id} is not newer than latest {latest.id}"
            )
        return TransientSnapshot(self, snap_id)

    def _persist(self, transient: TransientSnapshot) -> PersistedSnapshot:
        target = self.snapshots_dir / str(transient.id)
        if target.exists():
            shutil.rmtree(target)
        # make file *contents* durable before the rename publishes the
        # snapshot — else a crash yields a "persisted" snapshot with torn
        # data after the log prefix was compacted away
        for p in transient.path.iterdir():
            if p.is_file():
                storage_io.fsync_path(p)
        self._fsync_dir(transient.path)
        storage_io.replace(transient.path, target)
        self._fsync_dir(self.snapshots_dir)
        self._purge_older_than(transient.id)
        return PersistedSnapshot(transient.id, target)

    @staticmethod
    def _fsync_dir(path: Path) -> None:
        storage_io.fsync_path(path)

    def _purge_older_than(self, keep: SnapshotId) -> None:
        # chain-aware: the kept snapshot's ancestors (its delta chain's base
        # and intermediates) and every reserved snapshot's ancestors survive
        # — purging a base out from under a live delta chain would turn the
        # latest snapshot unrecoverable
        protected = self._ancestor_ids(keep)
        for reserved in self._reservations:
            protected |= self._ancestor_ids(reserved)
        for snap in self.list_snapshots():
            if snap.id < keep and snap.id not in protected:
                shutil.rmtree(snap.path, ignore_errors=True)

    # -- at-rest integrity (ISSUE 14) ----------------------------------------

    def quarantine(self, snapshot: PersistedSnapshot) -> Path | None:
        """Move a corrupt snapshot OUT of the recovery path: the directory
        is renamed to ``<id>.corrupt`` (bits preserved for postmortems, but
        ``SnapshotId.parse`` no longer matches, so queries, chains, and a
        later recovery all skip it and a replacement snapshot at the same
        positions is permitted again). Returns the quarantine path, or None
        when the rename failed (the snapshot stays visibly corrupt and the
        scrubber stays DEGRADED)."""
        target = snapshot.path.with_name(snapshot.path.name + ".corrupt")
        try:
            if target.exists():
                shutil.rmtree(target, ignore_errors=True)
            storage_io.replace(snapshot.path, target)
            return target
        except OSError:
            return None

    # -- reservations (pin during backup) ------------------------------------

    def reserve(self, snap_id: SnapshotId) -> None:
        self._reservations.add(snap_id)

    def release(self, snap_id: SnapshotId) -> None:
        self._reservations.discard(snap_id)
        latest = self.latest_snapshot()
        if latest is not None:
            self._purge_older_than(latest.id)

    # -- replication ---------------------------------------------------------

    def chunk_reader(
        self, snapshot: PersistedSnapshot, chunk_size: int = 1 << 20
    ) -> Iterator[SnapshotChunk]:
        """Stream a snapshot as checksummed chunks (leader → follower install)."""
        for f in snapshot.files():
            data = f.read_bytes()
            total = len(data)
            for off in range(0, max(total, 1), chunk_size):
                piece = data[off : off + chunk_size]
                yield SnapshotChunk(
                    snapshot_id=str(snapshot.id),
                    chunk_name=f.name,
                    offset=off,
                    total_size=total,
                    data=piece,
                    checksum=zlib.crc32(piece) & 0xFFFFFFFF,
                )

    def receive_snapshot(self, chunks: Iterator[SnapshotChunk]) -> PersistedSnapshot:
        """Follower side: rebuild a snapshot from replicated chunks."""
        transient: TransientSnapshot | None = None
        files: dict[str, bytearray] = {}
        snap_id: SnapshotId | None = None
        for chunk in chunks:
            if zlib.crc32(chunk.data) & 0xFFFFFFFF != chunk.checksum:
                raise InvalidSnapshotError(f"chunk checksum mismatch: {chunk.chunk_name}")
            if snap_id is None:
                snap_id = SnapshotId.parse(chunk.snapshot_id)
                if snap_id is None:
                    raise InvalidSnapshotError(f"bad snapshot id {chunk.snapshot_id}")
            buf = files.setdefault(chunk.chunk_name, bytearray())
            if len(buf) != chunk.offset:
                raise InvalidSnapshotError(f"out-of-order chunk for {chunk.chunk_name}")
            buf += chunk.data
        if snap_id is None:
            raise InvalidSnapshotError("no chunks received")
        transient = TransientSnapshot(self, snap_id)
        for name, buf in files.items():
            transient.write_file(name, bytes(buf))
        return transient.persist()


# -- chain loading (shared by partition recovery, chaos oracle, backup) -------


def load_chain_db(chain: list[PersistedSnapshot], consistency_checks: bool = False,
                  db=None):
    """Materialize a validated snapshot chain into a ZbDb: install the base's
    full ``state.bin`` (one bulk pass — O(n log n), not per-key insorts),
    then apply each delta in order. Raises ValueError on a base without
    state (durable-marker chains are the caller's special case) or on
    checksum mismatches the manifest somehow missed.

    ``db``: install into this (empty) instance instead of a fresh ``ZbDb`` —
    the tiered backend recovers through here (state/tiering.py)."""
    from zeebe_tpu.state.db import ZbDb

    base = chain[0]
    if not base.has_file(STATE_FILE):
        raise ValueError(f"chain base {base.id} has no {STATE_FILE}")
    if db is None:
        db = ZbDb(consistency_checks=consistency_checks)
    db.load_snapshot_bytes(base.read_file(STATE_FILE))
    for delta in chain[1:]:
        db.apply_delta_bytes(delta.read_file(DELTA_FILE))
    return db


# -- read-only inspection (cli snapshots) -------------------------------------


def inspect_store(directory: str | Path) -> list[dict]:
    """Describe every snapshot under a store root WITHOUT mutating anything —
    unlike constructing a FileBasedSnapshotStore (which deletes pending
    leftovers and corrupt snapshots on open), this is safe to point at a
    live or postmortem data directory. Returns one dict per snapshot, oldest
    first: id, positions, kind (full/delta/durable-marker), per-file sizes,
    manifest validity, parent link, and whether the full chain validates."""
    root = Path(directory)
    snapshots_dir = root / "snapshots"
    if not snapshots_dir.is_dir():
        return []
    snapshots: list[PersistedSnapshot] = []
    for p in sorted(snapshots_dir.iterdir()):
        snap_id = SnapshotId.parse(p.name)
        if snap_id is not None and p.is_dir():
            snapshots.append(PersistedSnapshot(snap_id, p))
    snapshots.sort(key=lambda s: s.id)
    by_id = {s.id: s for s in snapshots}
    valid = {s.id: _verify_manifest(s.path) for s in snapshots}

    def chain_valid(snap: PersistedSnapshot) -> tuple[bool, int]:
        depth, cur, seen = 1, snap, {snap.id}
        while True:
            if not valid.get(cur.id, False):
                return False, depth
            parent_id = cur.parent_id()
            if parent_id is None:
                return (not cur.is_delta), depth
            if not cur.is_delta or parent_id in seen or parent_id not in by_id:
                return False, depth
            seen.add(parent_id)
            cur = by_id[parent_id]
            depth += 1

    out = []
    for snap in snapshots:
        if snap.has_file(STATE_FILE):
            kind = "full"
        elif snap.is_delta:
            kind = "delta"
        elif snap.has_file("durable.bin"):
            kind = "durable-marker"
        else:
            kind = "unknown"
        files = {}
        try:
            for f in snap.files():
                files[f.name] = f.stat().st_size
        except OSError:
            pass
        ok, depth = chain_valid(snap)
        parent = snap.parent_id()
        out.append({
            "id": str(snap.id),
            "kind": kind,
            "processedPosition": snap.id.processed_position,
            "exportedPosition": snap.id.exported_position,
            "files": files,
            "sizeBytes": sum(files.values()),
            "valid": valid.get(snap.id, False),
            "parent": str(parent) if parent is not None else None,
            "chainLength": depth,
            "chainValid": ok,
        })
    return out
