"""File-based snapshot store: transient → persisted snapshot lifecycle.

Reference: snapshot/src/main/java/io/camunda/zeebe/snapshots/impl/
FileBasedSnapshotStore.java:51, FileBasedSnapshotId.java, SfvChecksumImpl.java,
FileBasedSnapshotChunkReader.java.

A snapshot is a directory of files identified by
``<index>-<term>-<processedPosition>-<exportedPosition>``; it is written into a
pending dir, checksummed (one CRC per file recorded in an SFV-style manifest),
then atomically renamed into place. Only the latest valid snapshot is kept
(older ones are purged on persist), except snapshots pinned by a reservation
(backup in progress). A chunk reader serves replication to followers.
"""

from __future__ import annotations

import dataclasses
import os
import re
import shutil
import zlib
from pathlib import Path
from typing import Callable, Iterator

_ID_RE = re.compile(r"^(\d+)-(\d+)-(\d+)-(\d+)$")
_MANIFEST = "CHECKSUM.sfv"


class InvalidSnapshotError(Exception):
    pass


@dataclasses.dataclass(frozen=True, order=True, slots=True)
class SnapshotId:
    """Ordering is by (index, term, processed_position, exported_position) —
    field order matters for comparisons (reference: FileBasedSnapshotId)."""

    index: int
    term: int
    processed_position: int
    exported_position: int

    def __str__(self) -> str:
        return f"{self.index}-{self.term}-{self.processed_position}-{self.exported_position}"

    @classmethod
    def parse(cls, name: str) -> "SnapshotId | None":
        m = _ID_RE.match(name)
        if not m:
            return None
        return cls(*(int(g) for g in m.groups()))


def _file_crc(path: Path) -> int:
    crc = 0
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _write_manifest(directory: Path) -> None:
    lines = []
    for p in sorted(directory.iterdir()):
        if p.name != _MANIFEST and p.is_file():
            lines.append(f"{p.name}\t{_file_crc(p):08x}\n")
    (directory / _MANIFEST).write_text("".join(lines))


def _verify_manifest(directory: Path) -> bool:
    manifest = directory / _MANIFEST
    if not manifest.exists():
        return False
    expected = {}
    for line in manifest.read_text().splitlines():
        name, _, crc = line.partition("\t")
        expected[name] = int(crc, 16)
    actual = {
        p.name: _file_crc(p) for p in directory.iterdir() if p.is_file() and p.name != _MANIFEST
    }
    return expected == actual


@dataclasses.dataclass(frozen=True, slots=True)
class PersistedSnapshot:
    id: SnapshotId
    path: Path

    def files(self) -> list[Path]:
        return sorted(p for p in self.path.iterdir() if p.is_file())

    def read_file(self, name: str) -> bytes:
        return (self.path / name).read_bytes()


@dataclasses.dataclass(frozen=True, slots=True)
class SnapshotChunk:
    """One replication unit (reference: SnapshotChunk SBE message)."""

    snapshot_id: str
    chunk_name: str
    offset: int
    total_size: int
    data: bytes
    checksum: int


class TransientSnapshot:
    """A snapshot being taken; becomes persisted (and visible) only on persist()."""

    def __init__(self, store: "FileBasedSnapshotStore", snap_id: SnapshotId) -> None:
        self._store = store
        self.id = snap_id
        self.path = store.pending_dir / str(snap_id)
        if self.path.exists():
            shutil.rmtree(self.path)
        self.path.mkdir(parents=True)
        self._taken = False

    def take(self, writer: Callable[[Path], None]) -> None:
        """Run ``writer(dir)`` to populate the snapshot directory."""
        writer(self.path)
        self._taken = True

    def write_file(self, name: str, data: bytes) -> None:
        (self.path / name).write_bytes(data)
        self._taken = True

    def persist(self) -> PersistedSnapshot:
        if not self._taken:
            raise InvalidSnapshotError("transient snapshot has no content")
        _write_manifest(self.path)
        return self._store._persist(self)

    def abort(self) -> None:
        shutil.rmtree(self.path, ignore_errors=True)


class FileBasedSnapshotStore:
    """Snapshot lifecycle manager for one partition's state directory."""

    def __init__(self, directory: str | Path) -> None:
        self.root = Path(directory)
        self.snapshots_dir = self.root / "snapshots"
        self.pending_dir = self.root / "pending"
        self.snapshots_dir.mkdir(parents=True, exist_ok=True)
        self.pending_dir.mkdir(parents=True, exist_ok=True)
        self._reservations: set[SnapshotId] = set()
        # clean pending leftovers from a crash
        for p in self.pending_dir.iterdir():
            shutil.rmtree(p, ignore_errors=True)
        # drop corrupt persisted snapshots (crash mid-rename etc.)
        for p in list(self.snapshots_dir.iterdir()):
            snap_id = SnapshotId.parse(p.name)
            if snap_id is None or not _verify_manifest(p):
                shutil.rmtree(p, ignore_errors=True)

    # -- queries -------------------------------------------------------------

    def latest_snapshot(self) -> PersistedSnapshot | None:
        best: SnapshotId | None = None
        for p in self.snapshots_dir.iterdir():
            snap_id = SnapshotId.parse(p.name)
            if snap_id is not None and (best is None or snap_id > best):
                best = snap_id
        if best is None:
            return None
        return PersistedSnapshot(best, self.snapshots_dir / str(best))

    def list_snapshots(self) -> list[PersistedSnapshot]:
        out = []
        for p in sorted(self.snapshots_dir.iterdir()):
            snap_id = SnapshotId.parse(p.name)
            if snap_id is not None:
                out.append(PersistedSnapshot(snap_id, p))
        return sorted(out, key=lambda s: s.id)

    # -- take ----------------------------------------------------------------

    def new_transient_snapshot(
        self, index: int, term: int, processed_position: int, exported_position: int
    ) -> TransientSnapshot:
        snap_id = SnapshotId(index, term, processed_position, exported_position)
        latest = self.latest_snapshot()
        if latest is not None and snap_id <= latest.id:
            raise InvalidSnapshotError(
                f"snapshot {snap_id} is not newer than latest {latest.id}"
            )
        return TransientSnapshot(self, snap_id)

    def _persist(self, transient: TransientSnapshot) -> PersistedSnapshot:
        target = self.snapshots_dir / str(transient.id)
        if target.exists():
            shutil.rmtree(target)
        # make file *contents* durable before the rename publishes the
        # snapshot — else a crash yields a "persisted" snapshot with torn
        # data after the log prefix was compacted away
        for p in transient.path.iterdir():
            if p.is_file():
                fd = os.open(p, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
        self._fsync_dir(transient.path)
        os.replace(transient.path, target)
        self._fsync_dir(self.snapshots_dir)
        self._purge_older_than(transient.id)
        return PersistedSnapshot(transient.id, target)

    @staticmethod
    def _fsync_dir(path: Path) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _purge_older_than(self, keep: SnapshotId) -> None:
        for snap in self.list_snapshots():
            if snap.id < keep and snap.id not in self._reservations:
                shutil.rmtree(snap.path, ignore_errors=True)

    # -- reservations (pin during backup) ------------------------------------

    def reserve(self, snap_id: SnapshotId) -> None:
        self._reservations.add(snap_id)

    def release(self, snap_id: SnapshotId) -> None:
        self._reservations.discard(snap_id)
        latest = self.latest_snapshot()
        if latest is not None:
            self._purge_older_than(latest.id)

    # -- replication ---------------------------------------------------------

    def chunk_reader(
        self, snapshot: PersistedSnapshot, chunk_size: int = 1 << 20
    ) -> Iterator[SnapshotChunk]:
        """Stream a snapshot as checksummed chunks (leader → follower install)."""
        for f in snapshot.files():
            data = f.read_bytes()
            total = len(data)
            for off in range(0, max(total, 1), chunk_size):
                piece = data[off : off + chunk_size]
                yield SnapshotChunk(
                    snapshot_id=str(snapshot.id),
                    chunk_name=f.name,
                    offset=off,
                    total_size=total,
                    data=piece,
                    checksum=zlib.crc32(piece) & 0xFFFFFFFF,
                )

    def receive_snapshot(self, chunks: Iterator[SnapshotChunk]) -> PersistedSnapshot:
        """Follower side: rebuild a snapshot from replicated chunks."""
        transient: TransientSnapshot | None = None
        files: dict[str, bytearray] = {}
        snap_id: SnapshotId | None = None
        for chunk in chunks:
            if zlib.crc32(chunk.data) & 0xFFFFFFFF != chunk.checksum:
                raise InvalidSnapshotError(f"chunk checksum mismatch: {chunk.chunk_name}")
            if snap_id is None:
                snap_id = SnapshotId.parse(chunk.snapshot_id)
                if snap_id is None:
                    raise InvalidSnapshotError(f"bad snapshot id {chunk.snapshot_id}")
            buf = files.setdefault(chunk.chunk_name, bytearray())
            if len(buf) != chunk.offset:
                raise InvalidSnapshotError(f"out-of-order chunk for {chunk.chunk_name}")
            buf += chunk.data
        if snap_id is None:
            raise InvalidSnapshotError("no chunks received")
        transient = TransientSnapshot(self, snap_id)
        for name, buf in files.items():
            transient.write_file(name, bytes(buf))
        return transient.persist()
