"""Disk-backed partition state with O(delta) checkpoints — the large-state
backend (VERDICT r4 item 2).

Reference anchors: zb-db/src/main/java/io/camunda/zeebe/db/impl/rocksdb/
transaction/ZeebeTransaction.java:22 (RocksDB transactional store whose
checkpoints are O(delta) hard links) and broker/…/partitions/impl/perf/
LargeStateControllerPerformanceTest.java:46,69-78 (≥10 snapshot+recover ops/s
on 4 GB of state). The design here is NOT a RocksDB port — it exploits this
framework's own invariant that the replicated log is the durability source of
truth (state is always recomputable by replay), so the disk structures only
need crash-consistency, not synchronous durability:

- **Hot/cold split**: committed values start life as the Python objects the
  engine wrote (hot). A size-budgeted LRU demotes cold values to their
  msgpack bytes (``_Packed``), so resident memory tracks the SERIALIZED state
  size instead of the Python-object expansion — the 0.5–4 GB anchors fit
  where a pure object heap would not. Reads resolve cold values lazily and
  re-promote them.
- **Write-ahead delta log**: every transaction commit appends its overlay
  (the changed keys only) to the current WAL segment — O(delta) per commit,
  buffered, no fsync on the hot path.
- **Checkpoint** = flush + fsync the WAL tail and atomically publish a tiny
  manifest. Cost is O(bytes written since the last checkpoint), never
  O(total state) — the property the in-memory ``to_snapshot_bytes`` lacked.
- **Compaction**: when the WAL chain outgrows the base, the full state is
  rewritten as a new base segment (cold values are spliced as already-packed
  bytes) and the chain resets — amortized O(1) per write.
- **Recovery** maps the base segment and indexes its KEYS only; values stay
  on disk as mmap-backed cold slices resolved (and CRC-verified) on first
  read. Recover cost ≈ key-index scan, not state size — the analogue of
  RocksDB's open-from-hard-linked-checkpoint, where nothing re-reads the
  SSTs either. The WAL chain (small by construction — compaction bounds it)
  replays eagerly.
"""

from __future__ import annotations

import mmap
import os
import struct
import zlib
from collections import OrderedDict
from pathlib import Path
from typing import Any

from zeebe_tpu.native import codec_fn as _codec_fn
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.state.db import ZbDb, _DELETED

try:
    from sortedcontainers import SortedList
except ImportError:
    from bisect import bisect_left, bisect_right, insort

    class SortedList:  # type: ignore[no-redef]
        """Blocked sorted list fallback for environments without
        sortedcontainers: the surface this module touches (add / discard /
        irange / iter / len) with the same O(sqrt n) insert bound — keys live
        in ≤2·LOAD blocks indexed by a bisect over per-block maxima, so an
        insert memmoves one block, never the whole key set."""

        __slots__ = ("_lists", "_maxes", "_len")

        LOAD = 512

        def __init__(self, iterable=()) -> None:
            keys = sorted(iterable)
            self._lists = [keys[i:i + self.LOAD]
                           for i in range(0, len(keys), self.LOAD)]
            self._maxes = [blk[-1] for blk in self._lists]
            self._len = len(keys)

        def add(self, key) -> None:
            if not self._lists:
                self._lists.append([key])
                self._maxes.append(key)
                self._len = 1
                return
            i = bisect_left(self._maxes, key)
            if i == len(self._lists):
                i -= 1
            blk = self._lists[i]
            insort(blk, key)
            self._len += 1
            if len(blk) > 2 * self.LOAD:
                half = len(blk) // 2
                self._lists[i:i + 1] = [blk[:half], blk[half:]]
                self._maxes[i:i + 1] = [blk[half - 1], blk[-1]]
            else:
                self._maxes[i] = blk[-1]

        def discard(self, key) -> None:
            i = bisect_left(self._maxes, key)
            if i == len(self._lists):
                return
            blk = self._lists[i]
            j = bisect_left(blk, key)
            if j == len(blk) or blk[j] != key:
                return
            del blk[j]
            self._len -= 1
            if blk:
                self._maxes[i] = blk[-1]
            else:
                del self._lists[i]
                del self._maxes[i]

        def irange(self, minimum=None, maximum=None,
                   inclusive=(True, True)):
            lists, maxes = self._lists, self._maxes

            def gen():
                if not lists:
                    return
                if minimum is None:
                    bi, ki = 0, 0
                else:
                    bi = bisect_left(maxes, minimum)
                    if bi == len(lists):
                        return
                    cut = bisect_left if inclusive[0] else bisect_right
                    ki = cut(lists[bi], minimum)
                while bi < len(lists):
                    blk = lists[bi]
                    while ki < len(blk):
                        key = blk[ki]
                        if maximum is not None and (
                                key > maximum
                                or (not inclusive[1] and key == maximum)):
                            return
                        yield key
                        ki += 1
                    bi += 1
                    ki = 0

            return gen()

        def bisect_left(self, key) -> int:
            i = bisect_left(self._maxes, key)
            if i == len(self._lists):
                return self._len
            before = sum(len(blk) for blk in self._lists[:i])
            return before + bisect_left(self._lists[i], key)

        def __iter__(self):
            for blk in self._lists:
                yield from blk

        def __len__(self) -> int:
            return self._len


_index_base_segment = _codec_fn("index_base_segment")

_FRAME = struct.Struct("<II")  # WAL frame: length, crc32
#: base-segment entry header: key len, value len, key crc. The value crc sits
#: AFTER the key, adjacent to the value bytes, so one contiguous mmap slice
#: [vcrc|value] is the whole cold representation — recovery then installs a
#: raw memoryview per entry (no per-entry Python object construction at all)
_ENTRY = struct.Struct("<HII")
_VCRC = struct.Struct("<I")
_MANIFEST = "MANIFEST"


class _Packed:
    """A cold committed value demoted in memory: its msgpack bytes."""

    __slots__ = ("b",)

    def __init__(self, b: bytes) -> None:
        self.b = b

    def resolve(self) -> Any:
        return msgpack.unpackb(self.b)


def _resolve_view(mv: memoryview) -> Any:
    """Resolve an mmap-backed cold slice ([vcrc u32][msgpack value]) with
    its crc check — the lazy analogue of RocksDB block checksums."""
    (crc,) = _VCRC.unpack_from(mv)
    body = mv[4:]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ValueError("corrupt cold state value (crc mismatch)")
    return msgpack.unpackb(body)


def _resolve_value(val: Any) -> Any:
    t = type(val)
    if t is _Packed:
        return val.resolve()
    if t is memoryview:
        return _resolve_view(val)
    return val


def _cold_size(val: Any) -> int:
    return len(val.b) if type(val) is _Packed else len(val) - 4


def _pack_value(value: Any) -> bytes:
    t = type(value)
    if t is _Packed:
        return value.b
    if t is memoryview:
        return bytes(value[4:])
    return msgpack.packb(value)


class DurableZbDb(ZbDb):
    """ZbDb with a disk-backed delta log and bounded object residency.

    Drop-in for the engine/processor: the transactional interface, column
    families, FK checks, and the full-serialization snapshot path
    (``to_snapshot_bytes`` — used by raft snapshot INSTALL to ship state to
    a lagging follower) are inherited. What changes:

    - ``checkpoint()``: O(delta) durable point; ``DurableZbDb.open()``
      recovers to the latest checkpoint.
    - cold values live as msgpack bytes under ``hot_budget_bytes`` of
      decoded-object budget.
    """

    # _data holds _Packed/memoryview cold representations: a delta snapshot
    # serialized from it would crash msgpack or round-trip wrong types (the
    # durable store has its own O(delta) story — checkpoint())
    supports_delta_snapshots = False

    #: knob defaults, shared by __init__ and open()
    DEFAULT_HOT_BUDGET_BYTES = 256 << 20
    DEFAULT_COMPACT_FACTOR = 2.0
    DEFAULT_MIN_COMPACT_BYTES = 64 << 20

    def __init__(self, directory: str | Path,
                 consistency_checks: bool = False,
                 hot_budget_bytes: int = DEFAULT_HOT_BUDGET_BYTES,
                 compact_factor: float = DEFAULT_COMPACT_FACTOR,
                 min_compact_bytes: int = DEFAULT_MIN_COMPACT_BYTES) -> None:
        super().__init__(consistency_checks)
        self._init_runtime(directory, hot_budget_bytes, compact_factor,
                           min_compact_bytes)
        self._open_wal()

    def _init_runtime(self, directory: str | Path, hot_budget_bytes: int,
                      compact_factor: float, min_compact_bytes: int) -> None:
        """Field setup shared by the constructor and ``open()`` (which
        bypasses ``__init__`` to stage recovery lazily)."""
        import threading

        # cold values need per-read resolution, which the native iterate
        # cannot do — use the (identical-semantics) Python merge path; and
        # the key index is a blocked SortedList (O(sqrt n) insert — a flat
        # list's O(n) memmove per new key collapses at 10^5+ keys), which
        # the native commit pass cannot mutate
        self._native_iterate = None
        self._native_commit = None
        self._sorted_keys = SortedList()
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.hot_budget_bytes = hot_budget_bytes
        self.compact_factor = compact_factor
        self.min_compact_bytes = min_compact_bytes
        # LRU of hot keys → approximate packed size (budget accounting).
        # Values live in _data; this only orders/fences them.
        self._hot: OrderedDict[bytes, int] = OrderedDict()
        self._hot_bytes = 0
        self._base_file: str | None = None
        self._base_bytes = 0
        self._wal_files: list[str] = []
        # durable length per sealed/recovered segment (a recovered segment
        # may hold frames beyond its checkpointed tail — commits that were
        # reverted by a recovery and will be re-derived by log replay; they
        # must never replay from disk ahead of their re-derivation)
        self._wal_tails: dict[str, int] = {}
        self._wal = None  # current segment handle
        self._wal_seq = 0
        self._wal_bytes = 0  # total bytes across the sealed+current chain
        # live mmaps backing cold value slices; released only at close (an
        # old base's map must outlive compaction while _data still holds
        # views into it — Linux keeps unlinked-but-mapped data readable)
        self._maps: list[mmap.mmap] = []
        self._recovery_lock = threading.Lock()

    # -- committed-store internals (SortedList key index) ---------------------

    def _put_committed(self, key: bytes, value: Any) -> None:
        if key not in self._data:
            self._sorted_keys.add(key)
        self._data[key] = value

    def _delete_committed(self, key: bytes) -> None:
        if key in self._data:
            del self._data[key]
            self._sorted_keys.discard(key)

    def _keys_with_prefix(self, prefix: bytes) -> list[bytes]:
        from zeebe_tpu.state.db import _prefix_successor

        return self._keys_in_range(prefix, _prefix_successor(prefix))

    def _keys_in_range(self, lo: bytes, hi: bytes | None) -> list[bytes]:
        if hi is None:
            return list(self._sorted_keys.irange(lo))
        return list(self._sorted_keys.irange(lo, hi, inclusive=(True, False)))

    def _first_key_at_or_after(self, lo: bytes, hi: bytes | None) -> bytes | None:
        if hi is None:
            return next(iter(self._sorted_keys.irange(lo)), None)
        return next(iter(self._sorted_keys.irange(lo, hi,
                                                  inclusive=(True, False))), None)

    def _rebuild_sorted_keys(self) -> None:
        self._sorted_keys = SortedList(self._data)

    def _install_sorted_keys(self, keys) -> None:
        self._sorted_keys = SortedList(keys)

    def _count_key_range(self, lo: bytes, hi: bytes | None) -> int:
        j = (self._sorted_keys.bisect_left(hi) if hi is not None
             else len(self._sorted_keys))
        return j - self._sorted_keys.bisect_left(lo)

    # -- wal ------------------------------------------------------------------

    def _open_wal(self) -> None:
        self._wal_seq += 1
        name = f"wal-{self._wal_seq:08d}.log"
        # "wb", not "ab": a new segment must TRUNCATE any stale file left by
        # a session that crashed before checkpointing this name into the
        # manifest — its dead frames would otherwise sit at the head and
        # replay a reverted timeline after the next checkpoint covers the
        # file (no manifest ever references a segment we are creating here:
        # manifests only list segments named by earlier, lower seqs)
        self._wal = open(self.directory / name, "wb")
        self._wal_files.append(name)

    def _pre_commit(self, writes: dict[bytes, Any]) -> None:
        if self._demote_pending:
            # demote the cold tail accumulated by earlier commits/reads;
            # safe mid-transaction — demotion only repacks COMMITTED values,
            # never overlay writes or the transaction's defensive copies
            self._maybe_demote()
        if not writes:
            return
        entries = []
        hot, data = self._hot, self._data
        for key, val in writes.items():
            if val is _DELETED:
                entries.append([key, True, b""])
                if key in hot:
                    self._hot_bytes -= hot.pop(key)
            else:
                packed = msgpack.packb(val)
                entries.append([key, False, packed])
                prev = hot.pop(key, None)
                if prev is not None:
                    self._hot_bytes -= prev
                hot[key] = len(packed)
                self._hot_bytes += len(packed)
        body = msgpack.packb(entries)
        frame = _FRAME.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body
        self._wal.write(frame)
        self._wal_bytes += len(frame)
        # demote over-budget cold tail AFTER the overlay applies (commit()
        # runs right after this hook) — deferring via a flag keeps ordering
        # simple because demotion only touches committed, non-overlay keys
        self._demote_pending = self._hot_bytes > self.hot_budget_bytes

    _demote_pending = False

    def _maybe_demote(self) -> None:
        if not self._demote_pending:
            return
        self._demote_pending = False
        hot, data = self._hot, self._data
        while self._hot_bytes > self.hot_budget_bytes and len(hot) > 1:
            key, size = hot.popitem(last=False)
            self._hot_bytes -= size
            val = data.get(key)
            if (val is not None and type(val) is not _Packed
                    and type(val) is not memoryview):
                data[key] = _Packed(msgpack.packb(val))

    # -- read resolution ------------------------------------------------------

    def _committed_value(self, key: bytes) -> Any:
        val = self._data.get(key)
        t = type(val)
        if t is not _Packed and t is not memoryview:
            return val
        obj = _resolve_value(val)
        # promote: the processing hot set should stay decoded
        size = _cold_size(val)
        self._data[key] = obj
        self._hot[key] = size
        self._hot_bytes += size
        if self._hot_bytes > self.hot_budget_bytes:
            self._demote_pending = True
        return obj

    def committed_get(self, code, key_parts) -> Any:
        """Cross-thread committed read: resolves cold values WITHOUT
        promoting (no LRU/object mutation from the query thread)."""
        from zeebe_tpu.state.db import encode_key

        self._ensure_recovered()

        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        return _resolve_value(self._data.get(encode_key(code, key_parts)))

    # -- checkpoint / recover -------------------------------------------------

    def checkpoint(self) -> dict:
        """Durable O(delta) checkpoint: fsync the WAL tail, publish the
        manifest. Returns the manifest dict (base, wal chain, tail offset)."""
        if self.in_transaction:
            raise RuntimeError("cannot checkpoint with an open transaction")
        self._ensure_recovered()
        self._maybe_demote()
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._wal_tails[self._wal_files[-1]] = self._wal.tell()
        manifest = self._manifest_doc()
        self._write_manifest(manifest)
        if self._wal_bytes > max(self._base_bytes * self.compact_factor,
                                 self.min_compact_bytes):
            manifest = self._compact()
        return manifest

    def _manifest_doc(self) -> dict:
        return {
            "base": self._base_file,
            "wals": list(self._wal_files),
            "tails": [self._wal_tails.get(name, 0) for name in self._wal_files],
        }

    def _write_manifest(self, manifest: dict) -> None:
        body = msgpack.packb(manifest)
        tmp = self.directory / (_MANIFEST + ".tmp")
        with open(tmp, "wb") as f:
            f.write(struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF) + body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.directory / _MANIFEST)
        dir_fd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def _compact(self) -> dict:
        """Rewrite the full state as a new base segment and reset the WAL
        chain. Cold values are spliced as already-packed bytes — no decode.
        The new manifest publishes BEFORE stale files unlink, so a crash at
        any point leaves a recoverable chain."""
        seq = self._wal_seq + 1
        name = f"base-{seq:08d}.seg"
        tmp = self.directory / (name + ".tmp")
        data = self._data
        total = 0
        with open(tmp, "wb") as f:
            for key in self._sorted_keys:
                val = data[key]
                kcrc = zlib.crc32(key) & 0xFFFFFFFF
                if type(val) is memoryview:
                    # cold slice already carries [vcrc|value] — splice whole
                    f.write(_ENTRY.pack(len(key), len(val) - 4, kcrc))
                    f.write(key)
                    f.write(val)
                    total += _ENTRY.size + len(key) + len(val)
                else:
                    packed = _pack_value(val)
                    f.write(_ENTRY.pack(len(key), len(packed), kcrc))
                    f.write(key)
                    f.write(_VCRC.pack(zlib.crc32(packed) & 0xFFFFFFFF))
                    f.write(packed)
                    total += _ENTRY.size + len(key) + 4 + len(packed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.directory / name)
        old_wals, old_base = self._wal_files, self._base_file
        self._wal.close()
        self._base_file = name
        self._base_bytes = total
        self._wal_files = []
        self._wal_tails = {}
        self._wal_bytes = 0
        self._wal_seq = seq
        self._open_wal()
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._wal_tails[self._wal_files[-1]] = self._wal.tell()
        manifest = self._manifest_doc()
        self._write_manifest(manifest)
        for stale in old_wals:
            try:
                os.unlink(self.directory / stale)
            except OSError:
                pass
        if old_base:
            try:
                os.unlink(self.directory / old_base)
            except OSError:
                pass
        return manifest

    @classmethod
    def open(cls, directory: str | Path, consistency_checks: bool = False,
             **kw) -> "DurableZbDb":
        """Recover to the latest checkpoint. The base segment is mmapped and
        only its KEY index materializes; values stay on disk as cold slices
        resolved lazily — recovery cost ≈ key scan, not state size."""
        directory = Path(directory)
        manifest_path = directory / _MANIFEST
        manifest = None
        if manifest_path.exists():
            raw = manifest_path.read_bytes()
            (crc,) = struct.unpack_from("<I", raw)
            body = raw[4:]
            if zlib.crc32(body) & 0xFFFFFFFF != crc:
                raise ValueError("corrupt durable-state manifest")
            manifest = msgpack.unpackb(body)
        db = cls.__new__(cls)
        ZbDb.__init__(db, consistency_checks)
        db._init_runtime(
            directory,
            kw.get("hot_budget_bytes", cls.DEFAULT_HOT_BUDGET_BYTES),
            kw.get("compact_factor", cls.DEFAULT_COMPACT_FACTOR),
            kw.get("min_compact_bytes", cls.DEFAULT_MIN_COMPACT_BYTES),
        )
        if manifest is not None:
            base = manifest.get("base")
            if base:
                db._base_file = base
                db._base_bytes = (directory / base).stat().st_size
            wals = manifest.get("wals") or []
            tails = manifest.get("tails") or [None] * len(wals)
            for wal, tail in zip(wals, tails):
                db._wal_bytes += (tail if tail
                                  else (directory / wal).stat().st_size)
                db._wal_tails[wal] = tail or 0
            db._wal_files = list(wals)
            db._wal_seq = _max_seq(wals, db._base_file)
            # LAZY recovery: open() publishes only the manifest view — the
            # base index + WAL replay run on FIRST state access
            # (_ensure_recovered). This is what RocksDB's recover-from-
            # checkpoint costs too: opening hard links + manifest, with the
            # data itself faulted in later through the block cache.
            db._lazy_recovery = (
                directory / base if base else None,
                [(directory / wal, tail) for wal, tail in zip(wals, tails)],
            )
        db._open_wal()
        return db

    #: staged (base_path, [(wal_path, tail), …]) recovery work, or None
    _lazy_recovery = None

    def _before_transaction(self) -> None:
        self._ensure_recovered()

    def _ensure_recovered(self) -> None:
        if self._lazy_recovery is None:
            return
        with self._recovery_lock:
            if self._lazy_recovery is None:
                return  # lost the race; the winner indexed already
            base_path, wal_specs = self._lazy_recovery
            data = self._data
            base_keys = self._index_base(base_path) if base_path else []
            touched: set[bytes] = set()
            for wal_path, tail in wal_specs:
                for entries in _read_wal(wal_path, tail):
                    for key, deleted, packed in entries:
                        touched.add(key)
                        if deleted:
                            data.pop(key, None)
                        else:
                            data[key] = _Packed(packed)
            # key order: the base arrives sorted (SortedList construction
            # from sorted input is a cheap O(n) pass); patch the (typically
            # tiny) WAL key-set delta in with O(sqrt n) adds/discards
            keys = SortedList(base_keys)
            base_set = set(base_keys) if touched else None
            for key in touched:
                in_data = key in data
                if in_data and key not in base_set:
                    keys.add(key)
                elif not in_data and key in base_set:
                    keys.discard(key)
            self._sorted_keys = keys
            # publish only after the view is complete (committed_get races)
            self._lazy_recovery = None

    def _index_base(self, path: Path) -> list[bytes]:
        """Scan a base segment's entry headers, verifying KEY crcs eagerly
        (cheap: keys are a sliver of the file) and installing mmap-backed
        cold slices for the values (their crc verifies at resolution). A
        torn/corrupt entry truncates the scan, like the journal. Returns the
        keys in file order (== sorted order: compaction writes sorted)."""
        size = path.stat().st_size
        if size == 0:
            return []
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        self._maps.append(mm)
        view = memoryview(mm)
        data = self._data
        if _index_base_segment is not None:
            # one native pass (codec.c index_base_segment): keys + raw cold
            # slices, zero per-entry Python construction — this is what makes
            # recovery O(key index), the gate for the ≥10 snapshot+recover
            # ops/s large-state floor
            return _index_base_segment(view, data)
        keys: list[bytes] = []
        off, n = 0, size
        while off + _ENTRY.size <= n:
            klen, vlen, kcrc = _ENTRY.unpack_from(mm, off)
            kstart = off + _ENTRY.size
            vend = kstart + klen + 4 + vlen
            if vend > n:
                return keys
            key = bytes(view[kstart:kstart + klen])
            if zlib.crc32(key) & 0xFFFFFFFF != kcrc:
                return keys
            data[key] = view[kstart + klen:vend]
            keys.append(key)
            off = vend
        return keys

    def approx_bytes(self) -> int:
        """Serialized size of the committed state (cold exact, hot by the
        last packed size; hot keys never packed yet are estimated on use)."""
        self._ensure_recovered()
        total = 0
        for key, val in self._data.items():
            t = type(val)
            if t is _Packed or t is memoryview:
                total += _cold_size(val)
            else:
                total += self._hot.get(key) or len(msgpack.packb(val))
        return total

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        # drop cold views so the maps can release; a map with a live
        # exported view elsewhere just stays for the GC
        self._data = {}
        self._sorted_keys = []
        for mm in self._maps:
            try:
                mm.close()
            except BufferError:
                pass
        self._maps = []

    # -- full-serialization compatibility -------------------------------------

    SNAPSHOT_MAGIC = ZbDb.SNAPSHOT_MAGIC

    def to_snapshot_bytes(self) -> bytes:
        """Full serialization (raft snapshot install ships this to lagging
        followers). Cold values decode once here — this path is rare and
        inherently O(total)."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        self._ensure_recovered()
        body = msgpack.packb([
            [k, self._resolve(self._data[k])] for k in self._sorted_keys
        ])
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.SNAPSHOT_MAGIC + struct.pack("<I", crc) + body

    _resolve = staticmethod(_resolve_value)

    def content_equals(self, other: ZbDb) -> bool:
        self._ensure_recovered()
        if isinstance(other, DurableZbDb):
            other._ensure_recovered()
        if set(self._data) != set(other._data):
            return False
        for key, val in self._data.items():
            if self._resolve(val) != self._resolve(other._data[key]):
                return False
        return True

    def install_snapshot_bytes(self, raw: bytes) -> None:
        """Replace the whole committed state from a full snapshot (raft
        INSTALL on a lagging follower), then compact so the disk structures
        reflect it."""
        self._ensure_recovered()  # settle staged work before wholesale replace
        restored = ZbDb.from_snapshot_bytes(raw)
        self._data = restored._data
        self._sorted_keys = SortedList(restored._sorted_keys)
        self._hot.clear()
        self._hot_bytes = 0
        self._compact()  # publishes the manifest for the new state


def _read_wal(path: Path, limit: int | None):
    """Yield commit-overlay entry lists from a WAL segment up to ``limit``
    bytes (the manifest's durable tail), tolerating a torn tail beyond it."""
    with open(path, "rb") as f:
        raw = f.read()
    if limit is not None:
        raw = raw[:limit]
    off, n = 0, len(raw)
    while off + _FRAME.size <= n:
        length, crc = _FRAME.unpack_from(raw, off)
        start = off + _FRAME.size
        end = start + length
        if end > n:
            return
        body = raw[start:end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return
        yield msgpack.unpackb(body)
        off = end


def _max_seq(wals: list[str], base: str | None) -> int:
    seq = 0
    for name in list(wals) + ([base] if base else []):
        stem = name.rsplit(".", 1)[0]
        try:
            seq = max(seq, int(stem.split("-", 1)[1]))
        except (IndexError, ValueError):
            pass
    return seq
