"""Column-family KV state store with transactions — the zb-db equivalent.

Reference: zb-db/src/main/java/io/camunda/zeebe/db/ZeebeDb.java,
impl/rocksdb/transaction/ZeebeTransaction.java:22, TransactionalColumnFamily,
DbLong/DbString/DbCompositeKey key types, ConsistencyChecksSettings.java:10.

Like the reference — a single store where *logical* column families share one
keyspace via an enum prefix — but host-memory-resident: the data set a
partition owns is bounded by snapshot size, the durability story is the log +
snapshots (state is always recomputable by replay), so an LSM on disk buys
nothing on the hot path. The store is an ordered map from encoded
``(cf, *key_parts)`` tuples to msgpack-able values, with:

- order-preserving key encoding (ints sign-flipped big-endian, strings
  NUL-terminated) so prefix iteration matches RocksDB iterator semantics;
- optimistic transactions: an overlay of pending puts/deletes applied on
  commit, discarded on rollback — the processing state machine wraps each
  command batch in one transaction (reference: ProcessingStateMachine:55-93);
- optional foreign-key consistency checks (reference: ForeignKeyChecker);
- whole-state serialization for the snapshot store (state/snapshot.py).
"""

from __future__ import annotations

import enum
import struct
import zlib
from bisect import bisect_left, insort
from typing import Any, Callable, Iterator

from zeebe_tpu.native import codec_fn as _codec_fn
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.utils import evict_oldest_half as _evict_oldest_half

_commit_overlay = _codec_fn("commit_overlay")
_iterate_snapshot = _codec_fn("iterate_snapshot")


class ZbDbInconsistentError(Exception):
    """A consistency check failed (reference: ZeebeDbInconsistentException)."""


class ColumnFamilyCode(enum.IntEnum):
    """Logical column families (reference: protocol/…/ZbColumnFamilies.java:20).

    Only families the engine currently uses are defined; codes are append-only
    and are the first byte of every encoded key.
    """

    DEFAULT = 0
    KEY = 1  # key generator state
    PROCESS_VERSION = 2
    PROCESS_CACHE = 3
    PROCESS_CACHE_BY_ID_AND_VERSION = 4
    PROCESS_CACHE_DIGEST_BY_ID = 5
    ELEMENT_INSTANCE_PARENT_CHILD = 6
    ELEMENT_INSTANCE_KEY = 7
    NUMBER_OF_TAKEN_SEQUENCE_FLOWS = 8
    JOBS = 10
    JOB_STATES = 11
    JOB_DEADLINES = 12
    JOB_ACTIVATABLE = 13
    JOB_BACKOFF = 14
    MESSAGE_KEY = 20
    MESSAGES = 21
    MESSAGE_DEADLINES = 22
    MESSAGE_IDS = 23
    MESSAGE_CORRELATED = 24
    MESSAGE_PROCESSES = 25
    MESSAGE_SUBSCRIPTION_BY_KEY = 30
    MESSAGE_SUBSCRIPTION_BY_SENT_TIME = 31
    MESSAGE_SUBSCRIPTION_BY_NAME_AND_CORRELATION_KEY = 32
    PROCESS_SUBSCRIPTION_BY_KEY = 33
    MESSAGE_START_EVENT_SUBSCRIPTION_BY_NAME_AND_KEY = 34
    MESSAGE_START_EVENT_SUBSCRIPTION_BY_KEY_AND_NAME = 35
    TIMERS = 40
    TIMER_DUE_DATES = 41
    TIMER_BY_ELEMENT = 42
    PENDING_DEPLOYMENT = 50
    DEPLOYMENT_RAW = 51
    EVENT_SCOPE = 60
    EVENT_TRIGGER = 61
    VARIABLES = 70
    TEMPORARY_VARIABLE_STORE = 71
    INCIDENTS = 80
    INCIDENT_PROCESS_INSTANCES = 81
    INCIDENT_JOBS = 82
    BANNED_INSTANCE = 90
    EXPORTER = 100
    LAST_PROCESSED_POSITION = 101
    MIGRATIONS_STATE = 102
    PROCESS_INSTANCE_KEY_BY_DEFINITION_KEY = 103
    SIGNAL_SUBSCRIPTION_BY_NAME_AND_KEY = 110
    SIGNAL_SUBSCRIPTION_BY_KEY_AND_NAME = 111
    DISTRIBUTION = 120
    PENDING_DISTRIBUTION = 121
    COMMAND_DISTRIBUTION_RECORD = 122
    RECEIVED_DISTRIBUTION_BY_TIME = 123
    MULTI_INSTANCE_OUTPUT = 130
    AWAIT_RESULT_METADATA = 131
    CHECKPOINT = 140
    FORMS = 150
    FORM_BY_ID_AND_VERSION = 151
    FORM_VERSION = 152
    FORM_DIGEST = 153
    DMN_DECISIONS = 160
    DMN_DECISION_REQUIREMENTS = 161
    DMN_LATEST_DECISION_BY_ID = 162
    DMN_LATEST_DRG_BY_ID = 163
    DMN_DECISIONS_BY_DRG = 164
    USER_TASKS = 170
    USER_TASK_STATES = 171
    COMPENSATION_SUBSCRIPTION = 180
    PROCESS_INSTANCE_RESULT = 190
    # replicated request dedupe (ISSUE 9): (gateway stream id, request id) →
    # {command position, stored reply frame}; the BY_POSITION index ages
    # entries out by log position. Materialized on processing AND replay, so
    # followers and restarted leaders inherit acked-command identity.
    REQUEST_DEDUPE = 200
    REQUEST_DEDUPE_BY_POSITION = 201


_I64 = struct.Struct(">Q")
_INT_PART = struct.Struct(">BQ")  # tag 0x01 + sign-flipped u64, fused


def _encode_part(part: Any, out: bytearray) -> None:
    """Order-preserving encoding per key part, type-tagged so mixed-type parts
    cannot collide: ints sort before strings sort before bytes."""
    if isinstance(part, bool):
        raise TypeError("bool key parts are ambiguous; use int 0/1")
    if isinstance(part, int):
        # flip sign bit: two's-complement int64 → lexicographically ordered u64
        out += _INT_PART.pack(0x01, (part & 0xFFFFFFFFFFFFFFFF) ^ 0x8000000000000000)
    elif isinstance(part, str):
        raw = part.encode("utf-8")
        if b"\x00" in raw:
            raise ValueError("NUL byte in string key part")
        out.append(0x02)
        out += raw
        out.append(0x00)
    elif isinstance(part, bytes):
        out.append(0x03)
        out += _I64.pack(len(part))
        out += part
    else:
        raise TypeError(f"unsupported key part type {type(part).__name__}")


# per-CF 2-byte prefixes, precomputed (encode_key runs several times per
# command on the admission/processing hot path)
_CF_PREFIX = {code: struct.pack(">H", int(code)) for code in ColumnFamilyCode}


_encode_key_native = _codec_fn("encode_key")


_INT2_PART = struct.Struct(">BQBQ")  # two fused int parts (tag+payload ×2)
_SIGN_FLIP = 0x8000000000000000
_U64_MASK = 0xFFFFFFFFFFFFFFFF


def _encode_key_py(cf: ColumnFamilyCode, parts: tuple) -> bytes:
    """Pure-Python encoding — THE SPEC the native pass must byte-match
    (tests/test_native_codec.py TestNativeEncodeKey fuzzes equality)."""
    prefix = _CF_PREFIX[cf]
    n = len(parts)
    # preallocated struct-packed fast paths for the dominant shapes:
    # (int,), (int, int), and (int, str)
    if n == 1:
        p0 = parts[0]
        if type(p0) is int:
            return prefix + _INT_PART.pack(
                0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP)
    elif n == 2:
        p0, p1 = parts
        if type(p0) is int:
            if type(p1) is int:
                return prefix + _INT2_PART.pack(
                    0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP,
                    0x01, (p1 & _U64_MASK) ^ _SIGN_FLIP)
            if type(p1) is str:
                raw = p1.encode("utf-8")
                if b"\x00" not in raw:
                    return b"".join((
                        prefix,
                        _INT_PART.pack(0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP),
                        b"\x02", raw, b"\x00"))
    out = bytearray(prefix)
    for part in parts:
        _encode_part(part, out)
    return bytes(out)


_raw_encode_key = (
    (lambda cf, parts: _encode_key_native(_CF_PREFIX[cf], parts))
    if _encode_key_native is not None
    else _encode_key_py
)

# encoded-key LRU keyed by (cf, parts): the admission/processing hot path
# re-derives the same handful of keys several times per command (element
# instance by key, job by key, variables by (scope, name), …). Measured: a
# dict hit beats the pure-Python encoder ~2-8x (most for multi-part/str
# keys) but LOSES to the native codec's direct call — so the cache fronts
# only the Python fallback; with the native codec loaded, encode_key stays
# the direct native call. Only int/str/bytes parts are cacheable: Python
# equality would otherwise alias 1.0/True onto an int entry and silently
# bypass the codec's type rejection (int, str, and bytes never compare
# equal across types, so the tuple key is collision-free within that set).
_KEY_CACHE_LIMIT = 16384
_key_cache: dict[tuple, bytes] = {}


def _encode_key_cached(cf: ColumnFamilyCode, parts: tuple) -> bytes:
    for p in parts:
        t = type(p)
        if t is not int and t is not str and t is not bytes:
            return _raw_encode_key(cf, parts)
    key = (int(cf), parts)
    cached = _key_cache.get(key)
    if cached is not None:
        return cached
    encoded = _raw_encode_key(cf, parts)
    _evict_oldest_half(_key_cache, _KEY_CACHE_LIMIT)
    _key_cache[key] = encoded
    return encoded


encode_key = (
    _raw_encode_key if _encode_key_native is not None else _encode_key_cached
)


def decode_key(encoded: bytes) -> tuple[ColumnFamilyCode, tuple]:
    """Inverse of encode_key: used by state migrations to inspect and rewrite
    keys whose shape changed between versions (reference: DbMigratorImpl
    migration tasks iterate raw column families)."""
    cf = ColumnFamilyCode(struct.unpack_from(">H", encoded)[0])
    parts: list = []
    i = 2
    n = len(encoded)
    while i < n:
        tag = encoded[i]
        i += 1
        if tag == 0x01:
            raw = _I64.unpack_from(encoded, i)[0] ^ 0x8000000000000000
            parts.append(raw - (1 << 64) if raw >= (1 << 63) else raw)
            i += 8
        elif tag == 0x02:
            j = encoded.index(0, i)
            parts.append(encoded[i:j].decode("utf-8"))
            i = j + 1
        elif tag == 0x03:
            length = _I64.unpack_from(encoded, i)[0]
            i += 8
            parts.append(encoded[i:i + length])
            i += length
        else:
            raise ValueError(f"unknown key part tag 0x{tag:02x}")
    return cf, tuple(parts)


_DELETED = object()
_MISSING_READ = object()


def _prefix_successor(prefix: bytes) -> bytes | None:
    """The smallest byte string greater than every string starting with
    ``prefix`` (exact range upper bound for sorted-key bisects), or None when
    no such bound exists (prefix is empty or all 0xff)."""
    p = bytearray(prefix)
    while p and p[-1] == 0xFF:
        p.pop()
    if not p:
        return None
    p[-1] += 1
    return bytes(p)


class Transaction:
    """Pending puts/deletes overlaying the committed store.

    ``_sorted_writes`` mirrors ``_writes``'s keys in sorted order (insort on
    first write of a key) so prefix iteration is a bisect range over the
    overlay instead of a full overlay scan — batch processing applies tens of
    thousands of events in one transaction, and an O(pending-writes) cost per
    ``iterate`` call turns the group quadratic."""

    __slots__ = ("_db", "_writes", "_sorted_writes", "_reads", "closed", "capture")

    def __init__(self, db: "ZbDb") -> None:
        self._db = db
        self._writes: dict[bytes, Any] = {}
        self._sorted_writes: list[bytes] = []
        # per-transaction read cache of defensively-copied committed values
        # (one copy per key per transaction; see get)
        self._reads: dict[bytes, Any] = {}
        self.closed = False
        # optional write-capture log: when a list, every put/delete is also
        # appended as ("put", key, value) / ("del", key, None) — the burst
        # template builder uses this to learn a command's state write-set
        self.capture: list | None = None

    def _committed_read(self, key: bytes) -> Any:
        """Committed value via the per-transaction copy cache: state code
        mutates fetched documents in place before put(); handing out the
        committed object would leak those mutations into the committed store
        on ROLLBACK (breaking transaction atomicity) and expose mid-mutation
        values to the lock-free committed readers (ZbDb.committed_get).
        Shallow copy: mutators only touch top-level fields (deep structures
        are replaced, not edited). get() and iterate() share the cache so a
        value mutated after a get() is seen identically by a later scan."""
        val = self._reads.get(key, _MISSING_READ)
        if val is not _MISSING_READ:
            return val
        val = self._db._committed_value(key)
        # only containers are copied (and cached): scalars are immutable, and
        # index scans over None/int values must stay allocation-free
        t = type(val)
        if t is dict:
            val = dict(val)
            self._reads[key] = val
        elif t is list:
            val = list(val)
            self._reads[key] = val
        return val

    def get(self, key: bytes) -> Any:
        if key in self._writes:
            val = self._writes[key]
            return None if val is _DELETED else val
        return self._committed_read(key)

    def put(self, key: bytes, value: Any) -> None:
        if key not in self._writes:
            insort(self._sorted_writes, key)
        self._writes[key] = value
        if self.capture is not None:
            self.capture.append(("put", key, value))

    def delete(self, key: bytes) -> None:
        if key not in self._writes:
            insort(self._sorted_writes, key)
        self._writes[key] = _DELETED
        if self.capture is not None:
            self.capture.append(("del", key, None))

    def exists(self, key: bytes) -> bool:
        if key in self._writes:
            return self._writes[key] is not _DELETED
        return key in self._db._data

    def iterate(self, prefix: bytes) -> Iterator[tuple[bytes, Any]]:
        """Ordered iteration over committed ∪ pending entries under prefix.

        Snapshot semantics (RocksDB-iterator-like): the view is materialized at
        call time, so scan-and-update loops (job deadlines, timer due dates)
        see a stable snapshot and never skip or double-see entries mutated
        mid-iteration.
        """
        db = self._db
        if db._native_iterate is not None:
            # one native merge pass (codec.c iterate_snapshot) — identical
            # semantics to the Python path below, including the defensive
            # copy-and-cache of committed container values
            return iter(db._native_iterate(
                db._sorted_keys, db._data, prefix, self._sorted_writes,
                self._writes, _DELETED, self._reads))
        return self.iterate_range(prefix, _prefix_successor(prefix))

    def iterate_range(self, lo: bytes, hi: bytes | None) -> Iterator[tuple[bytes, Any]]:
        """Ordered iteration over committed ∪ pending entries in ``[lo, hi)``.

        The due-date sweep primitive: the work (and the materialized
        snapshot) is O(entries in range), never O(entries under the column
        family) — a million parked deadlines cost a sweep nothing when none
        are due. Same snapshot semantics as ``iterate``."""
        db = self._db
        snapshot: list[tuple[bytes, Any]] = []
        writes = self._writes
        sw = self._sorted_writes
        wlo = bisect_left(sw, lo)
        whi = bisect_left(sw, hi) if hi is not None else len(sw)
        overlay_keys = sw[wlo:whi]
        if not overlay_keys:
            for key in db._keys_in_range(lo, hi):
                snapshot.append((key, self._committed_read(key)))
            return iter(snapshot)
        overlay = set(overlay_keys)
        for key in db._keys_in_range(lo, hi):
            if key in overlay:
                continue  # superseded by pending write/delete
            snapshot.append((key, self._committed_read(key)))
        for key in overlay_keys:
            val = writes[key]
            if val is not _DELETED:
                snapshot.append((key, val))
        snapshot.sort(key=lambda kv: kv[0])
        return iter(snapshot)

    def first_in_range(self, lo: bytes, hi: bytes | None) -> tuple[bytes, Any] | None:
        """Smallest committed-∪-pending entry in ``[lo, hi)``, or None.

        O(log n): one bisect each side plus a skip loop over pending deletes
        — the next-due-date probe that used to materialize a whole index."""
        db = self._db
        writes = self._writes
        sw = self._sorted_writes
        wi = bisect_left(sw, lo)
        cursor = lo
        while True:
            ck = db._first_key_at_or_after(cursor, hi)
            wk = None
            while wi < len(sw):
                k = sw[wi]
                if hi is not None and k >= hi:
                    break
                if k >= cursor:
                    wk = k
                    break
                wi += 1
            if wk is not None and (ck is None or wk <= ck):
                val = writes[wk]
                if val is _DELETED:
                    # deleted overlay entry shadows any committed twin; skip
                    # past it in both streams
                    wi += 1
                    cursor = wk + b"\x00"
                    continue
                return (wk, val)
            if ck is None:
                return None
            return (ck, self._committed_read(ck))

    def commit(self) -> None:
        db = self._db
        dirty = db._dirty_keys
        if dirty is not None and self._writes:
            # incremental-snapshot delta tracking: every committed overlay
            # key (put OR delete) joins the changed-keys-since-last-snapshot
            # set, regardless of which commit pass (native/python/durable)
            # applies it below
            dirty.update(self._writes)
        db._pre_commit(self._writes)
        if db._native_commit is not None:
            # one native pass (codec.c commit_overlay) applying the overlay
            # to the committed dict + sorted-keys list — identical semantics
            # to the per-key loop below
            db._native_commit(self._writes, db._data, db._sorted_keys, _DELETED)
        else:
            for key, val in self._writes.items():
                if val is _DELETED:
                    db._delete_committed(key)
                else:
                    db._put_committed(key, val)
        self._writes.clear()
        self.closed = True

    def rollback(self) -> None:
        self._writes.clear()
        self.closed = True


class ColumnFamily:
    """Typed facade over one logical column family within a transaction context.

    Keys are tuples of (int | str | bytes); values any msgpack-able object.
    Mirrors the reference's TransactionalColumnFamily get/put/iterate surface.
    """

    __slots__ = ("_db", "code", "_prefix")

    def __init__(self, db: "ZbDb", code: ColumnFamilyCode) -> None:
        self._db = db
        self.code = code
        self._prefix = struct.pack(">H", int(code))

    def _ctx(self) -> Transaction:
        return self._db.require_transaction()

    def _key(self, key_parts: tuple) -> bytes:
        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        return encode_key(self.code, key_parts)

    def get(self, key_parts: tuple) -> Any:
        return self._ctx().get(self._key(key_parts))

    def exists(self, key_parts: tuple) -> bool:
        return self._ctx().exists(self._key(key_parts))

    def put(self, key_parts: tuple, value: Any) -> None:
        self._db._check_foreign_keys(self.code, value)
        self._ctx().put(self._key(key_parts), value)

    def insert(self, key_parts: tuple, value: Any) -> None:
        """Put that requires the key to be absent (consistency precondition)."""
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and ctx.exists(key):
            raise ZbDbInconsistentError(f"insert: key already exists in {self.code.name}: {key_parts}")
        self._db._check_foreign_keys(self.code, value)
        ctx.put(key, value)

    def update(self, key_parts: tuple, value: Any) -> None:
        """Put that requires the key to exist (consistency precondition)."""
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and not ctx.exists(key):
            raise ZbDbInconsistentError(f"update: key missing in {self.code.name}: {key_parts}")
        self._db._check_foreign_keys(self.code, value)
        ctx.put(key, value)

    def delete(self, key_parts: tuple) -> None:
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and not ctx.exists(key):
            raise ZbDbInconsistentError(f"delete: key missing in {self.code.name}: {key_parts}")
        ctx.delete(key)

    def items(self, prefix: tuple = ()) -> Iterator[tuple[bytes, Any]]:
        """Iterate (encoded_key, value) pairs under a key-part prefix, ordered."""
        # a key-part prefix encodes exactly like a key of those parts, so the
        # scan prefix rides the same fast path (native/cached) as point keys
        pfx = encode_key(self.code, prefix) if prefix else self._prefix
        yield from self._ctx().iterate(pfx)

    def items_below(self, hi_parts: tuple,
                    prefix: tuple = ()) -> Iterator[tuple[bytes, Any]]:
        """Ordered (encoded_key, value) pairs under ``prefix`` whose key
        parts sort strictly below ``hi_parts`` — the O(in-range) primitive
        for due-date sweeps: ``items_below((now + 1,))`` over a
        ``(deadline, key)`` index touches exactly the due entries, never the
        parked backlog behind them."""
        lo = encode_key(self.code, prefix) if prefix else self._prefix
        hi = encode_key(self.code, hi_parts)
        yield from self._ctx().iterate_range(lo, hi)

    def first_item(self, prefix: tuple = ()) -> tuple[bytes, Any] | None:
        """Smallest (encoded_key, value) under ``prefix`` or None — O(log n),
        where ``next(items())`` materializes the whole prefix first."""
        lo = encode_key(self.code, prefix) if prefix else self._prefix
        return self._ctx().first_in_range(lo, _prefix_successor(lo))

    def values(self, prefix: tuple = ()) -> Iterator[Any]:
        for _, v in self.items(prefix):
            yield v

    def is_empty(self, prefix: tuple = ()) -> bool:
        return self.first_item(prefix) is None

    def first_value(self, prefix: tuple = ()) -> Any:
        item = self.first_item(prefix)
        return None if item is None else item[1]


class ZbDb:
    """The partition state store. One instance per partition.

    ``transaction()`` is a context manager committing on success, rolling back
    on exception — the unit of processing atomicity.
    """

    def __init__(self, consistency_checks: bool = False) -> None:
        self._data: dict[bytes, Any] = {}
        self._sorted_keys: list[bytes] = []
        self._txn: Transaction | None = None
        self.consistency_checks = consistency_checks
        self._foreign_key_checkers: dict[ColumnFamilyCode, Callable[["ZbDb", Any], None]] = {}
        # subclass hooks: the durable backend (state/durable.py) swaps the
        # native iterate/commit out (its cold values need per-read
        # resolution and its key index is a blocked sorted structure, not
        # the flat list the C pass mutates) and journals commit overlays
        # through _pre_commit
        self._native_iterate = _iterate_snapshot
        self._native_commit = _commit_overlay
        # changed-keys-since-last-snapshot set for incremental snapshots
        # (state/snapshot.py delta chains); None = tracking off — one is-None
        # check per commit
        self._dirty_keys: set[bytes] | None = None
        # physical observation seams (ISSUE 8) — NOT state, never replayed:
        # due_listener feeds deadline inserts to the hierarchical timer wheel
        # (engine/timer_wheel.py); park_listener feeds instances entering a
        # wait state to the tiering manager (state/tiering.py). Both fire on
        # processing AND replay (appliers run on both), both tolerate loss
        # (wheel rebuilds at transition; an unspilled instance just stays
        # hot), and both cost one is-None check when unwired.
        self.due_listener: Callable[[int], None] | None = None
        self.park_listener: Callable[[int], None] | None = None

    def note_due(self, due_ms: int) -> None:
        """State facades call this on every deadline-index insert (timer due
        dates, message TTLs, job deadlines/backoff)."""
        listener = self.due_listener
        if listener is not None:
            listener(due_ms)

    def note_parked(self, process_instance_key: int) -> None:
        """State facades call this when an instance enters a wait state
        (timer created, message subscription opened, job created)."""
        listener = self.park_listener
        if listener is not None:
            listener(process_instance_key)

    def key_counts_by_cf(self) -> dict[str, int]:
        """Committed key count per (non-empty) column family — one boundary
        bisect per CF over the sorted index, O(cfs × log n): cheap enough
        for the metrics cadence (``zeebe_state_keys{cf=…}``)."""
        out: dict[str, int] = {}
        for code, prefix in _CF_PREFIX.items():
            end = _prefix_successor(prefix)
            count = self._count_key_range(prefix, end)
            if count:
                out[code.name] = count
        return out

    def _count_key_range(self, lo: bytes, hi: bytes | None) -> int:
        i = bisect_left(self._sorted_keys, lo)
        j = (bisect_left(self._sorted_keys, hi) if hi is not None
             else len(self._sorted_keys))
        return j - i

    def committed_keys_of(self, code: ColumnFamilyCode,
                          prefix_parts: tuple = ()) -> list[bytes]:
        """Encoded COMMITTED keys under a column family (optionally a
        key-part prefix) without opening a transaction or materializing
        values — the timer-wheel rebuild and tiering scans read key indexes
        only. The returned list holds references into the sorted index, so a
        million keys cost one slice, not a million tuples."""
        pfx = (encode_key(code, prefix_parts) if prefix_parts
               else _CF_PREFIX[code])
        return self._keys_with_prefix(pfx)

    # -- committed-store internals ------------------------------------------

    def _committed_value(self, key: bytes) -> Any:
        """Committed read hook — overridden by backends whose stored
        representation needs resolving (durable cold values)."""
        return self._data.get(key)

    def _pre_commit(self, writes: dict[bytes, Any]) -> None:
        """Called with the overlay just before it applies — the durable
        backend appends it to the write-ahead delta log here."""

    def _put_committed(self, key: bytes, value: Any) -> None:
        if key not in self._data:
            insort(self._sorted_keys, key)
        self._data[key] = value

    def _delete_committed(self, key: bytes) -> None:
        if key in self._data:
            del self._data[key]
            i = bisect_left(self._sorted_keys, key)
            if i < len(self._sorted_keys) and self._sorted_keys[i] == key:
                self._sorted_keys.pop(i)

    def _keys_with_prefix(self, prefix: bytes) -> list[bytes]:
        return self._keys_in_range(prefix, _prefix_successor(prefix))

    def _keys_in_range(self, lo: bytes, hi: bytes | None) -> list[bytes]:
        i = bisect_left(self._sorted_keys, lo)
        j = bisect_left(self._sorted_keys, hi) if hi is not None else len(self._sorted_keys)
        return self._sorted_keys[i:j]

    def _first_key_at_or_after(self, lo: bytes, hi: bytes | None) -> bytes | None:
        i = bisect_left(self._sorted_keys, lo)
        if i >= len(self._sorted_keys):
            return None
        key = self._sorted_keys[i]
        if hi is not None and key >= hi:
            return None
        return key

    # -- transactions --------------------------------------------------------

    def transaction(self) -> "_TxnContext":
        self._before_transaction()
        return _TxnContext(self)

    def _before_transaction(self) -> None:
        """Hook before a transaction opens — the durable backend finishes
        lazy recovery (base-segment indexing) here."""

    def committed_get(self, code: ColumnFamilyCode, key_parts: tuple) -> Any:
        """Lock-free point read of the COMMITTED store, bypassing the single
        processing-owned transaction slot — the cross-thread read path for
        the QueryService (reference: StateQueryService reads a RocksDB
        snapshot concurrently with processing). An open processing
        transaction's uncommitted writes are invisible, exactly as with a
        storage snapshot; dict point reads are atomic under the GIL."""
        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        return self._data.get(encode_key(code, key_parts))

    def require_transaction(self) -> Transaction:
        if self._txn is None or self._txn.closed:
            raise RuntimeError("state access outside a transaction")
        return self._txn

    @property
    def in_transaction(self) -> bool:
        return self._txn is not None and not self._txn.closed

    # -- column families -----------------------------------------------------

    def column_family(self, code: ColumnFamilyCode) -> ColumnFamily:
        return ColumnFamily(self, code)

    def register_foreign_key_check(
        self, code: ColumnFamilyCode, check: Callable[["ZbDb", Any], None]
    ) -> None:
        self._foreign_key_checkers[code] = check

    def _check_foreign_keys(self, code: ColumnFamilyCode, value: Any) -> None:
        if self.consistency_checks:
            checker = self._foreign_key_checkers.get(code)
            if checker is not None:
                checker(self, value)

    # -- snapshot serialization ---------------------------------------------

    SNAPSHOT_MAGIC = b"ZSNP\x01"

    def to_snapshot_bytes(self) -> bytes:
        """Serialize the committed state (msgpack body + crc32 trailer)."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        body = msgpack.packb(
            [[k, v] for k, v in ((k, self._data[k]) for k in self._sorted_keys)]
        )
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.SNAPSHOT_MAGIC + struct.pack("<I", crc) + body

    @classmethod
    def from_snapshot_bytes(cls, raw: bytes, consistency_checks: bool = False) -> "ZbDb":
        db = cls(consistency_checks=consistency_checks)
        db.load_snapshot_bytes(raw)
        return db

    def load_snapshot_bytes(self, raw: bytes) -> int:
        """Install a full snapshot into THIS (possibly subclassed) store in
        one bulk pass — the instance-method twin of ``from_snapshot_bytes``
        for backends whose constructors need more than consistency flags
        (the tiered store). Returns the entry count."""
        if raw[:5] != self.SNAPSHOT_MAGIC:
            raise ValueError("bad state snapshot magic")
        (crc,) = struct.unpack_from("<I", raw, 5)
        body = raw[9:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("state snapshot checksum mismatch")
        entries = msgpack.unpackb(body)
        data = self._data
        if not data:
            # snapshot bodies serialize in sorted-key order: installing into
            # an empty store is a straight O(n) append, no sort needed
            keys = []
            for k, v in entries:
                data[k] = v
                keys.append(k)
            self._install_sorted_keys(keys)
        else:
            for k, v in entries:
                data[k] = v
            self._rebuild_sorted_keys()
        return len(entries)

    # -- bulk load (snapshot/chain install fast path) -------------------------

    def bulk_apply(self, puts: dict[bytes, Any],
                   deletes: "tuple | list | set" = ()) -> None:
        """Apply many puts/deletes in one pass: dict update + ONE sorted-key
        rebuild — O(n log n) total where per-key ``insort`` is O(n) each
        (quadratic on a million-key restore). Semantically identical to the
        incremental path (tests/test_state.py asserts parity)."""
        data = self._data
        for key in deletes:
            data.pop(key, None)
        data.update(puts)
        self._rebuild_sorted_keys()

    def _rebuild_sorted_keys(self) -> None:
        """Rebuild the key index from ``_data`` (hook: the durable backend
        rebuilds its blocked SortedList here instead of a flat list)."""
        self._sorted_keys = sorted(self._data)

    def _install_sorted_keys(self, keys: list[bytes]) -> None:
        """Install an ALREADY-SORTED key list as the index (same hook)."""
        self._sorted_keys = keys

    def content_equals(self, other: "ZbDb") -> bool:
        """Deep state equality — the replay≡processing test oracle."""
        return self._data == other._data

    # -- incremental-snapshot delta serialization ----------------------------

    DELTA_MAGIC = b"ZDLT\x01"
    # subclasses whose _data holds non-msgpack-able representations (the
    # durable store's _Packed/memoryview cold values) must opt OUT: a delta
    # serialized from them would crash packb or decode as the wrong type
    supports_delta_snapshots = True

    def begin_delta_tracking(self) -> None:
        """Start (or restart) recording changed keys. Call after recovery so
        the first delta captures exactly the writes since the recovered
        snapshot chain's tip."""
        self._dirty_keys = set()

    @property
    def delta_tracking(self) -> bool:
        return self._dirty_keys is not None

    @property
    def dirty_key_count(self) -> int:
        return len(self._dirty_keys) if self._dirty_keys is not None else 0

    @property
    def key_count(self) -> int:
        return len(self._data)

    def to_delta_bytes(self) -> bytes:
        """Serialize the changed-keys-since-tracking-start as a delta
        (msgpack ``[[key, deleted, value], …]`` + crc32 trailer, same
        integrity scheme as the full snapshot). Does NOT clear the tracked
        set — the caller clears only after the delta is durably persisted,
        so an aborted snapshot never loses changes."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        if self._dirty_keys is None:
            raise RuntimeError("delta tracking is not active")
        data = self._data
        entries = []
        for key in sorted(self._dirty_keys):
            if key in data:
                entries.append([key, False, data[key]])
            else:
                entries.append([key, True, None])
        body = msgpack.packb(entries)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.DELTA_MAGIC + struct.pack("<I", crc) + body

    def clear_delta_tracking(self) -> None:
        """Reset the changed-key window (the just-persisted delta covers it)."""
        self._dirty_keys = set()

    def apply_delta_bytes(self, raw: bytes) -> int:
        """Apply one delta on top of the committed store (chain recovery:
        base snapshot, then each delta in order). Returns the entry count."""
        if raw[:5] != self.DELTA_MAGIC:
            raise ValueError("bad state delta magic")
        (crc,) = struct.unpack_from("<I", raw, 5)
        body = raw[9:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("state delta checksum mismatch")
        entries = msgpack.unpackb(body)
        # bulk fast path: insort per key is O(existing) each — a delta the
        # size of the store (chain recovery of a freshly-parked million
        # instances) turns quadratic. Sort-once rebuild wins when the delta
        # is large both absolutely and relative to the resident key set.
        if len(entries) >= 1024 and len(entries) * 8 >= len(self._data):
            puts: dict[bytes, Any] = {}
            deletes: list[bytes] = []
            for key, deleted, value in entries:
                if deleted:
                    puts.pop(key, None)
                    deletes.append(key)
                else:
                    puts[key] = value
            self.bulk_apply(puts, deletes)
        else:
            for key, deleted, value in entries:
                if deleted:
                    self._delete_committed(key)
                else:
                    self._put_committed(key, value)
        return len(entries)


class _TxnContext:
    __slots__ = ("_db", "_txn")

    def __init__(self, db: ZbDb) -> None:
        self._db = db

    def __enter__(self) -> Transaction:
        if self._db.in_transaction:
            raise RuntimeError("nested transactions are not supported")
        self._txn = Transaction(self._db)
        self._db._txn = self._txn
        return self._txn

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._txn.closed:
            if exc_type is None:
                self._txn.commit()
            else:
                self._txn.rollback()
        self._db._txn = None
