"""Column-family KV state store with transactions — the zb-db equivalent.

Reference: zb-db/src/main/java/io/camunda/zeebe/db/ZeebeDb.java,
impl/rocksdb/transaction/ZeebeTransaction.java:22, TransactionalColumnFamily,
DbLong/DbString/DbCompositeKey key types, ConsistencyChecksSettings.java:10.

Like the reference — a single store where *logical* column families share one
keyspace via an enum prefix — but host-memory-resident: the data set a
partition owns is bounded by snapshot size, the durability story is the log +
snapshots (state is always recomputable by replay), so an LSM on disk buys
nothing on the hot path. The store is an ordered map from encoded
``(cf, *key_parts)`` tuples to msgpack-able values, with:

- order-preserving key encoding (ints sign-flipped big-endian, strings
  NUL-terminated) so prefix iteration matches RocksDB iterator semantics;
- optimistic transactions: an overlay of pending puts/deletes applied on
  commit, discarded on rollback — the processing state machine wraps each
  command batch in one transaction (reference: ProcessingStateMachine:55-93);
- optional foreign-key consistency checks (reference: ForeignKeyChecker);
- whole-state serialization for the snapshot store (state/snapshot.py).
"""

from __future__ import annotations

import enum
import struct
import zlib
from bisect import bisect_left, insort
from typing import Any, Callable, Iterator

from zeebe_tpu.native import codec_fn as _codec_fn
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.utils import evict_oldest_half as _evict_oldest_half

_commit_overlay = _codec_fn("commit_overlay")
_iterate_snapshot = _codec_fn("iterate_snapshot")


class ZbDbInconsistentError(Exception):
    """A consistency check failed (reference: ZeebeDbInconsistentException)."""


class ColumnFamilyCode(enum.IntEnum):
    """Logical column families (reference: protocol/…/ZbColumnFamilies.java:20).

    Only families the engine currently uses are defined; codes are append-only
    and are the first byte of every encoded key.
    """

    DEFAULT = 0
    KEY = 1  # key generator state
    PROCESS_VERSION = 2
    PROCESS_CACHE = 3
    PROCESS_CACHE_BY_ID_AND_VERSION = 4
    PROCESS_CACHE_DIGEST_BY_ID = 5
    ELEMENT_INSTANCE_PARENT_CHILD = 6
    ELEMENT_INSTANCE_KEY = 7
    NUMBER_OF_TAKEN_SEQUENCE_FLOWS = 8
    JOBS = 10
    JOB_STATES = 11
    JOB_DEADLINES = 12
    JOB_ACTIVATABLE = 13
    JOB_BACKOFF = 14
    MESSAGE_KEY = 20
    MESSAGES = 21
    MESSAGE_DEADLINES = 22
    MESSAGE_IDS = 23
    MESSAGE_CORRELATED = 24
    MESSAGE_PROCESSES = 25
    MESSAGE_SUBSCRIPTION_BY_KEY = 30
    MESSAGE_SUBSCRIPTION_BY_SENT_TIME = 31
    MESSAGE_SUBSCRIPTION_BY_NAME_AND_CORRELATION_KEY = 32
    PROCESS_SUBSCRIPTION_BY_KEY = 33
    MESSAGE_START_EVENT_SUBSCRIPTION_BY_NAME_AND_KEY = 34
    MESSAGE_START_EVENT_SUBSCRIPTION_BY_KEY_AND_NAME = 35
    TIMERS = 40
    TIMER_DUE_DATES = 41
    TIMER_BY_ELEMENT = 42
    PENDING_DEPLOYMENT = 50
    DEPLOYMENT_RAW = 51
    EVENT_SCOPE = 60
    EVENT_TRIGGER = 61
    VARIABLES = 70
    TEMPORARY_VARIABLE_STORE = 71
    INCIDENTS = 80
    INCIDENT_PROCESS_INSTANCES = 81
    INCIDENT_JOBS = 82
    BANNED_INSTANCE = 90
    EXPORTER = 100
    LAST_PROCESSED_POSITION = 101
    MIGRATIONS_STATE = 102
    PROCESS_INSTANCE_KEY_BY_DEFINITION_KEY = 103
    SIGNAL_SUBSCRIPTION_BY_NAME_AND_KEY = 110
    SIGNAL_SUBSCRIPTION_BY_KEY_AND_NAME = 111
    DISTRIBUTION = 120
    PENDING_DISTRIBUTION = 121
    COMMAND_DISTRIBUTION_RECORD = 122
    RECEIVED_DISTRIBUTION_BY_TIME = 123
    MULTI_INSTANCE_OUTPUT = 130
    AWAIT_RESULT_METADATA = 131
    CHECKPOINT = 140
    FORMS = 150
    FORM_BY_ID_AND_VERSION = 151
    FORM_VERSION = 152
    FORM_DIGEST = 153
    DMN_DECISIONS = 160
    DMN_DECISION_REQUIREMENTS = 161
    DMN_LATEST_DECISION_BY_ID = 162
    DMN_LATEST_DRG_BY_ID = 163
    DMN_DECISIONS_BY_DRG = 164
    USER_TASKS = 170
    USER_TASK_STATES = 171
    COMPENSATION_SUBSCRIPTION = 180
    PROCESS_INSTANCE_RESULT = 190


_I64 = struct.Struct(">Q")
_INT_PART = struct.Struct(">BQ")  # tag 0x01 + sign-flipped u64, fused


def _encode_part(part: Any, out: bytearray) -> None:
    """Order-preserving encoding per key part, type-tagged so mixed-type parts
    cannot collide: ints sort before strings sort before bytes."""
    if isinstance(part, bool):
        raise TypeError("bool key parts are ambiguous; use int 0/1")
    if isinstance(part, int):
        # flip sign bit: two's-complement int64 → lexicographically ordered u64
        out += _INT_PART.pack(0x01, (part & 0xFFFFFFFFFFFFFFFF) ^ 0x8000000000000000)
    elif isinstance(part, str):
        raw = part.encode("utf-8")
        if b"\x00" in raw:
            raise ValueError("NUL byte in string key part")
        out.append(0x02)
        out += raw
        out.append(0x00)
    elif isinstance(part, bytes):
        out.append(0x03)
        out += _I64.pack(len(part))
        out += part
    else:
        raise TypeError(f"unsupported key part type {type(part).__name__}")


# per-CF 2-byte prefixes, precomputed (encode_key runs several times per
# command on the admission/processing hot path)
_CF_PREFIX = {code: struct.pack(">H", int(code)) for code in ColumnFamilyCode}


_encode_key_native = _codec_fn("encode_key")


_INT2_PART = struct.Struct(">BQBQ")  # two fused int parts (tag+payload ×2)
_SIGN_FLIP = 0x8000000000000000
_U64_MASK = 0xFFFFFFFFFFFFFFFF


def _encode_key_py(cf: ColumnFamilyCode, parts: tuple) -> bytes:
    """Pure-Python encoding — THE SPEC the native pass must byte-match
    (tests/test_native_codec.py TestNativeEncodeKey fuzzes equality)."""
    prefix = _CF_PREFIX[cf]
    n = len(parts)
    # preallocated struct-packed fast paths for the dominant shapes:
    # (int,), (int, int), and (int, str)
    if n == 1:
        p0 = parts[0]
        if type(p0) is int:
            return prefix + _INT_PART.pack(
                0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP)
    elif n == 2:
        p0, p1 = parts
        if type(p0) is int:
            if type(p1) is int:
                return prefix + _INT2_PART.pack(
                    0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP,
                    0x01, (p1 & _U64_MASK) ^ _SIGN_FLIP)
            if type(p1) is str:
                raw = p1.encode("utf-8")
                if b"\x00" not in raw:
                    return b"".join((
                        prefix,
                        _INT_PART.pack(0x01, (p0 & _U64_MASK) ^ _SIGN_FLIP),
                        b"\x02", raw, b"\x00"))
    out = bytearray(prefix)
    for part in parts:
        _encode_part(part, out)
    return bytes(out)


_raw_encode_key = (
    (lambda cf, parts: _encode_key_native(_CF_PREFIX[cf], parts))
    if _encode_key_native is not None
    else _encode_key_py
)

# encoded-key LRU keyed by (cf, parts): the admission/processing hot path
# re-derives the same handful of keys several times per command (element
# instance by key, job by key, variables by (scope, name), …). Measured: a
# dict hit beats the pure-Python encoder ~2-8x (most for multi-part/str
# keys) but LOSES to the native codec's direct call — so the cache fronts
# only the Python fallback; with the native codec loaded, encode_key stays
# the direct native call. Only int/str/bytes parts are cacheable: Python
# equality would otherwise alias 1.0/True onto an int entry and silently
# bypass the codec's type rejection (int, str, and bytes never compare
# equal across types, so the tuple key is collision-free within that set).
_KEY_CACHE_LIMIT = 16384
_key_cache: dict[tuple, bytes] = {}


def _encode_key_cached(cf: ColumnFamilyCode, parts: tuple) -> bytes:
    for p in parts:
        t = type(p)
        if t is not int and t is not str and t is not bytes:
            return _raw_encode_key(cf, parts)
    key = (int(cf), parts)
    cached = _key_cache.get(key)
    if cached is not None:
        return cached
    encoded = _raw_encode_key(cf, parts)
    _evict_oldest_half(_key_cache, _KEY_CACHE_LIMIT)
    _key_cache[key] = encoded
    return encoded


encode_key = (
    _raw_encode_key if _encode_key_native is not None else _encode_key_cached
)


def decode_key(encoded: bytes) -> tuple[ColumnFamilyCode, tuple]:
    """Inverse of encode_key: used by state migrations to inspect and rewrite
    keys whose shape changed between versions (reference: DbMigratorImpl
    migration tasks iterate raw column families)."""
    cf = ColumnFamilyCode(struct.unpack_from(">H", encoded)[0])
    parts: list = []
    i = 2
    n = len(encoded)
    while i < n:
        tag = encoded[i]
        i += 1
        if tag == 0x01:
            raw = _I64.unpack_from(encoded, i)[0] ^ 0x8000000000000000
            parts.append(raw - (1 << 64) if raw >= (1 << 63) else raw)
            i += 8
        elif tag == 0x02:
            j = encoded.index(0, i)
            parts.append(encoded[i:j].decode("utf-8"))
            i = j + 1
        elif tag == 0x03:
            length = _I64.unpack_from(encoded, i)[0]
            i += 8
            parts.append(encoded[i:i + length])
            i += length
        else:
            raise ValueError(f"unknown key part tag 0x{tag:02x}")
    return cf, tuple(parts)


_DELETED = object()
_MISSING_READ = object()


def _prefix_successor(prefix: bytes) -> bytes | None:
    """The smallest byte string greater than every string starting with
    ``prefix`` (exact range upper bound for sorted-key bisects), or None when
    no such bound exists (prefix is empty or all 0xff)."""
    p = bytearray(prefix)
    while p and p[-1] == 0xFF:
        p.pop()
    if not p:
        return None
    p[-1] += 1
    return bytes(p)


class Transaction:
    """Pending puts/deletes overlaying the committed store.

    ``_sorted_writes`` mirrors ``_writes``'s keys in sorted order (insort on
    first write of a key) so prefix iteration is a bisect range over the
    overlay instead of a full overlay scan — batch processing applies tens of
    thousands of events in one transaction, and an O(pending-writes) cost per
    ``iterate`` call turns the group quadratic."""

    __slots__ = ("_db", "_writes", "_sorted_writes", "_reads", "closed", "capture")

    def __init__(self, db: "ZbDb") -> None:
        self._db = db
        self._writes: dict[bytes, Any] = {}
        self._sorted_writes: list[bytes] = []
        # per-transaction read cache of defensively-copied committed values
        # (one copy per key per transaction; see get)
        self._reads: dict[bytes, Any] = {}
        self.closed = False
        # optional write-capture log: when a list, every put/delete is also
        # appended as ("put", key, value) / ("del", key, None) — the burst
        # template builder uses this to learn a command's state write-set
        self.capture: list | None = None

    def _committed_read(self, key: bytes) -> Any:
        """Committed value via the per-transaction copy cache: state code
        mutates fetched documents in place before put(); handing out the
        committed object would leak those mutations into the committed store
        on ROLLBACK (breaking transaction atomicity) and expose mid-mutation
        values to the lock-free committed readers (ZbDb.committed_get).
        Shallow copy: mutators only touch top-level fields (deep structures
        are replaced, not edited). get() and iterate() share the cache so a
        value mutated after a get() is seen identically by a later scan."""
        val = self._reads.get(key, _MISSING_READ)
        if val is not _MISSING_READ:
            return val
        val = self._db._committed_value(key)
        # only containers are copied (and cached): scalars are immutable, and
        # index scans over None/int values must stay allocation-free
        t = type(val)
        if t is dict:
            val = dict(val)
            self._reads[key] = val
        elif t is list:
            val = list(val)
            self._reads[key] = val
        return val

    def get(self, key: bytes) -> Any:
        if key in self._writes:
            val = self._writes[key]
            return None if val is _DELETED else val
        return self._committed_read(key)

    def put(self, key: bytes, value: Any) -> None:
        if key not in self._writes:
            insort(self._sorted_writes, key)
        self._writes[key] = value
        if self.capture is not None:
            self.capture.append(("put", key, value))

    def delete(self, key: bytes) -> None:
        if key not in self._writes:
            insort(self._sorted_writes, key)
        self._writes[key] = _DELETED
        if self.capture is not None:
            self.capture.append(("del", key, None))

    def exists(self, key: bytes) -> bool:
        if key in self._writes:
            return self._writes[key] is not _DELETED
        return key in self._db._data

    def iterate(self, prefix: bytes) -> Iterator[tuple[bytes, Any]]:
        """Ordered iteration over committed ∪ pending entries under prefix.

        Snapshot semantics (RocksDB-iterator-like): the view is materialized at
        call time, so scan-and-update loops (job deadlines, timer due dates)
        see a stable snapshot and never skip or double-see entries mutated
        mid-iteration.
        """
        db = self._db
        if db._native_iterate is not None:
            # one native merge pass (codec.c iterate_snapshot) — identical
            # semantics to the Python path below, including the defensive
            # copy-and-cache of committed container values
            return iter(db._native_iterate(
                db._sorted_keys, db._data, prefix, self._sorted_writes,
                self._writes, _DELETED, self._reads))
        snapshot: list[tuple[bytes, Any]] = []
        writes = self._writes
        sw = self._sorted_writes
        lo = bisect_left(sw, prefix)
        end = _prefix_successor(prefix)
        hi = bisect_left(sw, end) if end is not None else len(sw)
        overlay_keys = sw[lo:hi]
        if not overlay_keys:
            for key in db._keys_with_prefix(prefix):
                snapshot.append((key, self._committed_read(key)))
            return iter(snapshot)
        overlay = set(overlay_keys)
        for key in db._keys_with_prefix(prefix):
            if key in overlay:
                continue  # superseded by pending write/delete
            snapshot.append((key, self._committed_read(key)))
        for key in overlay_keys:
            val = writes[key]
            if val is not _DELETED:
                snapshot.append((key, val))
        snapshot.sort(key=lambda kv: kv[0])
        return iter(snapshot)

    def commit(self) -> None:
        db = self._db
        dirty = db._dirty_keys
        if dirty is not None and self._writes:
            # incremental-snapshot delta tracking: every committed overlay
            # key (put OR delete) joins the changed-keys-since-last-snapshot
            # set, regardless of which commit pass (native/python/durable)
            # applies it below
            dirty.update(self._writes)
        db._pre_commit(self._writes)
        if db._native_commit is not None:
            # one native pass (codec.c commit_overlay) applying the overlay
            # to the committed dict + sorted-keys list — identical semantics
            # to the per-key loop below
            db._native_commit(self._writes, db._data, db._sorted_keys, _DELETED)
        else:
            for key, val in self._writes.items():
                if val is _DELETED:
                    db._delete_committed(key)
                else:
                    db._put_committed(key, val)
        self._writes.clear()
        self.closed = True

    def rollback(self) -> None:
        self._writes.clear()
        self.closed = True


class ColumnFamily:
    """Typed facade over one logical column family within a transaction context.

    Keys are tuples of (int | str | bytes); values any msgpack-able object.
    Mirrors the reference's TransactionalColumnFamily get/put/iterate surface.
    """

    __slots__ = ("_db", "code", "_prefix")

    def __init__(self, db: "ZbDb", code: ColumnFamilyCode) -> None:
        self._db = db
        self.code = code
        self._prefix = struct.pack(">H", int(code))

    def _ctx(self) -> Transaction:
        return self._db.require_transaction()

    def _key(self, key_parts: tuple) -> bytes:
        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        return encode_key(self.code, key_parts)

    def get(self, key_parts: tuple) -> Any:
        return self._ctx().get(self._key(key_parts))

    def exists(self, key_parts: tuple) -> bool:
        return self._ctx().exists(self._key(key_parts))

    def put(self, key_parts: tuple, value: Any) -> None:
        self._db._check_foreign_keys(self.code, value)
        self._ctx().put(self._key(key_parts), value)

    def insert(self, key_parts: tuple, value: Any) -> None:
        """Put that requires the key to be absent (consistency precondition)."""
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and ctx.exists(key):
            raise ZbDbInconsistentError(f"insert: key already exists in {self.code.name}: {key_parts}")
        self._db._check_foreign_keys(self.code, value)
        ctx.put(key, value)

    def update(self, key_parts: tuple, value: Any) -> None:
        """Put that requires the key to exist (consistency precondition)."""
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and not ctx.exists(key):
            raise ZbDbInconsistentError(f"update: key missing in {self.code.name}: {key_parts}")
        self._db._check_foreign_keys(self.code, value)
        ctx.put(key, value)

    def delete(self, key_parts: tuple) -> None:
        key = self._key(key_parts)
        ctx = self._ctx()
        if self._db.consistency_checks and not ctx.exists(key):
            raise ZbDbInconsistentError(f"delete: key missing in {self.code.name}: {key_parts}")
        ctx.delete(key)

    def items(self, prefix: tuple = ()) -> Iterator[tuple[bytes, Any]]:
        """Iterate (encoded_key, value) pairs under a key-part prefix, ordered."""
        # a key-part prefix encodes exactly like a key of those parts, so the
        # scan prefix rides the same fast path (native/cached) as point keys
        pfx = encode_key(self.code, prefix) if prefix else self._prefix
        yield from self._ctx().iterate(pfx)

    def values(self, prefix: tuple = ()) -> Iterator[Any]:
        for _, v in self.items(prefix):
            yield v

    def is_empty(self, prefix: tuple = ()) -> bool:
        return next(self.items(prefix), None) is None

    def first_value(self, prefix: tuple = ()) -> Any:
        item = next(self.items(prefix), None)
        return None if item is None else item[1]


class ZbDb:
    """The partition state store. One instance per partition.

    ``transaction()`` is a context manager committing on success, rolling back
    on exception — the unit of processing atomicity.
    """

    def __init__(self, consistency_checks: bool = False) -> None:
        self._data: dict[bytes, Any] = {}
        self._sorted_keys: list[bytes] = []
        self._txn: Transaction | None = None
        self.consistency_checks = consistency_checks
        self._foreign_key_checkers: dict[ColumnFamilyCode, Callable[["ZbDb", Any], None]] = {}
        # subclass hooks: the durable backend (state/durable.py) swaps the
        # native iterate/commit out (its cold values need per-read
        # resolution and its key index is a blocked sorted structure, not
        # the flat list the C pass mutates) and journals commit overlays
        # through _pre_commit
        self._native_iterate = _iterate_snapshot
        self._native_commit = _commit_overlay
        # changed-keys-since-last-snapshot set for incremental snapshots
        # (state/snapshot.py delta chains); None = tracking off — one is-None
        # check per commit
        self._dirty_keys: set[bytes] | None = None

    # -- committed-store internals ------------------------------------------

    def _committed_value(self, key: bytes) -> Any:
        """Committed read hook — overridden by backends whose stored
        representation needs resolving (durable cold values)."""
        return self._data.get(key)

    def _pre_commit(self, writes: dict[bytes, Any]) -> None:
        """Called with the overlay just before it applies — the durable
        backend appends it to the write-ahead delta log here."""

    def _put_committed(self, key: bytes, value: Any) -> None:
        if key not in self._data:
            insort(self._sorted_keys, key)
        self._data[key] = value

    def _delete_committed(self, key: bytes) -> None:
        if key in self._data:
            del self._data[key]
            i = bisect_left(self._sorted_keys, key)
            if i < len(self._sorted_keys) and self._sorted_keys[i] == key:
                self._sorted_keys.pop(i)

    def _keys_with_prefix(self, prefix: bytes) -> list[bytes]:
        lo = bisect_left(self._sorted_keys, prefix)
        end = _prefix_successor(prefix)
        hi = bisect_left(self._sorted_keys, end) if end is not None else len(self._sorted_keys)
        return self._sorted_keys[lo:hi]

    # -- transactions --------------------------------------------------------

    def transaction(self) -> "_TxnContext":
        self._before_transaction()
        return _TxnContext(self)

    def _before_transaction(self) -> None:
        """Hook before a transaction opens — the durable backend finishes
        lazy recovery (base-segment indexing) here."""

    def committed_get(self, code: ColumnFamilyCode, key_parts: tuple) -> Any:
        """Lock-free point read of the COMMITTED store, bypassing the single
        processing-owned transaction slot — the cross-thread read path for
        the QueryService (reference: StateQueryService reads a RocksDB
        snapshot concurrently with processing). An open processing
        transaction's uncommitted writes are invisible, exactly as with a
        storage snapshot; dict point reads are atomic under the GIL."""
        if not isinstance(key_parts, tuple):
            key_parts = (key_parts,)
        return self._data.get(encode_key(code, key_parts))

    def require_transaction(self) -> Transaction:
        if self._txn is None or self._txn.closed:
            raise RuntimeError("state access outside a transaction")
        return self._txn

    @property
    def in_transaction(self) -> bool:
        return self._txn is not None and not self._txn.closed

    # -- column families -----------------------------------------------------

    def column_family(self, code: ColumnFamilyCode) -> ColumnFamily:
        return ColumnFamily(self, code)

    def register_foreign_key_check(
        self, code: ColumnFamilyCode, check: Callable[["ZbDb", Any], None]
    ) -> None:
        self._foreign_key_checkers[code] = check

    def _check_foreign_keys(self, code: ColumnFamilyCode, value: Any) -> None:
        if self.consistency_checks:
            checker = self._foreign_key_checkers.get(code)
            if checker is not None:
                checker(self, value)

    # -- snapshot serialization ---------------------------------------------

    SNAPSHOT_MAGIC = b"ZSNP\x01"

    def to_snapshot_bytes(self) -> bytes:
        """Serialize the committed state (msgpack body + crc32 trailer)."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        body = msgpack.packb(
            [[k, v] for k, v in ((k, self._data[k]) for k in self._sorted_keys)]
        )
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.SNAPSHOT_MAGIC + struct.pack("<I", crc) + body

    @classmethod
    def from_snapshot_bytes(cls, raw: bytes, consistency_checks: bool = False) -> "ZbDb":
        if raw[:5] != cls.SNAPSHOT_MAGIC:
            raise ValueError("bad state snapshot magic")
        (crc,) = struct.unpack_from("<I", raw, 5)
        body = raw[9:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("state snapshot checksum mismatch")
        db = cls(consistency_checks=consistency_checks)
        for k, v in msgpack.unpackb(body):
            db._data[k] = v
            db._sorted_keys.append(k)
        return db

    def content_equals(self, other: "ZbDb") -> bool:
        """Deep state equality — the replay≡processing test oracle."""
        return self._data == other._data

    # -- incremental-snapshot delta serialization ----------------------------

    DELTA_MAGIC = b"ZDLT\x01"
    # subclasses whose _data holds non-msgpack-able representations (the
    # durable store's _Packed/memoryview cold values) must opt OUT: a delta
    # serialized from them would crash packb or decode as the wrong type
    supports_delta_snapshots = True

    def begin_delta_tracking(self) -> None:
        """Start (or restart) recording changed keys. Call after recovery so
        the first delta captures exactly the writes since the recovered
        snapshot chain's tip."""
        self._dirty_keys = set()

    @property
    def delta_tracking(self) -> bool:
        return self._dirty_keys is not None

    @property
    def dirty_key_count(self) -> int:
        return len(self._dirty_keys) if self._dirty_keys is not None else 0

    @property
    def key_count(self) -> int:
        return len(self._data)

    def to_delta_bytes(self) -> bytes:
        """Serialize the changed-keys-since-tracking-start as a delta
        (msgpack ``[[key, deleted, value], …]`` + crc32 trailer, same
        integrity scheme as the full snapshot). Does NOT clear the tracked
        set — the caller clears only after the delta is durably persisted,
        so an aborted snapshot never loses changes."""
        if self.in_transaction:
            raise RuntimeError("cannot snapshot with an open transaction")
        if self._dirty_keys is None:
            raise RuntimeError("delta tracking is not active")
        data = self._data
        entries = []
        for key in sorted(self._dirty_keys):
            if key in data:
                entries.append([key, False, data[key]])
            else:
                entries.append([key, True, None])
        body = msgpack.packb(entries)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return self.DELTA_MAGIC + struct.pack("<I", crc) + body

    def clear_delta_tracking(self) -> None:
        """Reset the changed-key window (the just-persisted delta covers it)."""
        self._dirty_keys = set()

    def apply_delta_bytes(self, raw: bytes) -> int:
        """Apply one delta on top of the committed store (chain recovery:
        base snapshot, then each delta in order). Returns the entry count."""
        if raw[:5] != self.DELTA_MAGIC:
            raise ValueError("bad state delta magic")
        (crc,) = struct.unpack_from("<I", raw, 5)
        body = raw[9:]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("state delta checksum mismatch")
        entries = msgpack.unpackb(body)
        for key, deleted, value in entries:
            if deleted:
                self._delete_committed(key)
            else:
                self._put_committed(key, value)
        return len(entries)


class _TxnContext:
    __slots__ = ("_db", "_txn")

    def __init__(self, db: ZbDb) -> None:
        self._db = db

    def __enter__(self) -> Transaction:
        if self._db.in_transaction:
            raise RuntimeError("nested transactions are not supported")
        self._txn = Transaction(self._db)
        self._db._txn = self._txn
        return self._txn

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._txn.closed:
            if exc_type is None:
                self._txn.commit()
            else:
                self._txn.rollback()
        self._db._txn = None
