"""State substrate: column-family KV store + snapshot store (SURVEY.md §2.4, §2.6)."""

from zeebe_tpu.state.db import (
    ColumnFamily,
    ColumnFamilyCode,
    Transaction,
    ZbDb,
    ZbDbInconsistentError,
    encode_key,
)
from zeebe_tpu.state.durable import DurableZbDb
from zeebe_tpu.state.tiering import (
    ColdRef,
    ColdStore,
    TieredZbDb,
    TieringCfg,
    TieringManager,
)
from zeebe_tpu.state.snapshot import (
    FileBasedSnapshotStore,
    InvalidSnapshotError,
    PersistedSnapshot,
    SnapshotChunk,
    SnapshotId,
    TransientSnapshot,
)

__all__ = [
    "ColdRef",
    "ColdStore",
    "ColumnFamily",
    "ColumnFamilyCode",
    "DurableZbDb",
    "TieredZbDb",
    "TieringCfg",
    "TieringManager",
    "FileBasedSnapshotStore",
    "InvalidSnapshotError",
    "PersistedSnapshot",
    "SnapshotChunk",
    "SnapshotId",
    "Transaction",
    "TransientSnapshot",
    "ZbDb",
    "ZbDbInconsistentError",
    "encode_key",
]
