"""Replicated request dedupe: the exactly-once table for client commands.

Reference shape: the gateway's retry contract in the reference engine is
safe because the broker answers a resent request from the *log*, not from
process memory. The multi-process runtime's in-memory ingress dedupe
(``multiproc/worker.py``) dies with the worker, degrading acked-command
semantics to at-most-once across a crash. This module moves the dedupe
table into partition state, materialized from the replicated log on
processing AND replay (Raft — Ongaro & Ousterhout 2014, PAPERS.md — is what
makes the log the shared source of truth), so a follower promoted to leader
or a restarted leader inherits every request's fate:

- ``REQUEST_DEDUPE``: ``(request_stream_id, request_id)`` →
  ``{"c": command position, "f": stored reply frame}``. An entry without
  ``"f"`` is *awaiting*: the command was processed but its reply (if any)
  belongs to a later step (await-result), or it produced none.
- ``REQUEST_DEDUPE_BY_POSITION``: ``(command position, stream id, request
  id)`` → None — the aging index. Entries older than
  ``RETENTION_POSITIONS`` log positions are deleted as new entries land,
  on live processing and replay alike, so the table stays bounded AND
  replay-parity holds (aging is a pure function of the log).

Materialization rule (identical on the live and replay paths — the parity
oracle ``testing.chaos.engine_state_equals`` compares this family too):

1. When a command carrying a request id is processed, write an awaiting
   entry at its position.
2. Every logged EVENT/COMMAND_REJECTION whose frame carries a request id
   (``engine/writers.py`` stamps responses) overwrites the entry with the
   reply: command position + the reply frame re-encoded with timestamp 0
   (frames are position-independent, so live and replayed bytes agree).
3. After noting, age out entries older than the retention window.

Reads from ingress use the committed-read discipline the other state
facades use (``ZbDb.committed_get``; the worker ingress handler runs on the
pump thread between transactions).
"""

from __future__ import annotations

import os

from zeebe_tpu.state.db import ColumnFamilyCode, decode_key


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


#: entries whose command position is more than this many log positions
#: behind the newest note are aged out. Read once at import so every
#: processor in a process (live AND the replay oracle) agrees.
RETENTION_POSITIONS = max(
    _env_int("ZEEBE_REQUEST_DEDUPE_RETENTIONPOSITIONS", 100_000), 1)

#: replies larger than this store only the command position (the gateway
#: resend then waits for the deadline instead of a replayed reply); bounded
#: so one huge variables payload cannot bloat the dedupe table
MAX_REPLY_FRAME_BYTES = 64 * 1024


class RequestDedupeState:
    """Facade over the two dedupe column families. All writes must run
    inside the owning processor's transaction; ``lookup_committed`` is the
    cross-step committed read for ingress."""

    def __init__(self, db) -> None:
        self.db = db
        self._table = db.column_family(ColumnFamilyCode.REQUEST_DEDUPE)
        self._by_position = db.column_family(
            ColumnFamilyCode.REQUEST_DEDUPE_BY_POSITION)

    # -- writes (processing + replay, inside the step transaction) -------------

    def note_awaiting(self, position: int, stream_id: int,
                      request_id: int) -> None:
        """The command at ``position`` carrying ``(stream_id, request_id)``
        was processed; no reply recorded yet (overwritten by ``note_reply``
        when one lands in the same or a later step)."""
        self._put(stream_id, request_id, {"c": position})

    def note_reply(self, command_position: int, record) -> None:
        """``record`` (an EVENT or COMMAND_REJECTION whose frame carries the
        request identity) answers ``(record.request_stream_id,
        record.request_id)``; store the reply for resend replay. Frames are
        encoded with timestamp 0 — position and batch timestamp live outside
        the frame, so live and replayed bytes are identical."""
        frame = record.encode(timestamp=0)[0]
        entry = {"c": command_position}
        if len(frame) <= MAX_REPLY_FRAME_BYTES:
            entry["f"] = frame
        else:
            entry["big"] = True
        self._put(record.request_stream_id, record.request_id, entry)

    def _put(self, stream_id: int, request_id: int, entry: dict) -> None:
        key = (stream_id, request_id)
        prev = self._table.get(key)
        self._table.put(key, entry)
        if prev is not None and prev["c"] != entry["c"]:
            # a duplicate command slipped in below the ingress check (e.g. a
            # pre-dedupe log): the newest position owns the index entry
            self._by_position.delete((prev["c"], stream_id, request_id))
        if prev is None or prev["c"] != entry["c"]:
            self._by_position.put((entry["c"], stream_id, request_id), None)

    def age_out(self, position: int) -> None:
        """Delete entries older than the retention window below
        ``position``. O(expired) via the position index; deterministic from
        the log, so replayed state ages identically."""
        horizon = position - RETENTION_POSITIONS
        if horizon <= 0:
            return
        expired = [enc for enc, _ in self._by_position.items_below((horizon,))]
        for enc in expired:
            _cf, (old_position, stream_id, request_id) = decode_key(enc)
            self._by_position.delete((old_position, stream_id, request_id))
            self._table.delete((stream_id, request_id))

    # -- reads (ingress, committed-read discipline) ----------------------------

    @staticmethod
    def lookup_committed(db, stream_id: int, request_id: int) -> dict | None:
        """The committed dedupe entry for a request identity, or None. Safe
        from the pump thread between transactions (same discipline as the
        query facades)."""
        if request_id < 0:
            return None
        return db.committed_get(ColumnFamilyCode.REQUEST_DEDUPE,
                                (stream_id, request_id))
