"""Record — the unit of the event-sourced stream.

A record is metadata (position, key, record type, value type, intent, rejection)
plus a value payload (a msgpack map). Mirrors the reference's ``Record<T>``
interface and ``RecordMetadata`` SBE header (reference: protocol/src/main/java/io/
camunda/zeebe/protocol/record/Record.java; protocol-impl/…/record/RecordMetadata.java).

Values are plain dicts with camelCase keys matching the reference's JSON view, so
parity tests can diff event streams directly against reference semantics.
Serialization is a fixed-layout metadata header + msgpack value body.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Mapping

from zeebe_tpu.protocol import msgpack
from zeebe_tpu.protocol.enums import RecordType, RejectionType, ValueType
from zeebe_tpu.protocol.intent import Intent


def _intent_tables() -> dict[int, dict[int, Intent]]:
    out: dict[int, dict[int, Intent]] = {}
    for vt in ValueType:
        try:
            cls = Intent.for_value_type(vt)
        except (KeyError, ValueError):
            continue
        out[int(vt)] = {int(member): member for member in cls}
    return out


# decode lookup tables: plain dict gets beat Enum.__call__ 4x per record on
# the log-scan hot path
_RT_BY_VALUE = {int(v): v for v in RecordType}
_VT_BY_VALUE = {int(v): v for v in ValueType}
_REJ_BY_VALUE = {int(v): v for v in RejectionType}
_INTENT_BY_VT = _intent_tables()

# Wire layout for the serialized metadata header, preceding the msgpack body
# (the reference frames this with SBE; we use a fixed little-endian struct —
# same information, simpler codegen story):
#   u8 recordType | u8 valueType | u8 intent | u8 rejectionType
#   i64 key | i64 sourceRecordPosition | i64 timestamp
#   i32 requestStreamId | i64 requestId | i64 operationReference
#   u16 rejectionReasonLen | rejectionReason (utf-8)
#   u32 valueLen | value (msgpack)
_HEADER = struct.Struct("<BBBBqqqiqqH")


def _py_decode_frame(data: bytes) -> tuple:
    """Pure-Python frame decode; same 12-tuple as the native fast path."""
    fields = _HEADER.unpack_from(data, 0)
    reason_len = fields[10]
    off = _HEADER.size
    reason = data[off : off + reason_len].decode("utf-8")
    off += reason_len
    (value_len,) = struct.unpack_from("<I", data, off)
    off += 4
    if off + value_len != len(data):
        raise ValueError(
            f"record frame length mismatch: header says {off + value_len}, got {len(data)}"
        )
    value = msgpack.unpackb(data[off : off + value_len])
    return (*fields[:10], reason, value)


from zeebe_tpu import native as _native  # noqa: E402  (cycle-free leaf package)

_codec = _native.load_codec()
_decode_frame = (
    _codec.decode_record_frame
    if _codec is not None and hasattr(_codec, "decode_record_frame")
    else _py_decode_frame
)
# encode mirror (native/codec.c encode_record_frame): None on a stale .so or
# under ZEEBE_TPU_NO_NATIVE — the Python body in _py_encode stays the
# byte-parity oracle either way
_encode_frame = _native.codec_fn("encode_record_frame")

NO_POSITION = -1
NO_KEY = -1
NO_REQUEST = -1


@dataclasses.dataclass(frozen=True, slots=True)
class Record:
    """Immutable stream record. ``position`` is assigned by the sequencer at
    append time; ``source_record_position`` back-links a follow-up record to the
    command that produced it (drives replay's lastProcessedPosition tracking)."""

    record_type: RecordType
    value_type: ValueType
    intent: Intent
    value: Mapping[str, Any]
    key: int = NO_KEY
    position: int = NO_POSITION
    source_record_position: int = NO_POSITION
    timestamp: int = 0  # epoch millis, assigned at append time
    partition_id: int = 0
    rejection_type: RejectionType = RejectionType.NULL_VAL
    rejection_reason: str = ""
    # Request correlation for client responses (gateway stream/request ids).
    request_stream_id: int = NO_REQUEST
    request_id: int = NO_REQUEST
    # Client-supplied reference carried through to events (reference 8.4 feature).
    operation_reference: int = 0

    @property
    def is_command(self) -> bool:
        return self.record_type == RecordType.COMMAND

    @property
    def is_event(self) -> bool:
        return self.record_type == RecordType.EVENT

    @property
    def is_rejection(self) -> bool:
        return self.record_type == RecordType.COMMAND_REJECTION

    def replace(self, **kw: Any) -> "Record":
        # hand-rolled dataclasses.replace: this runs once per record on the
        # append path (timestamp/request stamping) and dataclasses.replace's
        # signature re-validation is ~4x the cost of the constructor call;
        # positional construction skips the kwargs dict plumbing on top.
        # _FIELDS/_FIELD_INDEX are derived from the dataclass below so new
        # fields can never be silently dropped.
        current = [getattr(self, name) for name in _FIELDS]
        for name, value in kw.items():
            current[_FIELD_INDEX[name]] = value
        return Record(*current)

    # -- serialization -------------------------------------------------------

    def to_bytes(self) -> bytes:
        return self.encode()[0]

    def encode(self, timestamp: int | None = None) -> tuple[bytes, bytes]:
        """Serialize; returns (frame, value_body) — the msgpack value bytes
        are exposed so the append path can seed its decode cache without
        re-packing the value. ``timestamp`` (when given) is packed instead of
        ``self.timestamp`` — the append path stamps one batch timestamp, and
        passing it here avoids a per-record replace().

        One native call builds header, reason, and msgpack body in a single
        buffer pass (native/codec.c encode_record_frame); ``_py_encode`` is
        the pure-Python specification with identical bytes."""
        if _encode_frame is not None:
            value = self.value
            return _encode_frame(
                self.record_type, self.value_type, self.intent,
                self.rejection_type, self.key, self.source_record_position,
                self.timestamp if timestamp is None else timestamp,
                self.request_stream_id, self.request_id,
                self.operation_reference, self.rejection_reason,
                value if type(value) is dict else dict(value),
            )
        return self._py_encode(timestamp)

    def _py_encode(self, timestamp: int | None = None) -> tuple[bytes, bytes]:
        """Pure-Python frame encode; same (frame, body) as the native path."""
        reason = self.rejection_reason.encode("utf-8")
        if len(reason) > 0xFFFF:
            # the wire field is u16; truncate on a codepoint boundary so an
            # oversized error message can never poison the append path
            reason = reason[:0xFFFF]
            while reason and (reason[-1] & 0xC0) == 0x80:
                reason = reason[:-1]
            if reason and reason[-1] >= 0xC0:  # dangling lead byte
                reason = reason[:-1]
        value = self.value
        body = msgpack.packb(value if type(value) is dict else dict(value))
        header = _HEADER.pack(
            int(self.record_type),
            int(self.value_type),
            int(self.intent),
            int(self.rejection_type),
            self.key,
            self.source_record_position,
            self.timestamp if timestamp is None else timestamp,
            self.request_stream_id,
            self.request_id,
            self.operation_reference,
            len(reason),
        )
        return b"".join((header, reason, struct.pack("<I", len(body)), body)), body

    @classmethod
    def from_bytes(cls, data: bytes, position: int = NO_POSITION, partition_id: int = 0,
                   timestamp: int | None = None) -> "Record":
        """``timestamp`` (when given) overrides the frame's timestamp field —
        the batch framing stamps one timestamp per batch, and passing it here
        avoids a per-record replace() on the decode path."""
        try:
            return cls._from_bytes(data, position, partition_id, timestamp)
        except (struct.error, UnicodeDecodeError, msgpack.MsgPackError,
                KeyError) as exc:
            # KeyError: unknown enum value in the frame header (lookup tables)
            raise ValueError(f"malformed record frame: {exc}") from exc

    @classmethod
    def _from_bytes(cls, data: bytes, position: int, partition_id: int,
                    timestamp_override: int | None = None) -> "Record":
        # one native call parses the fixed header, the rejection reason, and
        # the msgpack body together (native/codec.c decode_record_frame);
        # _py_decode_frame is the pure-Python fallback with identical output
        (
            record_type,
            value_type,
            intent_val,
            rejection_type,
            key,
            source_pos,
            timestamp,
            request_stream_id,
            request_id,
            operation_reference,
            reason,
            value,
        ) = _decode_frame(data)
        # dict lookups instead of Enum.__call__ (4 enum constructions per
        # record add up on the log-scan hot path)
        vt = _VT_BY_VALUE[value_type]
        intent = _INTENT_BY_VT[value_type][intent_val]
        return cls(
            record_type=_RT_BY_VALUE[record_type],
            value_type=vt,
            intent=intent,
            value=value,
            key=key,
            position=position,
            source_record_position=source_pos,
            timestamp=timestamp if timestamp_override is None else timestamp_override,
            partition_id=partition_id,
            rejection_type=_REJ_BY_VALUE[rejection_type],
            rejection_reason=reason,
            request_stream_id=request_stream_id,
            request_id=request_id,
            operation_reference=operation_reference,
        )

    # -- JSON view (reference: protocol-jackson) -----------------------------

    def to_json_dict(self) -> dict[str, Any]:
        """Camel-case JSON view matching the reference's Record JSON shape."""
        return {
            "position": self.position,
            "sourceRecordPosition": self.source_record_position,
            "key": self.key,
            "timestamp": self.timestamp,
            "recordType": self.record_type.name,
            "valueType": self.value_type.name,
            "intent": self.intent.name,
            "partitionId": self.partition_id,
            "rejectionType": self.rejection_type.name,
            "rejectionReason": self.rejection_reason,
            "operationReference": self.operation_reference,
            "value": dict(self.value),
        }


_FIELDS = tuple(f.name for f in dataclasses.fields(Record))
_FIELD_INDEX = {name: i for i, name in enumerate(_FIELDS)}


def command(value_type: ValueType, intent: Intent, value: Mapping[str, Any], **kw: Any) -> Record:
    return Record(RecordType.COMMAND, value_type, intent, value, **kw)


def event(value_type: ValueType, intent: Intent, value: Mapping[str, Any], **kw: Any) -> Record:
    return Record(RecordType.EVENT, value_type, intent, value, **kw)


def rejection(
    cmd: Record, rejection_type: RejectionType, reason: str, position: int = NO_POSITION
) -> Record:
    """Build the COMMAND_REJECTION record answering ``cmd``."""
    return Record(
        record_type=RecordType.COMMAND_REJECTION,
        value_type=cmd.value_type,
        intent=cmd.intent,
        value=cmd.value,
        key=cmd.key,
        position=position,
        source_record_position=cmd.position,
        partition_id=cmd.partition_id,
        rejection_type=rejection_type,
        rejection_reason=reason,
        request_stream_id=cmd.request_stream_id,
        request_id=cmd.request_id,
        operation_reference=cmd.operation_reference,
    )
