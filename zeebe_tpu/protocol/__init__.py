"""Protocol layer: record schema, intents, keys, msgpack codec (SURVEY.md §2.9)."""

from zeebe_tpu.protocol.enums import (
    DEFAULT_TENANT,
    BpmnElementType,
    BpmnEventType,
    ErrorType,
    PartitionRole,
    RecordType,
    RejectionType,
    ValueType,
)
from zeebe_tpu.protocol.intent import Intent
from zeebe_tpu.protocol.keys import (
    KeyGenerator,
    decode_key_in_partition,
    decode_partition_id,
    encode_partition_id,
)
from zeebe_tpu.protocol.record import Record, command, event, rejection

__all__ = [
    "DEFAULT_TENANT",
    "BpmnElementType",
    "BpmnEventType",
    "ErrorType",
    "Intent",
    "KeyGenerator",
    "PartitionRole",
    "Record",
    "RecordType",
    "RejectionType",
    "ValueType",
    "command",
    "decode_key_in_partition",
    "decode_partition_id",
    "encode_partition_id",
    "event",
    "rejection",
]
