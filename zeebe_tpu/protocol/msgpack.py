"""MessagePack codec — the record value wire format.

Self-contained implementation of the msgpack spec subset Zeebe uses for record
values and variable documents (reference: msgpack-core/src/main/java/io/camunda/
zeebe/msgpack/spec/{MsgPackWriter,MsgPackReader}.java): nil, bool, int (up to
64-bit signed/unsigned), float64, str, bin, array, map.

Why not the C `msgpack` package: record codecs are part of the framework (the
reference implements its own zero-alloc reader/writer rather than depending on
msgpack-java), and this module is also the specification for the planned C++
hot-path codec. The pure-Python path is used for control-plane records; the bulk
data path (device arrays) never goes through msgpack at all — that is the point
of the TPU design. Tests cross-check this codec against the C msgpack package.
"""

from __future__ import annotations

import struct
from typing import Any

_pack_f64 = struct.Struct(">d").pack
_pack_u16 = struct.Struct(">H").pack
_pack_u32 = struct.Struct(">I").pack
_pack_u64 = struct.Struct(">Q").pack
_pack_i8 = struct.Struct(">b").pack
_pack_i16 = struct.Struct(">h").pack
_pack_i32 = struct.Struct(">i").pack
_pack_i64 = struct.Struct(">q").pack


class MsgPackError(Exception):
    pass


def packb(obj: Any) -> bytes:
    """Serialize ``obj`` to msgpack bytes. Dict keys are serialized in insertion
    order (determinism: callers must present keys in a canonical order; record
    values do — see record.py)."""
    buf = bytearray()
    _pack(obj, buf)
    return bytes(buf)


def _pack(obj: Any, buf: bytearray, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise MsgPackError(f"msgpack nesting exceeds {_MAX_DEPTH}")
    if obj is None:
        buf.append(0xC0)
    elif obj is True:
        buf.append(0xC3)
    elif obj is False:
        buf.append(0xC2)
    elif isinstance(obj, int):
        _pack_int(obj, buf)
    elif isinstance(obj, float):
        buf.append(0xCB)
        buf += _pack_f64(obj)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        n = len(raw)
        if n < 32:
            buf.append(0xA0 | n)
        elif n < 0x100:
            buf.append(0xD9)
            buf.append(n)
        elif n < 0x10000:
            buf.append(0xDA)
            buf += _pack_u16(n)
        else:
            buf.append(0xDB)
            buf += _pack_u32(n)
        buf += raw
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        n = len(raw)
        if n < 0x100:
            buf.append(0xC4)
            buf.append(n)
        elif n < 0x10000:
            buf.append(0xC5)
            buf += _pack_u16(n)
        else:
            buf.append(0xC6)
            buf += _pack_u32(n)
        buf += raw
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n < 16:
            buf.append(0x90 | n)
        elif n < 0x10000:
            buf.append(0xDC)
            buf += _pack_u16(n)
        else:
            buf.append(0xDD)
            buf += _pack_u32(n)
        for item in obj:
            _pack(item, buf, depth + 1)
    elif isinstance(obj, dict):
        n = len(obj)
        if n < 16:
            buf.append(0x80 | n)
        elif n < 0x10000:
            buf.append(0xDE)
            buf += _pack_u16(n)
        else:
            buf.append(0xDF)
            buf += _pack_u32(n)
        for k, v in obj.items():
            _pack(k, buf, depth + 1)
            _pack(v, buf, depth + 1)
    else:
        raise MsgPackError(f"cannot msgpack type {type(obj).__name__}")


def _pack_int(v: int, buf: bytearray) -> None:
    if v >= 0:
        if v < 0x80:
            buf.append(v)
        elif v < 0x100:
            buf.append(0xCC)
            buf.append(v)
        elif v < 0x10000:
            buf.append(0xCD)
            buf += _pack_u16(v)
        elif v < 0x100000000:
            buf.append(0xCE)
            buf += _pack_u32(v)
        elif v < 0x10000000000000000:
            buf.append(0xCF)
            buf += _pack_u64(v)
        else:
            raise MsgPackError(f"int too large: {v}")
    else:
        if v >= -32:
            buf.append(v & 0xFF)
        elif v >= -0x80:
            buf.append(0xD0)
            buf += _pack_i8(v)
        elif v >= -0x8000:
            buf.append(0xD1)
            buf += _pack_i16(v)
        elif v >= -0x80000000:
            buf.append(0xD2)
            buf += _pack_i32(v)
        elif v >= -0x8000000000000000:
            buf.append(0xD3)
            buf += _pack_i64(v)
        else:
            raise MsgPackError(f"int too small: {v}")


_MAX_DEPTH = 256


class _Reader:
    __slots__ = ("data", "pos", "depth")

    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0
        self.depth = 0

    def read(self) -> Any:
        data = self.data
        i = self.pos
        if i >= len(data):
            raise MsgPackError("truncated msgpack data")
        if self.depth > _MAX_DEPTH:
            raise MsgPackError(f"msgpack nesting exceeds {_MAX_DEPTH}")
        b = data[i]
        self.pos = i + 1
        if b < 0x80:  # positive fixint
            return b
        if b >= 0xE0:  # negative fixint
            return b - 0x100
        if 0x80 <= b <= 0x8F:
            return self._read_map(b & 0x0F)
        if 0x90 <= b <= 0x9F:
            return self._read_array(b & 0x0F)
        if 0xA0 <= b <= 0xBF:
            return self._read_str(b & 0x1F)
        handler = _HANDLERS.get(b)
        if handler is None:
            raise MsgPackError(f"unsupported msgpack byte 0x{b:02x}")
        return handler(self)

    def _take(self, n: int) -> bytes:
        i = self.pos
        if i + n > len(self.data):
            raise MsgPackError("truncated msgpack data")
        self.pos = i + n
        return self.data[i : i + n]

    def _read_str(self, n: int) -> str:
        return self._take(n).decode("utf-8")

    def _read_array(self, n: int) -> list:
        self.depth += 1
        out = [self.read() for _ in range(n)]
        self.depth -= 1
        return out

    def _read_map(self, n: int) -> dict:
        self.depth += 1
        out = {}
        for _ in range(n):
            k = self.read()
            out[k] = self.read()
        self.depth -= 1
        return out

    def _u(self, fmt: str, n: int) -> int:
        return struct.unpack(fmt, self._take(n))[0]


_HANDLERS = {
    0xC0: lambda r: None,
    0xC2: lambda r: False,
    0xC3: lambda r: True,
    0xC4: lambda r: bytes(r._take(r._u(">B", 1))),
    0xC5: lambda r: bytes(r._take(r._u(">H", 2))),
    0xC6: lambda r: bytes(r._take(r._u(">I", 4))),
    0xCA: lambda r: r._u(">f", 4),
    0xCB: lambda r: r._u(">d", 8),
    0xCC: lambda r: r._u(">B", 1),
    0xCD: lambda r: r._u(">H", 2),
    0xCE: lambda r: r._u(">I", 4),
    0xCF: lambda r: r._u(">Q", 8),
    0xD0: lambda r: r._u(">b", 1),
    0xD1: lambda r: r._u(">h", 2),
    0xD2: lambda r: r._u(">i", 4),
    0xD3: lambda r: r._u(">q", 8),
    0xD9: lambda r: r._read_str(r._u(">B", 1)),
    0xDA: lambda r: r._read_str(r._u(">H", 2)),
    0xDB: lambda r: r._read_str(r._u(">I", 4)),
    0xDC: lambda r: r._read_array(r._u(">H", 2)),
    0xDD: lambda r: r._read_array(r._u(">I", 4)),
    0xDE: lambda r: r._read_map(r._u(">H", 2)),
    0xDF: lambda r: r._read_map(r._u(">I", 4)),
}


def unpackb(data: bytes) -> Any:
    """Deserialize one msgpack value from ``data`` (must consume all bytes).

    All malformed-input failures surface as MsgPackError so corrupt-frame
    handling in stream consumers can catch one exception type.
    """
    r = _Reader(bytes(data))
    try:
        obj = r.read()
    except MsgPackError:
        raise
    except (UnicodeDecodeError, TypeError, struct.error) as exc:
        raise MsgPackError(f"malformed msgpack data: {exc}") from exc
    if r.pos != len(r.data):
        raise MsgPackError(f"trailing bytes after msgpack value: {len(r.data) - r.pos}")
    return obj


# keep the pure-Python implementations importable under stable names (the
# native parity tests and the ZEEBE_TPU_NO_NATIVE escape hatch use them)
py_packb = packb
py_unpackb = unpackb

from zeebe_tpu import native as _native  # noqa: E402  (cycle-free leaf package)

_codec = _native.load_codec()
if _codec is not None:
    _codec.set_error_class(MsgPackError)
    packb = _codec.packb
    unpackb = _codec.unpackb
