"""Intent enums — the verb of every record (reference: protocol/src/main/java/io/
camunda/zeebe/protocol/record/intent/*.java, 32 enums).

Each ValueType has an Intent enum; commands use imperative intents (CREATE,
COMPLETE), events use past/progressive intents (CREATED, ELEMENT_ACTIVATING).
Integer codes are wire format and device opcodes — append-only.

``Intent.for_value_type`` maps a ValueType to its intent enum so records can be
decoded generically.
"""

from __future__ import annotations

import enum

from zeebe_tpu.protocol.enums import ValueType

try:
    _nonmember = enum.nonmember
except AttributeError:  # Python < 3.11
    class _NonMember:
        """Descriptor stand-in for enum.nonmember, local to this module (the
        stdlib is not patched): the EnumDict skips descriptors when
        collecting members, and attribute access unwraps to the original
        value — the same observable behavior the declarations below rely on."""

        __slots__ = ("_value",)

        def __init__(self, value):
            self._value = value

        def __get__(self, obj, objtype=None):
            return self._value

    _nonmember = _NonMember


class Intent(enum.IntEnum):
    """Base class marker; all concrete intents subclass this via IntEnum idiom."""

    @classmethod
    def for_value_type(cls, value_type: ValueType) -> type["Intent"]:
        try:
            return _INTENTS_BY_VALUE_TYPE[value_type]
        except KeyError:
            raise ValueError(f"no intent enum for value type {value_type!r}") from None

    @property
    def is_event(self) -> bool:
        """True if this intent names a state change (event), not a request (command)."""
        return self.name in type(self)._EVENT_NAMES  # type: ignore[attr-defined]


class ProcessInstanceIntent(Intent):
    """Element lifecycle (reference: intent/ProcessInstanceIntent.java).

    Commands ACTIVATE/COMPLETE/TERMINATE_ELEMENT drive the BPMN state machine;
    ELEMENT_* events record lifecycle transitions; SEQUENCE_FLOW_TAKEN records
    token movement.
    """

    CANCEL = 0
    SEQUENCE_FLOW_TAKEN = 1
    ELEMENT_ACTIVATING = 2
    ELEMENT_ACTIVATED = 3
    ELEMENT_COMPLETING = 4
    ELEMENT_COMPLETED = 5
    ELEMENT_TERMINATING = 6
    ELEMENT_TERMINATED = 7
    ACTIVATE_ELEMENT = 8
    COMPLETE_ELEMENT = 9
    TERMINATE_ELEMENT = 10

    _EVENT_NAMES = _nonmember(frozenset(
        {
            "SEQUENCE_FLOW_TAKEN",
            "ELEMENT_ACTIVATING",
            "ELEMENT_ACTIVATED",
            "ELEMENT_COMPLETING",
            "ELEMENT_COMPLETED",
            "ELEMENT_TERMINATING",
            "ELEMENT_TERMINATED",
        }
    ))


class ProcessInstanceCreationIntent(Intent):
    CREATE = 0
    CREATED = 1
    CREATE_WITH_AWAITING_RESULT = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED"}))


class ProcessInstanceResultIntent(Intent):
    COMPLETED = 0

    _EVENT_NAMES = _nonmember(frozenset({"COMPLETED"}))


class ProcessInstanceModificationIntent(Intent):
    MODIFY = 0
    MODIFIED = 1

    _EVENT_NAMES = _nonmember(frozenset({"MODIFIED"}))


class ProcessInstanceMigrationIntent(Intent):
    MIGRATE = 0
    MIGRATED = 1

    _EVENT_NAMES = _nonmember(frozenset({"MIGRATED"}))


class ProcessInstanceBatchIntent(Intent):
    ACTIVATE = 0
    ACTIVATED = 1
    TERMINATE = 2
    TERMINATED = 3

    _EVENT_NAMES = _nonmember(frozenset({"ACTIVATED", "TERMINATED"}))


class JobIntent(Intent):
    """Job lifecycle (reference: intent/JobIntent.java)."""

    CREATED = 0
    COMPLETE = 1
    COMPLETED = 2
    TIME_OUT = 3
    TIMED_OUT = 4
    FAIL = 5
    FAILED = 6
    UPDATE_RETRIES = 7
    RETRIES_UPDATED = 8
    CANCEL = 9
    CANCELED = 10
    THROW_ERROR = 11
    ERROR_THROWN = 12
    RECUR_AFTER_BACKOFF = 13
    RECURRED_AFTER_BACKOFF = 14
    YIELD = 15
    YIELDED = 16
    UPDATE_TIMEOUT = 17
    TIMEOUT_UPDATED = 18

    _EVENT_NAMES = _nonmember(frozenset(
        {
            "CREATED",
            "COMPLETED",
            "TIMED_OUT",
            "FAILED",
            "RETRIES_UPDATED",
            "CANCELED",
            "ERROR_THROWN",
            "RECURRED_AFTER_BACKOFF",
            "YIELDED",
            "TIMEOUT_UPDATED",
        }
    ))


class JobBatchIntent(Intent):
    ACTIVATE = 0
    ACTIVATED = 1

    _EVENT_NAMES = _nonmember(frozenset({"ACTIVATED"}))


class DeploymentIntent(Intent):
    CREATE = 0
    CREATED = 1
    DISTRIBUTE = 2
    DISTRIBUTED = 3
    FULLY_DISTRIBUTED = 4

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DISTRIBUTED", "FULLY_DISTRIBUTED"}))


class DeploymentDistributionIntent(Intent):
    DISTRIBUTING = 0
    COMPLETE = 1
    COMPLETED = 2

    _EVENT_NAMES = _nonmember(frozenset({"DISTRIBUTING", "COMPLETED"}))


class ProcessIntent(Intent):
    CREATED = 0
    DELETING = 1
    DELETED = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DELETING", "DELETED"}))


class MessageIntent(Intent):
    PUBLISH = 0
    PUBLISHED = 1
    EXPIRE = 2
    EXPIRED = 3

    _EVENT_NAMES = _nonmember(frozenset({"PUBLISHED", "EXPIRED"}))


class MessageBatchIntent(Intent):
    """One record expiring N messages (reference: protocol.xml:52
    MESSAGE_BATCH, engine/…/message/MessageBatchExpireProcessor.java) — the
    TTL sweep plans one batch command instead of per-message EXPIREs."""

    EXPIRE = 0
    EXPIRED = 1

    _EVENT_NAMES = _nonmember(frozenset({"EXPIRED"}))


class MessageSubscriptionIntent(Intent):
    CREATE = 0
    CREATED = 1
    CORRELATING = 2
    CORRELATE = 3
    CORRELATED = 4
    REJECT = 5
    REJECTED = 6
    DELETE = 7
    DELETED = 8

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "CORRELATING", "CORRELATED", "REJECTED", "DELETED"}))


class ProcessMessageSubscriptionIntent(Intent):
    CREATING = 0
    CREATE = 1
    CREATED = 2
    CORRELATE = 3
    CORRELATED = 4
    DELETING = 5
    DELETE = 6
    DELETED = 7

    _EVENT_NAMES = _nonmember(frozenset({"CREATING", "CREATED", "CORRELATED", "DELETING", "DELETED"}))


class MessageStartEventSubscriptionIntent(Intent):
    CREATED = 0
    CORRELATED = 1
    DELETED = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "CORRELATED", "DELETED"}))


class TimerIntent(Intent):
    CREATED = 0
    TRIGGER = 1
    TRIGGERED = 2
    CANCELED = 3

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "TRIGGERED", "CANCELED"}))


class IncidentIntent(Intent):
    CREATED = 0
    RESOLVE = 1
    RESOLVED = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "RESOLVED"}))


class VariableIntent(Intent):
    CREATED = 0
    UPDATED = 1
    MIGRATED = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "UPDATED", "MIGRATED"}))


class VariableDocumentIntent(Intent):
    UPDATE = 0
    UPDATED = 1

    _EVENT_NAMES = _nonmember(frozenset({"UPDATED"}))


class ErrorIntent(Intent):
    CREATED = 0

    _EVENT_NAMES = _nonmember(frozenset({"CREATED"}))


class ProcessEventIntent(Intent):
    TRIGGERING = 0
    TRIGGERED = 1

    _EVENT_NAMES = _nonmember(frozenset({"TRIGGERING", "TRIGGERED"}))


class DecisionIntent(Intent):
    CREATED = 0
    DELETED = 1

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DELETED"}))


class DecisionRequirementsIntent(Intent):
    CREATED = 0
    DELETED = 1

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DELETED"}))


class DecisionEvaluationIntent(Intent):
    EVALUATED = 0
    FAILED = 1
    EVALUATE = 2  # standalone evaluation command (gateway EvaluateDecision rpc)

    _EVENT_NAMES = _nonmember(frozenset({"EVALUATED", "FAILED"}))


class EscalationIntent(Intent):
    ESCALATED = 0
    NOT_ESCALATED = 1

    _EVENT_NAMES = _nonmember(frozenset({"ESCALATED", "NOT_ESCALATED"}))


class SignalIntent(Intent):
    BROADCAST = 0
    BROADCASTED = 1

    _EVENT_NAMES = _nonmember(frozenset({"BROADCASTED"}))


class SignalSubscriptionIntent(Intent):
    CREATED = 0
    DELETED = 1

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DELETED"}))


class ResourceDeletionIntent(Intent):
    DELETE = 0
    DELETING = 1
    DELETED = 2

    _EVENT_NAMES = _nonmember(frozenset({"DELETING", "DELETED"}))


class CommandDistributionIntent(Intent):
    """Generalized command distribution lifecycle (reference:
    docs/generalized_distribution.md, intent/CommandDistributionIntent.java)."""

    STARTED = 0
    DISTRIBUTING = 1
    ACKNOWLEDGE = 2
    ACKNOWLEDGED = 3
    FINISHED = 4

    _EVENT_NAMES = _nonmember(frozenset({"STARTED", "DISTRIBUTING", "ACKNOWLEDGED", "FINISHED"}))


class CheckpointIntent(Intent):
    CREATE = 0
    CREATED = 1
    IGNORED = 2

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "IGNORED"}))


class FormIntent(Intent):
    CREATED = 0
    DELETED = 1

    _EVENT_NAMES = _nonmember(frozenset({"CREATED", "DELETED"}))


class UserTaskIntent(Intent):
    CREATING = 0
    CREATED = 1
    COMPLETE = 2
    COMPLETING = 3
    COMPLETED = 4
    CANCELING = 5
    CANCELED = 6
    ASSIGN = 7
    ASSIGNING = 8
    ASSIGNED = 9
    CLAIM = 10
    UPDATE = 11
    UPDATING = 12
    UPDATED = 13

    _EVENT_NAMES = _nonmember(frozenset(
        {
            "CREATING",
            "CREATED",
            "COMPLETING",
            "COMPLETED",
            "CANCELING",
            "CANCELED",
            "ASSIGNING",
            "ASSIGNED",
            "UPDATING",
            "UPDATED",
        }
    ))


_INTENTS_BY_VALUE_TYPE: dict[ValueType, type[Intent]] = {
    ValueType.PROCESS_INSTANCE_MIGRATION: ProcessInstanceMigrationIntent,
    ValueType.JOB: JobIntent,
    ValueType.DEPLOYMENT: DeploymentIntent,
    ValueType.PROCESS_INSTANCE: ProcessInstanceIntent,
    ValueType.INCIDENT: IncidentIntent,
    ValueType.MESSAGE: MessageIntent,
    ValueType.MESSAGE_BATCH: MessageBatchIntent,
    ValueType.MESSAGE_SUBSCRIPTION: MessageSubscriptionIntent,
    ValueType.PROCESS_MESSAGE_SUBSCRIPTION: ProcessMessageSubscriptionIntent,
    ValueType.JOB_BATCH: JobBatchIntent,
    ValueType.TIMER: TimerIntent,
    ValueType.MESSAGE_START_EVENT_SUBSCRIPTION: MessageStartEventSubscriptionIntent,
    ValueType.VARIABLE: VariableIntent,
    ValueType.VARIABLE_DOCUMENT: VariableDocumentIntent,
    ValueType.PROCESS_INSTANCE_CREATION: ProcessInstanceCreationIntent,
    ValueType.ERROR: ErrorIntent,
    ValueType.PROCESS: ProcessIntent,
    ValueType.DEPLOYMENT_DISTRIBUTION: DeploymentDistributionIntent,
    ValueType.PROCESS_EVENT: ProcessEventIntent,
    ValueType.DECISION: DecisionIntent,
    ValueType.DECISION_REQUIREMENTS: DecisionRequirementsIntent,
    ValueType.DECISION_EVALUATION: DecisionEvaluationIntent,
    ValueType.PROCESS_INSTANCE_MODIFICATION: ProcessInstanceModificationIntent,
    ValueType.ESCALATION: EscalationIntent,
    ValueType.SIGNAL: SignalIntent,
    ValueType.SIGNAL_SUBSCRIPTION: SignalSubscriptionIntent,
    ValueType.RESOURCE_DELETION: ResourceDeletionIntent,
    ValueType.COMMAND_DISTRIBUTION: CommandDistributionIntent,
    ValueType.PROCESS_INSTANCE_BATCH: ProcessInstanceBatchIntent,
    ValueType.CHECKPOINT: CheckpointIntent,
    ValueType.FORM: FormIntent,
    ValueType.USER_TASK: UserTaskIntent,
    ValueType.PROCESS_INSTANCE_RESULT: ProcessInstanceResultIntent,
}
