"""Protocol enums: record types, value types, rejection types, element types.

Mirrors the reference protocol module (reference: protocol/src/main/java/io/camunda/
zeebe/protocol/record/{RecordType,ValueType,RejectionType}.java and
value/BpmnElementType.java). Enum integer codes are part of this framework's wire
format (they also index device-side opcode tables in zeebe_tpu.ops), so they are
append-only: never renumber.
"""

from __future__ import annotations

import enum


class RecordType(enum.IntEnum):
    """Kind of a record on the stream (reference: record/RecordType.java)."""

    NULL_VAL = 0
    COMMAND = 1
    EVENT = 2
    COMMAND_REJECTION = 3


class ValueType(enum.IntEnum):
    """Discriminator for the record value payload (reference: record/ValueType.java).

    One entry per record value schema; the (RecordType, ValueType, Intent) triple
    selects the processor in the engine's RecordProcessorMap.
    """

    NULL_VAL = 0
    JOB = 1
    DEPLOYMENT = 2
    PROCESS_INSTANCE = 3
    INCIDENT = 4
    MESSAGE = 5
    MESSAGE_SUBSCRIPTION = 6
    PROCESS_MESSAGE_SUBSCRIPTION = 7
    JOB_BATCH = 8
    TIMER = 9
    MESSAGE_START_EVENT_SUBSCRIPTION = 10
    VARIABLE = 11
    VARIABLE_DOCUMENT = 12
    PROCESS_INSTANCE_CREATION = 13
    ERROR = 14
    PROCESS = 15
    DEPLOYMENT_DISTRIBUTION = 16
    PROCESS_EVENT = 17
    DECISION = 18
    DECISION_REQUIREMENTS = 19
    DECISION_EVALUATION = 20
    PROCESS_INSTANCE_MODIFICATION = 21
    ESCALATION = 22
    SIGNAL = 23
    SIGNAL_SUBSCRIPTION = 24
    RESOURCE_DELETION = 25
    COMMAND_DISTRIBUTION = 26
    PROCESS_INSTANCE_BATCH = 27
    CHECKPOINT = 28
    FORM = 29
    USER_TASK = 30
    PROCESS_INSTANCE_RESULT = 31
    PROCESS_INSTANCE_MIGRATION = 32
    MESSAGE_BATCH = 33
    SBE_UNKNOWN = 255


# the tenant every record belongs to unless stated otherwise (reference:
# TenantOwned.DEFAULT_TENANT_IDENTIFIER)
DEFAULT_TENANT = "<default>"


class RejectionType(enum.IntEnum):
    """Why a command was rejected (reference: record/RejectionType.java)."""

    NULL_VAL = 0
    INVALID_ARGUMENT = 1
    NOT_FOUND = 2
    ALREADY_EXISTS = 3
    INVALID_STATE = 4
    PROCESSING_ERROR = 5
    EXCEEDED_BATCH_RECORD_SIZE = 6


class BpmnElementType(enum.IntEnum):
    """BPMN element taxonomy (reference: record/value/BpmnElementType.java).

    The integer code doubles as the device-side element opcode: the automaton
    kernel's ``lax.switch`` over element behavior is indexed by this value
    (see zeebe_tpu.ops.automaton).
    """

    UNSPECIFIED = 0
    PROCESS = 1
    SUB_PROCESS = 2
    EVENT_SUB_PROCESS = 3
    START_EVENT = 4
    INTERMEDIATE_CATCH_EVENT = 5
    INTERMEDIATE_THROW_EVENT = 6
    BOUNDARY_EVENT = 7
    END_EVENT = 8
    SERVICE_TASK = 9
    RECEIVE_TASK = 10
    USER_TASK = 11
    MANUAL_TASK = 12
    TASK = 13
    EXCLUSIVE_GATEWAY = 14
    INCLUSIVE_GATEWAY = 15
    PARALLEL_GATEWAY = 16
    EVENT_BASED_GATEWAY = 17
    SEQUENCE_FLOW = 18
    MULTI_INSTANCE_BODY = 19
    CALL_ACTIVITY = 20
    BUSINESS_RULE_TASK = 21
    SCRIPT_TASK = 22
    SEND_TASK = 23

    @property
    def is_gateway(self) -> bool:
        return self in (
            BpmnElementType.EXCLUSIVE_GATEWAY,
            BpmnElementType.INCLUSIVE_GATEWAY,
            BpmnElementType.PARALLEL_GATEWAY,
            BpmnElementType.EVENT_BASED_GATEWAY,
        )

    @property
    def is_task(self) -> bool:
        return self in (
            BpmnElementType.SERVICE_TASK,
            BpmnElementType.RECEIVE_TASK,
            BpmnElementType.USER_TASK,
            BpmnElementType.MANUAL_TASK,
            BpmnElementType.TASK,
            BpmnElementType.BUSINESS_RULE_TASK,
            BpmnElementType.SCRIPT_TASK,
            BpmnElementType.SEND_TASK,
        )

    @property
    def is_container(self) -> bool:
        return self in (
            BpmnElementType.PROCESS,
            BpmnElementType.SUB_PROCESS,
            BpmnElementType.EVENT_SUB_PROCESS,
            BpmnElementType.MULTI_INSTANCE_BODY,
        )

    @property
    def is_job_worker_task(self) -> bool:
        """Element types implemented through jobs (reference: bpmn/task/JobWorkerTaskProcessor)."""
        return self in (
            BpmnElementType.SERVICE_TASK,
            BpmnElementType.SEND_TASK,
            BpmnElementType.BUSINESS_RULE_TASK,
            BpmnElementType.SCRIPT_TASK,
            BpmnElementType.USER_TASK,
        )


class BpmnEventType(enum.IntEnum):
    """Event trigger taxonomy (reference: record/value/BpmnEventType.java)."""

    UNSPECIFIED = 0
    NONE = 1
    MESSAGE = 2
    TIMER = 3
    ERROR = 4
    SIGNAL = 5
    ESCALATION = 6
    TERMINATE = 7
    LINK = 8
    COMPENSATION = 9


class ErrorType(enum.IntEnum):
    """Incident error types (reference: record/value/ErrorType.java)."""

    UNKNOWN = 0
    IO_MAPPING_ERROR = 1
    JOB_NO_RETRIES = 2
    CONDITION_ERROR = 3
    EXTRACT_VALUE_ERROR = 4
    UNHANDLED_ERROR_EVENT = 5
    MESSAGE_SIZE_EXCEEDED = 6
    CALLED_ELEMENT_ERROR = 7
    CALLED_DECISION_ERROR = 8
    DECISION_EVALUATION_ERROR = 9
    FORM_NOT_FOUND = 10
    EXECUTION_LISTENER_NO_RETRIES = 11


class PartitionRole(enum.IntEnum):
    """Role of a node for a partition (reference: atomix raft Role)."""

    FOLLOWER = 0
    CANDIDATE = 1
    LEADER = 2
    INACTIVE = 3
