"""At-rest storage scrubber: find bit rot before the read path trips on it
(ISSUE 14).

Detection-at-read (CRC checks on every journal/cold read, manifest checks at
recovery) bounds *served* corruption but leaves a window: a rotten frame is
only discovered when something reads it — possibly at the worst moment
(recovery, a leader transition, an exporter catching up). The scrubber
closes the window from the other side: a pump-throttled, byte-budgeted
background walk re-CRCs every at-rest artifact the partition owns —

- the **raft journal** (the replicated source of truth),
- the **stream journal** (the materialized committed prefix),
- the **snapshot chain** files (one file per slice, against the manifest),
- sealed **cold-store** segments (parked-instance frames),

— and on a mismatch immediately hands the finding to the partition's repair
seam for that target (truncate + re-converge, quarantine + re-snapshot /
re-fetch, DEGRADED + transition). Every pass, detection, and repair lands in
``zeebe_storage_scrub_*`` metrics, typed flight events, and the
``storageIntegrity`` block on partition ``/health`` (compact form on
``/cluster/status`` rows), plus a per-partition ``scrub-state.json``
evidence file the torture gate reads offline.

Honesty notes (also in docs/durability.md): scrubbing is *eventual* — rot
landing between the last pass and a read is caught at the read, not by the
scrubber; the walk covers drained file bytes (the pump thread is the only
writer, so the extent is race-free); and a repair that cannot complete yet
(no leader to re-fetch from, an idle partition that cannot take a newer
snapshot) leaves the partition DEGRADED until it can.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import zlib
from collections import deque
from pathlib import Path
from typing import Callable

from zeebe_tpu.state.snapshot import manifest_entries
from zeebe_tpu.utils.metrics import REGISTRY as _REG

_M_SCANNED = _REG.counter(
    "storage_scrub_scanned_bytes_total",
    "bytes re-CRCed by the at-rest storage scrubber", ("partition",))
_M_PASSES = _REG.counter(
    "storage_scrub_passes_total",
    "completed full scrub cycles over every target", ("partition",))
_M_CORRUPTIONS = _REG.counter(
    "storage_scrub_corruptions_total",
    "at-rest corruptions detected (by scrub or read path)",
    ("partition", "target"))
_M_REPAIRS = _REG.counter(
    "storage_scrub_repairs_total",
    "storage repairs executed (truncate/quarantine/refetch/transition)",
    ("partition", "target"))

#: target walk order; one target slice per pump pass
TARGETS = ("raft", "stream", "snapshot", "cold")


@dataclasses.dataclass
class ScrubCfg:
    """Knobs (env: ``ZEEBE_BROKER_DATA_SCRUB*``, broker/config.py)."""

    enabled: bool = True
    #: minimum ms between scrub slices (pump-throttle)
    interval_ms: int = 1_000
    #: byte budget per slice (bounds pump stall per pass)
    bytes_per_pass: int = 4 << 20


class StorageScrubber:
    """One partition's scrubber. Pump-thread only (every walk shares the
    storage owners' single-writer discipline). Holds resumable cursors per
    target and bounded detection/repair evidence rings."""

    def __init__(self, partition, cfg: ScrubCfg,
                 clock_millis: Callable[[], int]) -> None:
        self.partition = partition
        self.cfg = cfg
        self.clock_millis = clock_millis
        pid = str(partition.partition_id)
        self._m_scanned = _M_SCANNED.labels(pid)
        self._m_passes = _M_PASSES.labels(pid)
        self._target_i = 0
        self._raft_cursor = 0
        self._stream_cursor = 0
        self._cold_cursor = (0, 0)
        self._snapshot_queue: list[tuple[str, str]] = []  # (snap dir, file)
        # resumable intra-file CRC state for the snapshot walk
        self._snapshot_offset = 0
        self._snapshot_crc = 0
        self._last_run_ms = 0
        self._last_pass_ms: int | None = None
        self.scanned_bytes = 0
        self.full_passes = 0
        self.detections: deque = deque(maxlen=64)
        self.repairs: deque = deque(maxlen=64)
        #: unrepaired-corruption latch: set on detection, cleared when the
        #: repair for that target reports completion
        self.pending_repair: dict | None = None
        self._evidence_path = Path(partition.directory) / "scrub-state.json"
        self._last_evidence_ms = 0

    # -- public accounting (read by /health, the repair seams, torture) --------

    def note_corruption(self, target: str, detail: dict,
                        source: str = "scrub") -> None:
        """Record a detection — from a scrub walk OR a read path that
        tripped first (one evidence home for both detectors)."""
        pid = str(self.partition.partition_id)
        _M_CORRUPTIONS.labels(pid, target).inc()
        event = {"target": target, "source": source,
                 "atMs": self.clock_millis(), **detail}
        self.detections.append(event)
        self.pending_repair = event
        flight = self.partition.flight
        if flight is not None:
            flight.record(self.partition.partition_id, "storage_corruption",
                          **event)
        self._write_evidence(force=True)

    def note_repair(self, target: str, action: str, detail: dict,
                    complete: bool = True) -> None:
        pid = str(self.partition.partition_id)
        _M_REPAIRS.labels(pid, target).inc()
        event = {"target": target, "action": action, "complete": complete,
                 "atMs": self.clock_millis(), **detail}
        self.repairs.append(event)
        if complete:
            self.pending_repair = None
        flight = self.partition.flight
        if flight is not None:
            flight.record(self.partition.partition_id, "storage_repair",
                          **event)
            flight.dump(f"storage-repair:partition-{pid}")
        self._write_evidence(force=True)

    def status(self) -> dict:
        """The ``storageIntegrity`` block for partition ``/health``."""
        return {
            "status": "DEGRADED" if self.pending_repair is not None
                      else "HEALTHY",
            "scannedBytes": self.scanned_bytes,
            "fullPasses": self.full_passes,
            "lastFullPassMs": self._last_pass_ms,
            "corruptionsDetected": len(self.detections),
            "repairs": len(self.repairs),
            **({"pendingRepair": self.pending_repair}
               if self.pending_repair is not None else {}),
            "lastDetections": list(self.detections)[-5:],
            "lastRepairs": list(self.repairs)[-5:],
        }

    # -- the pump hook ---------------------------------------------------------

    def maybe_run(self, now_ms: int | None = None) -> int:
        now = self.clock_millis() if now_ms is None else now_ms
        if now - self._last_run_ms < self.cfg.interval_ms:
            return 0
        self._last_run_ms = now
        target = TARGETS[self._target_i]
        scanned = 0
        try:
            if target == "raft":
                scanned = self._scrub_journal(
                    self.partition.raft.journal, "raft")
            elif target == "stream":
                scanned = self._scrub_journal(
                    self.partition.stream_journal, "stream")
            elif target == "snapshot":
                scanned = self._scrub_snapshots()
            else:
                scanned = self._scrub_cold()
        except Exception:  # noqa: BLE001 — the scrubber must never take
            # the pump down; an unrepairable fault already latched FAILED /
            # DEGRADED through the repair seam's own containment
            import logging

            logging.getLogger("zeebe_tpu.broker.scrubber").exception(
                "scrub slice for %s failed on partition %s", target,
                self.partition.partition_id)
        finally:
            # a repair seam raising out of a slice must not wedge the
            # rotation on one target forever
            self._advance_target(target)
        if scanned:
            self.scanned_bytes += scanned
            self._m_scanned.inc(scanned)
        self._write_evidence()
        # a pending repair retries once per cycle (e.g. a follower waiting
        # for a leader to re-fetch its snapshot from)
        pending = self.pending_repair
        if pending is not None and pending.get("target") == "snapshot" \
                and self._target_i == 0:
            self.partition.repair_snapshot_corruption(pending)
        return 1 if scanned else 0

    def _advance_target(self, target: str) -> None:
        self._target_i = (self._target_i + 1) % len(TARGETS)
        if self._target_i == 0 and target == TARGETS[-1]:
            self.full_passes += 1
            self._last_pass_ms = self.clock_millis()
            self._m_passes.inc()

    # -- per-target walks ------------------------------------------------------

    def _scrub_journal(self, journal, target: str) -> int:
        cursor = self._raft_cursor if target == "raft" else self._stream_cursor
        next_index, scanned, corrupt = journal.scrub(
            cursor, self.cfg.bytes_per_pass)
        if next_index > journal.last_index:
            next_index = 0  # wrapped: restart from the head next slice
        if target == "raft":
            self._raft_cursor = next_index
        else:
            self._stream_cursor = next_index
        if corrupt is not None:
            self.note_corruption(target, {
                "corruptIndex": corrupt, "directory": str(journal.dir)})
            if target == "raft":
                # the repair evidence flows back through raft's
                # storage_listener → note_repair (one evidence path whether
                # the scrubber or a live read found the rot)
                self.partition.raft.repair_journal_corruption()
            else:
                self.partition.repair_stream_corruption(corrupt)
        return scanned

    def _scrub_snapshots(self) -> int:
        store = self.partition.snapshot_store
        if not self._snapshot_queue:
            # refresh the work list: every persisted snapshot's manifest
            # entries, one (dir, file) pair per slice
            for snap in store.list_snapshots():
                entries = manifest_entries(snap.path)
                if entries is None:
                    self.note_corruption("snapshot", {
                        "snapshotId": str(snap.id),
                        "file": "CHECKSUM.sfv",
                        "reason": "manifest-unreadable"})
                    self.partition.repair_snapshot_corruption(
                        {"snapshotId": str(snap.id)})
                    return 0
                for name in entries:
                    self._snapshot_queue.append((str(snap.path), name))
            if not self._snapshot_queue:
                return 0
        scanned = 0
        while self._snapshot_queue and scanned < self.cfg.bytes_per_pass:
            dirname, name = self._snapshot_queue[-1]
            path = Path(dirname) / name
            expected = manifest_entries(Path(dirname))
            if expected is None or name not in expected:
                # snapshot purged/replaced since queueing — stale entry
                self._snapshot_queue.pop()
                self._snapshot_offset = 0
                self._snapshot_crc = 0
                continue
            # resumable incremental CRC: persisted snapshot files are
            # immutable, so the rolling crc survives across slices — the
            # byte budget bounds the pump stall even for a huge state.bin
            # (file_crc in one gulp would read it all on one slice)
            done = False
            actual: int | None = None
            try:
                with open(path, "rb") as f:
                    f.seek(self._snapshot_offset)
                    while scanned < self.cfg.bytes_per_pass:
                        chunk = f.read(min(
                            1 << 20, self.cfg.bytes_per_pass - scanned))
                        if not chunk:
                            done = True
                            actual = self._snapshot_crc & 0xFFFFFFFF
                            break
                        self._snapshot_crc = zlib.crc32(
                            chunk, self._snapshot_crc)
                        self._snapshot_offset += len(chunk)
                        scanned += len(chunk)
            except OSError:
                done = True  # vanished mid-walk: unreadable = mismatch
            if not done:
                break  # budget exhausted mid-file; resume next slice
            self._snapshot_queue.pop()
            self._snapshot_offset = 0
            self._snapshot_crc = 0
            if actual != expected[name]:
                snap_id = os.path.basename(dirname)
                self.note_corruption("snapshot", {
                    "snapshotId": snap_id, "file": name,
                    "path": str(path)})
                self.partition.repair_snapshot_corruption(
                    {"snapshotId": snap_id})
                break
        return scanned

    def _scrub_cold(self) -> int:
        db = self.partition.db
        cold = getattr(db, "cold", None)
        if cold is None:
            return 0
        cursor, scanned, corruption = cold.scrub(
            self._cold_cursor, self.cfg.bytes_per_pass)
        self._cold_cursor = cursor
        if corruption is not None:
            self._cold_cursor = (0, 0)
            self.note_corruption("cold", corruption)
            self.partition.repair_cold_corruption(
                f"at-rest cold corruption: {corruption}")
        return scanned

    # -- offline evidence (the torture checker reads this) ---------------------

    def _write_evidence(self, force: bool = False) -> None:
        now = time.time() * 1000.0
        if not force and now - self._last_evidence_ms < 1000:
            return
        self._last_evidence_ms = now
        payload = {
            "partitionId": self.partition.partition_id,
            "pid": os.getpid(),
            **self.status(),
            "detections": list(self.detections),
            "repairs": list(self.repairs),
        }
        try:
            tmp = self._evidence_path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(payload), encoding="utf-8")
            os.replace(tmp, self._evidence_path)
        except OSError:  # pragma: no cover — evidence is best-effort
            pass
