"""ZeebePartition: one partition's full vertical on one broker node.

Reference: broker/src/main/java/io/camunda/zeebe/broker/system/partitions/
ZeebePartition.java:38 — an actor listening to Raft role changes and running
the transition steps (ZeebePartitionFactory.java:71-85): LogStorage → LogStream
→ ZeebeDb (recover from snapshot, StateControllerImpl.recover :74) → …
→ StreamProcessor → SnapshotDirector → ExporterDirector.

Design (tpu-native): the Raft log is the replication transport + durable
command record; the partition materializes the *committed prefix* into its
local stream journal, identically on leaders and followers, so the stream
processor, exporters, and recovery read one consistent log regardless of role.
Positions are assigned by the leader at Raft-append time (the Sequencer run
ahead of commit); entries that never commit are simply never materialized —
exactly the reference's "uncommitted entries are invisible above the log
storage" contract.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from time import perf_counter as _perf_counter
from typing import Any, Callable

from zeebe_tpu.cluster.messaging import MessagingService
from zeebe_tpu.cluster.raft import RaftNode, RaftRole
from zeebe_tpu.engine.distribution import CommandRedistributor
from zeebe_tpu.engine.engine import Engine
from zeebe_tpu.engine.message_timer import DueDateCheckers
from zeebe_tpu.exporters.director import ExporterDirector
from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.logstreams import LogAppendEntry, LogStream, patch_prepatched_batch
from zeebe_tpu.observability.tracer import get_tracer as _get_tracer
from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.msgpack import packb, unpackb
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.snapshot import FileBasedSnapshotStore
from zeebe_tpu.stream import StreamProcessor, StreamProcessorMode

DEFAULT_SNAPSHOT_PERIOD_MS = 5 * 60 * 1000

# command-ingress tracing (singleton mutated in place; one enabled-check per
# client_write when tracing is off)
_TRACER = _get_tracer()


class BackpressureExceeded(Exception):
    """Client command rejected by the in-flight limiter (maps to gRPC
    RESOURCE_EXHAUSTED at the gateway)."""


class _RaftWriter:
    """LogStreamWriter-shaped adapter the StreamProcessor writes through:
    follow-ups and scheduled commands replicate via Raft before they become
    readable (reference: AtomixLogStorage.append → LeaderRole.appendEntry)."""

    def __init__(self, partition: "ZeebePartition") -> None:
        self.partition = partition

    def try_write(self, entries, source_position: int = -1) -> int:
        result = self.partition.write_entries(list(entries), source_position)
        return result if result is not None else -1

    def append_prepatched(self, buf: bytearray, pos_offsets, ts_offsets,
                          count: int, has_pending_commands: bool = False) -> int:
        """Burst-template fast path over Raft: patch positions/timestamps into
        the pre-serialized batch, replicate the bytes (mirrors
        LogStreamWriter.append_prepatched; the committed entry materializes
        into the stream journal like any other batch)."""
        p = self.partition
        if p.role != RaftRole.LEADER:
            return -1
        first_position = p._next_position
        timestamp = p.clock_millis()
        patch_prepatched_batch(buf, pos_offsets, ts_offsets,
                               first_position, timestamp)
        if p.raft.append(bytes(buf), asqn=first_position) is None:
            return -1
        # remember the command-scan skip flag until the committed entry
        # materializes into the stream journal
        p._prepatched_flags[first_position] = has_pending_commands
        p._next_position = first_position + count
        return first_position + count - 1


class ZeebePartition:
    def __init__(
        self,
        messaging: MessagingService,
        partition_id: int,
        members: list[str],
        directory: str | Path,
        clock_millis: Callable[[], int],
        partition_count: int = 1,
        exporters_factory: Callable[[], dict[str, Any]] | None = None,
        inter_partition_sender=None,
        response_sink: Callable[[Any], None] | None = None,
        snapshot_period_ms: int = DEFAULT_SNAPSHOT_PERIOD_MS,
        priority: int = 1,
        consistency_checks: bool = True,
        backup_service=None,
        on_checkpoint=None,
        backpressure=None,
        on_jobs_available=None,
        kernel_backend_enabled: bool = True,
        mesh_runner=None,
        durable_state: bool = False,
        health_monitor=None,
        flight_recorder=None,
    ) -> None:
        self.partition_id = partition_id
        self.partition_count = partition_count
        self.directory = Path(directory)
        self.clock_millis = clock_millis
        # factory, not instances: each partition (and each transition) gets its
        # own exporter instances — a shared instance's controller would ack
        # positions into whichever partition opened it last
        self.exporters_factory = exporters_factory or (lambda: {})
        self.inter_partition_sender = inter_partition_sender
        self.response_sink = response_sink or (lambda r: None)
        self.snapshot_period_ms = snapshot_period_ms
        self.consistency_checks = consistency_checks
        self.backup_service = backup_service  # BackupService | None
        self.on_checkpoint = on_checkpoint  # broker cache-bump hook
        # jobs-available side effect: (partition_id, {job types}) → broker →
        # gateway hub (long-poll wakeup + job push dispatch)
        self.on_jobs_available = on_jobs_available
        self.kernel_backend_enabled = kernel_backend_enabled
        self.mesh_runner = mesh_runner
        self.durable_state = durable_state
        # broker health monitor (CriticalComponentsHealthMonitor | None): the
        # exporter director reports per-exporter DEGRADED/HEALTHY through it
        self.health_monitor = health_monitor
        # flight recorder (observability/flight_recorder.py | None): this
        # partition's bounded black-box ring of operational events
        self.flight = flight_recorder
        self._exporter_flight_status: dict[str, Any] = {}
        # client-ingress backpressure (CommandRateLimiter | None) and the
        # disk-monitor pause flag; both gate client_write only — follow-ups,
        # scheduled commands, and inter-partition traffic always pass
        self.limiter = backpressure
        self.paused = False        # admin pause (BrokerAdminService)
        self.disk_paused = False   # disk watermark pause — independent source

        self.snapshot_store = FileBasedSnapshotStore(self.directory / "snapshots")
        self.raft = RaftNode(
            messaging, partition_id, members, self.directory / "raft",
            clock_millis, priority=priority,
        )
        self.raft.commit_listeners.append(self._on_raft_commit)
        self.raft.role_listeners.append(self._on_role_change)
        self.raft.snapshot_provider = self._provide_install_snapshot
        self.raft.snapshot_receiver = self._receive_install_snapshot

        self._stream_dir = self.directory / "stream"
        self.stream_journal = SegmentedJournal(self._stream_dir)
        self.stream = LogStream(self.stream_journal, partition_id, clock=clock_millis)

        self.role = RaftRole.FOLLOWER
        self.db: ZbDb | None = None
        self.engine: Engine | None = None
        self.processor: StreamProcessor | None = None
        self.exporter_director: ExporterDirector | None = None
        self.checkers: DueDateCheckers | None = None
        self.redistributor: CommandRedistributor | None = None
        self._applied_raft_index = 0
        # asqn → has_pending_commands for burst batches appended via
        # append_prepatched (consumed at materialization)
        self._prepatched_flags: dict[int, bool] = {}
        self._latest_checkpoint = 0
        self._next_position = self.stream.last_position + 1
        self._last_snapshot_ms = clock_millis()
        self._transition()  # start as follower (replay mode)
        # catch up on whatever the raft log already committed before we wired
        self._materialize_committed()

    # -- raft integration ------------------------------------------------------

    def _on_raft_commit(self, commit_index: int) -> None:
        self._materialize_committed()

    def _materialize_committed(self) -> None:
        """Append newly committed raft entries' payloads to the stream journal."""
        for entry in self.raft.committed_entries(self._applied_raft_index + 1):
            self._applied_raft_index = entry["index"]
            if entry.get("init") or not entry.get("data"):
                continue
            self.stream.append_committed_payload(
                entry["data"], entry["asqn"],
                has_pending_commands=self._prepatched_flags.pop(entry["asqn"], None),
            )
            if self.flight is not None:
                # last-K committed-batch summaries (one ring entry per BATCH,
                # not per record — count is the payload's leading u32)
                import struct as _struct

                self.flight.record(
                    self.partition_id, "records", first=entry["asqn"],
                    count=_struct.unpack_from("<I", entry["data"], 0)[0])
        self._next_position = max(self._next_position, self.stream.last_position + 1)

    def _on_role_change(self, role: RaftRole, term: int) -> None:
        self.role = role
        if self.flight is not None:
            self.flight.record(self.partition_id, "role_change",
                               role=role.value, term=term)
        self._transition()

    # -- transition steps (reference: PartitionTransitionImpl) -----------------

    def _transition(self) -> None:
        """Tear down and rebuild the processing vertical for the current role:
        recover db from the latest snapshot, replay the stream journal, then
        process (leader) or keep replaying (follower)."""
        self._recover_db()
        # flags for appends that never committed under the previous role must
        # not leak onto a NEW leader's batch at a reused position (raft may
        # have truncated ours) — wrong flags make the command scan skip real
        # commands
        self._prepatched_flags.clear()
        # state migrations run between snapshot recovery and the stream
        # processor opening (reference: MigrationTransitionStep →
        # DbMigratorImpl.runMigrations)
        from zeebe_tpu.engine.migration import DbMigrator

        DbMigrator(self.db).run_migrations()
        mode = (
            StreamProcessorMode.PROCESSING
            if self.role == RaftRole.LEADER else StreamProcessorMode.REPLAY
        )
        self.engine = Engine(
            self.db, self.partition_id, clock_millis=self.clock_millis,
            partition_count=self.partition_count,
        )
        # per-transition query façade (reference: QueryServiceTransitionStep —
        # closed and replaced with the db on every role change)
        from zeebe_tpu.engine.query import QueryService

        if getattr(self, "query_service", None) is not None:
            self.query_service.close()
        self.query_service = QueryService(self.db, self.engine.state)
        if self.inter_partition_sender is not None:
            self.engine.wire_sender(self.inter_partition_sender)
        kernel_backend = None
        if self.kernel_backend_enabled and mode == StreamProcessorMode.PROCESSING:
            # the partition's batched execution backend (BASELINE.json north
            # star): groups of kernel-eligible commands ride the device;
            # construction is lazy — no device work until a candidate arrives
            from zeebe_tpu.engine.kernel_backend import KernelBackend

            kernel_backend = KernelBackend(self.engine, max_group=2048,
                                           chunk_steps=8,
                                           mesh_runner=self.mesh_runner)
        self.processor = StreamProcessor(
            self.stream, self.db, self.engine, mode=mode,
            response_sink=self.response_sink, clock_millis=self.clock_millis,
            writer=_RaftWriter(self),
            kernel_backend=kernel_backend,
        )
        if self.on_jobs_available is not None:
            listener = self.on_jobs_available
            self.processor.on_jobs_available = (
                lambda types, pid=self.partition_id: listener(pid, types)
            )
        self.processor.start()
        self.checkers = DueDateCheckers(
            self.engine.state, self.processor.schedule_service, self.clock_millis
        )
        self.redistributor = CommandRedistributor(
            self.engine.state, self.engine.sender,
            self.processor.schedule_service, self.clock_millis,
        )
        if self.exporter_director is not None:
            self.exporter_director.close()  # flush partial bulks, run Exporter.close
        if self.health_monitor is not None:
            # fresh containers know nothing of the old ones' failures: a
            # stale DEGRADED report must not outlive the director it came
            # from (the new director re-reports on its own first failure)
            self.health_monitor.deregister_matching(
                f"partition-{self.partition_id}.exporter-")
        self.exporter_director = ExporterDirector(
            self.stream, self.db, self.exporters_factory(),
            clock_millis=self.clock_millis,
            on_health=self._report_exporter_health,
        )
        self.engine.checkpoint.listeners.append(self._on_checkpoint_created)
        # lock-free checkpoint-id cache: refreshed here on the owner thread
        # and bumped by the applier hook on BOTH leader processing and
        # follower replay (the cross-partition send path reads it without
        # touching this db)
        self.engine.appliers.on_checkpoint_applied = self._observe_checkpoint_applied
        with self.db.transaction():
            self._latest_checkpoint = self.engine.checkpoint_state.latest_id()
        if self.role == RaftRole.LEADER:
            # leader sequencer continues after the last position in the raft
            # log (committed or not — uncommitted entries still own positions)
            self._next_position = max(
                self._next_position, self._last_raft_position() + 1
            )

    def _recover_db(self) -> None:
        """StateControllerImpl.recover: latest valid snapshot → runtime db.

        Durable mode: the on-disk delta log (state/durable.py) recovers to
        its last checkpoint in O(bytes); a full snapshot from the store only
        overrides it when NEWER (a received raft INSTALL persisted one)."""
        if self.durable_state:
            from zeebe_tpu.state import ColumnFamilyCode
            from zeebe_tpu.state.durable import DurableZbDb

            if isinstance(self.db, DurableZbDb):
                self.db.close()
            db = DurableZbDb.open(self.directory / "state",
                                  consistency_checks=self.consistency_checks)
            snapshot = self.snapshot_store.latest_snapshot()
            if snapshot is not None:
                try:
                    state_bin = snapshot.read_file("state.bin")
                except (FileNotFoundError, OSError):
                    state_bin = None  # durable-marker snapshot: disk is current
                if state_bin is not None:
                    snap_processed = unpackb(
                        snapshot.read_file("meta.bin")).get("lastProcessed", -1)
                    durable_processed = db.committed_get(
                        ColumnFamilyCode.LAST_PROCESSED_POSITION, ("last",))
                    if snap_processed > (durable_processed
                                         if durable_processed is not None else -1):
                        db.install_snapshot_bytes(state_bin)
            self.db = db
            return
        snapshot = self.snapshot_store.latest_snapshot()
        if snapshot is not None:
            try:
                state_bin = snapshot.read_file("state.bin")
            except (FileNotFoundError, OSError):
                state_bin = None
            if state_bin is None:
                # durable-marker snapshot (taken while the DURABLESTATE flag
                # was on) with the flag now OFF: recover from the durable
                # disk this once — the next snapshot writes state.bin and
                # the migration back to in-memory completes (flag must stay
                # reversible; reference config flags are)
                from zeebe_tpu.state.durable import DurableZbDb

                self.db = DurableZbDb.open(
                    self.directory / "state",
                    consistency_checks=self.consistency_checks)
                return
            self.db = ZbDb.from_snapshot_bytes(
                state_bin,
                consistency_checks=self.consistency_checks,
            )
        else:
            self.db = ZbDb(consistency_checks=self.consistency_checks)

    def _last_raft_position(self) -> int:
        """Highest stream position assigned in the raft log (scan the suffix
        after the materialized prefix; usually empty or tiny)."""
        last = self.stream.last_position
        for rec in self.raft.journal.read_from(self._applied_raft_index + 1):
            entry = unpackb(rec.data)
            if entry.get("init") or not entry.get("data"):
                continue
            # count of records in the batch payload is the first u32
            import struct

            count = struct.unpack_from("<I", entry["data"], 0)[0]
            last = max(last, entry["asqn"] + count - 1)
        return last

    # -- command ingress (CommandApiRequestHandler equivalent) -----------------

    def client_write(self, record: Record) -> int | None:
        """Client API ingress: backpressure + pause gate, then the normal
        write path (reference: CommandApiRequestHandler.handleExecuteCommand —
        rate limiter check before LogStreamWriter.tryWrite)."""
        if self.paused or self.disk_paused:
            return None
        tracer = _TRACER
        # capture the enabled flag once: a mid-flight configure_tracing must
        # not pair a real perf_counter with the 0.0 sentinel
        traced = tracer.enabled
        t0 = _perf_counter() if traced else 0.0
        if self.limiter is not None and not self.limiter.try_acquire(record):
            if self.flight is not None:
                # on the rejection (exception) path only — never on admits
                self.flight.record(
                    self.partition_id, "backpressure_reject",
                    limit=self.limiter.limit,
                    valueType=record.value_type.name)
            raise BackpressureExceeded(
                f"partition {self.partition_id} has reached its in-flight "
                f"command limit ({self.limiter.limit})"
            )
        t_acquired = _perf_counter() if traced else 0.0
        position = self.write_commands([record])
        if position is not None and self.limiter is not None:
            self.limiter.on_appended(position)
        if traced and position is not None:
            # the Raft path bypasses the local LogStreamWriter, so the ack
            # stamp is taken here; the trace root is the command's own
            # position — the same id the processor and exporter spans use
            tracer.note_append(self.partition_id, position)
            trace_id = f"{self.partition_id}:{position}"
            if tracer.sampled(trace_id):
                if self.limiter is not None:
                    tracer.emit(trace_id, "broker.backpressure_acquire",
                                t_acquired - t0, self.partition_id,
                                attrs={"position": position})
                tracer.emit(trace_id, "broker.command_append",
                            _perf_counter() - t_acquired, self.partition_id,
                            attrs={"position": position,
                                   "valueType": record.value_type.name,
                                   "intent": record.intent.name})
        return position

    def write_commands(self, records: list[Record],
                       source_position: int = -1) -> int | None:
        """Leader-only: sequence the records and append to Raft; they become
        processable once committed. Returns the last assigned position."""
        return self.write_entries([LogAppendEntry(r) for r in records],
                                  source_position)

    def write_entries(self, entries: list[LogAppendEntry],
                      source_position: int = -1) -> int | None:
        if self.role != RaftRole.LEADER or not entries:
            return None
        first_position = self._next_position
        payload = self.stream.serialize_batch(entries, first_position, source_position)
        index = self.raft.append(payload, asqn=first_position)
        if index is None:
            return None
        self._next_position = first_position + len(entries)
        return first_position + len(entries) - 1

    # -- pump (the actor loop, driven by the broker) ---------------------------

    def pump(self) -> int:
        """Advance processing/replay, scheduled work, and exporters."""
        work = 0
        if self.processor is None:
            return work
        if self.role == RaftRole.LEADER and self.processor.phase.value == "processing":
            work += self.processor.run_until_idle()
            self.checkers.reschedule()
            self.redistributor.reschedule()
            due = self.processor.schedule_service.next_due_millis
            if due is not None and due <= self.clock_millis():
                work += 1  # scheduled commands were written; next pump processes
        else:
            work += self.processor.replay_available()
        work += self.exporter_director.export_available()
        if self.limiter is not None and self.limiter.in_flight:
            processed = self.processor.last_processed_position
            for position in [p for p in self.limiter.in_flight if p <= processed]:
                self.limiter.on_processed(position)
        self._maybe_snapshot()
        return work

    # -- snapshotting (AsyncSnapshotDirector equivalent) -----------------------

    def _maybe_snapshot(self) -> None:
        now = self.clock_millis()
        if now - self._last_snapshot_ms < self.snapshot_period_ms:
            return
        self._last_snapshot_ms = now
        self.take_snapshot()

    def take_snapshot(self) -> bool:
        """Snapshot the db at lastProcessedPosition, then compact both logs up
        to min(processed, exported) (reference: AsyncSnapshotDirector.java:37 —
        wait for commit, persist, then Raft compacts)."""
        if self.processor is None or self.db is None:
            return False
        processed = self.processor.last_processed_position
        if processed < 0:
            return False
        # the reference waits until lastWrittenPosition is committed before
        # persisting (AsyncSnapshotDirector): our materialized stream journal
        # IS the committed prefix, so written-but-unmaterialized means wait
        if self.processor.last_written_position > self.stream.last_position:
            return False
        import time as _time

        from zeebe_tpu.utils.metrics import REGISTRY

        snapshot_started = _time.perf_counter()
        exported = self.exporter_director.lowest_exporter_position()
        term = self.raft.current_term
        raft_index = self.raft.journal.seek_to_asqn(processed)
        if raft_index <= 0:
            raft_index = self.raft.snapshot_index
        try:
            transient = self.snapshot_store.new_transient_snapshot(
                raft_index, term, processed, exported if exported < 2**62 else processed
            )
        except Exception:
            return False  # not newer than the latest snapshot
        if self.durable_state:
            # O(delta): fsync the durable delta log + manifest; the snapshot
            # entry only carries bookkeeping (positions for recovery-ordering
            # and the raft compaction boundary) — reference: RocksDB
            # checkpoints are hard links, not value copies
            manifest = self.db.checkpoint()
            transient.write_file("durable.bin", packb({"manifest": manifest}))
        else:
            transient.write_file("state.bin", self.db.to_snapshot_bytes())
        transient.write_file("meta.bin", packb({
            "lastProcessed": processed,
            "lastPosition": self.stream.last_position,
        }))
        persist_started = _time.perf_counter()
        snapshot = transient.persist()
        pid = str(self.partition_id)
        REGISTRY.counter(
            "snapshot_count", "snapshots persisted", ("partition",)
        ).labels(pid).inc()
        elapsed = _time.perf_counter() - snapshot_started
        REGISTRY.histogram(
            "snapshot_duration_seconds", "time to persist a snapshot",
            ("partition",)
        ).labels(pid).observe(elapsed)
        REGISTRY.histogram(
            "snapshot_duration", "time to take+persist a snapshot, seconds",
            ("partition",)).labels(pid).observe(elapsed)
        REGISTRY.histogram(
            "snapshot_persist_duration",
            "time to persist the transient snapshot, seconds",
            ("partition",)).labels(pid).observe(
            _time.perf_counter() - persist_started)
        try:
            size = 0
            chunks = 0
            for f in snapshot.path.rglob("*"):
                if f.is_file():
                    size += f.stat().st_size
                    chunks += 1
            REGISTRY.gauge(
                "snapshot_size_bytes", "bytes of the latest snapshot",
                ("partition",)).labels(pid).set(size)
            REGISTRY.gauge(
                "snapshot_file_size_megabytes",
                "megabytes of the latest snapshot", ("partition",)
            ).labels(pid).set(size / 1e6)
            REGISTRY.gauge(
                "snapshot_chunks_count",
                "files in the latest snapshot", ("partition",)
            ).labels(pid).set(chunks)
        except OSError:
            pass
        # raft log compaction bound: nothing above the snapshot index, nothing
        # unexported, nothing unmaterialized
        compact_position = min(processed, exported)
        compact_index = self.raft.journal.seek_to_asqn(compact_position)
        if compact_index > 1:
            # the snapshot boundary's term is the term of the entry it replaces
            # (not the current term) or _entry_term answers wrongly at the
            # boundary and replication backs up into a needless snapshot install
            boundary_term = self.raft.entry_term(compact_index - 1)
            # durable mode: no state.bin exists and the install payload is
            # built LIVE by the snapshot_provider — pass None so raft skips
            # the send entirely when the provider declines (b"" would ship a
            # torn install: journal reset + unpackb crash on the receiver)
            self.raft.set_snapshot(
                compact_index - 1, boundary_term,
                None if self.durable_state else self._install_payload(snapshot),
            )
        return True

    # -- snapshot replication (leader → lagging follower) ----------------------

    def _install_payload(self, snapshot) -> bytes:
        return packb({
            "state": snapshot.read_file("state.bin"),
            "meta": snapshot.read_file("meta.bin"),
        })

    def _provide_install_snapshot(self):
        if self.durable_state:
            # build the payload live from the durable store (rare path: a
            # follower fell behind the compacted log). Meta must describe the
            # LIVE state dump, not the last checkpoint — the receiver aligns
            # its stream to meta.lastPosition and the state's own
            # lastProcessed marker
            if self.db is None or self.processor is None or self.db.in_transaction:
                return None
            return (self.raft.snapshot_index, self.raft.snapshot_term, packb({
                "state": self.db.to_snapshot_bytes(),
                "meta": packb({
                    "lastProcessed": self.processor.last_processed_position,
                    "lastPosition": self.stream.last_position,
                }),
            }))
        snapshot = self.snapshot_store.latest_snapshot()
        if snapshot is None:
            return None
        return (self.raft.snapshot_index, self.raft.snapshot_term,
                self._install_payload(snapshot))

    def _receive_install_snapshot(self, data: bytes) -> None:
        """Follower fell behind the leader's compacted log: replace local state
        wholesale (reference: PassiveRole + FileBasedReceivedSnapshot →
        StateControllerImpl recover)."""
        payload = unpackb(data)
        meta = unpackb(payload["meta"])
        # persist locally so restart recovers from it
        try:
            transient = self.snapshot_store.new_transient_snapshot(
                self.raft.snapshot_index, self.raft.snapshot_term,
                meta["lastProcessed"], meta["lastProcessed"],
            )
            transient.write_file("state.bin", payload["state"])
            transient.write_file("meta.bin", payload["meta"])
            transient.persist()
        except Exception:
            pass  # not newer than what we have
        # reset the stream journal past the snapshot and rebuild the vertical
        self.stream_journal.close()
        shutil.rmtree(self._stream_dir, ignore_errors=True)
        self.stream_journal = SegmentedJournal(self._stream_dir)
        self.stream = LogStream(self.stream_journal, self.partition_id,
                                clock=self.clock_millis)
        self.stream._next_position = meta["lastPosition"] + 1
        self._next_position = meta["lastPosition"] + 1
        self._transition()

    # -- lifecycle -------------------------------------------------------------

    def tick(self) -> None:
        self.raft.tick()

    def close(self) -> None:
        if self.exporter_director is not None:
            self.exporter_director.close()
        self.raft.close()
        self.stream_journal.close()
        if self.durable_state and self.db is not None:
            from zeebe_tpu.state.durable import DurableZbDb

            if isinstance(self.db, DurableZbDb):
                self.db.close()

    def hard_crash(self) -> None:
        """Power-loss crash simulation (chaos harness flush-boundary fault):
        unlike ``close``, nothing flushes — both journals discard every byte
        not covered by an fsync (buffered appends AND file bytes written
        since the last flush), exactly what surviving hardware would hold
        after losing power between a buffered append and its covering flush.
        Exporters/state are simply abandoned; recovery rebuilds them."""
        self.raft.journal.simulate_power_loss()
        self.stream_journal.simulate_power_loss()

    def latest_checkpoint_id(self) -> int:
        """Lock-free: read by OTHER partitions' ownership threads on every
        inter-partition send — must never open this partition's db (the owner
        thread may be mid-transaction). The cache refreshes at transition and
        on every checkpoint-created apply."""
        return self._latest_checkpoint

    def _observe_checkpoint_applied(self, checkpoint_id: int) -> None:
        self._latest_checkpoint = max(self._latest_checkpoint, checkpoint_id)
        if self.on_checkpoint is not None:
            # broker-level cache (max over local replicas) follows along —
            # on followers too, which the processing listener never covers
            self.on_checkpoint(checkpoint_id)

    def _on_checkpoint_created(self, checkpoint_id: int, position: int) -> None:
        self._latest_checkpoint = max(self._latest_checkpoint, checkpoint_id)
        if self.on_checkpoint is not None:
            self.on_checkpoint(checkpoint_id)
        if self.backup_service is not None:
            self.backup_service.take_backup(self, checkpoint_id, position)

    def _report_exporter_health(self, exporter_id: str, status,
                                message: str = "") -> None:
        """Per-exporter health sub-component under this partition (a backing-
        off exporter degrades the broker without taking the partition down)."""
        if (self.flight is not None
                and self._exporter_flight_status.get(exporter_id) != status):
            # transitions only: a backing-off exporter re-reports DEGRADED on
            # every retry, which would crowd everything else out of the ring
            self._exporter_flight_status[exporter_id] = status
            self.flight.record(self.partition_id, "exporter_state",
                               exporter=exporter_id, status=status.name,
                               message=message)
        if self.health_monitor is not None:
            self.health_monitor.report(
                f"partition-{self.partition_id}.exporter-{exporter_id}",
                status, message)

    @property
    def is_leader(self) -> bool:
        return self.role == RaftRole.LEADER

    def health(self) -> dict:
        return {
            "partitionId": self.partition_id,
            "role": self.role.value,
            "term": self.raft.current_term,
            "commitIndex": self.raft.commit_index,
            "lastPosition": self.stream.last_position,
            "lastProcessed": self.processor.last_processed_position
            if self.processor else -1,
        }
