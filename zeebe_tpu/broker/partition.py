"""ZeebePartition: one partition's full vertical on one broker node.

Reference: broker/src/main/java/io/camunda/zeebe/broker/system/partitions/
ZeebePartition.java:38 — an actor listening to Raft role changes and running
the transition steps (ZeebePartitionFactory.java:71-85): LogStorage → LogStream
→ ZeebeDb (recover from snapshot, StateControllerImpl.recover :74) → …
→ StreamProcessor → SnapshotDirector → ExporterDirector.

Design (tpu-native): the Raft log is the replication transport + durable
command record; the partition materializes the *committed prefix* into its
local stream journal, identically on leaders and followers, so the stream
processor, exporters, and recovery read one consistent log regardless of role.
Positions are assigned by the leader at Raft-append time (the Sequencer run
ahead of commit); entries that never commit are simply never materialized —
exactly the reference's "uncommitted entries are invisible above the log
storage" contract.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from time import perf_counter as _perf_counter
from typing import Any, Callable

from zeebe_tpu.cluster.messaging import MessagingService
from zeebe_tpu.cluster.raft import RaftNode, RaftRole
from zeebe_tpu.engine.distribution import CommandRedistributor
from zeebe_tpu.engine.engine import Engine
from zeebe_tpu.engine.message_timer import DueDateCheckers
from zeebe_tpu.exporters.director import ExporterDirector
from zeebe_tpu.journal import SegmentedJournal
from zeebe_tpu.journal.journal import CorruptedJournalError
from zeebe_tpu.state.tiering import ColdCorruptionError
from zeebe_tpu.logstreams import LogAppendEntry, LogStream, patch_prepatched_batch
from zeebe_tpu.observability.tracer import get_tracer as _get_tracer
from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.msgpack import packb, unpackb
from zeebe_tpu.state import ZbDb
from zeebe_tpu.state.snapshot import (
    DELTA_FILE,
    STATE_FILE,
    FileBasedSnapshotStore,
    load_chain_db,
)
from zeebe_tpu.stream import Phase as _Phase
from zeebe_tpu.stream import StreamProcessor, StreamProcessorMode
from zeebe_tpu.utils.metrics import REGISTRY as _REG

DEFAULT_SNAPSHOT_PERIOD_MS = 5 * 60 * 1000
# recovery-time budget (ISSUE 6): recoveries slower than this increment the
# exceeded counter (default alert rule recovery_budget_exceeded) and the
# snapshot scheduler snapshots early when projected replay debt threatens it
DEFAULT_RECOVERY_BUDGET_MS = 60_000
# max base+delta chain length before the next snapshot rebases to a full one
DEFAULT_SNAPSHOT_CHAIN_LENGTH = 8
# replay throughput assumed before the first measured recovery (records/s);
# deliberately conservative so the adaptive scheduler errs toward snapshotting
DEFAULT_REPLAY_RATE_RPS = 10_000.0
# snapshot early once projected replay time passes this fraction of the budget
REPLAY_DEBT_BUDGET_FRACTION = 0.5

# command-ingress tracing (singleton mutated in place; one enabled-check per
# client_write when tracing is off)
_TRACER = _get_tracer()

# recovery-budget plane metrics (module-level so the families exist from
# first partition construction — the metrics-doc scenario and the sampler
# both see them without waiting for a slow recovery)
_M_RECOVERY_DURATION = _REG.histogram(
    "recovery_duration_seconds",
    "seconds to rebuild a partition's vertical (snapshot install + replay) "
    "on a restart or role transition", ("partition",),
    buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 15, 60, 300))
_M_RECOVERY_REPLAYED = _REG.counter(
    "recovery_replay_records_total",
    "records replayed during partition recoveries", ("partition",))
_M_RECOVERY_SNAPSHOT_AGE = _REG.gauge(
    "recovery_snapshot_age_records",
    "records between the recovered snapshot's processed position and the "
    "log end at recovery time", ("partition",))
_M_RECOVERY_EXCEEDED = _REG.counter(
    "recovery_budget_exceeded_total",
    "recoveries that blew recovery_budget_ms", ("partition",))
_M_SNAPSHOT_KIND = _REG.counter(
    "snapshot_kind_total", "snapshots persisted by kind (full/delta/durable)",
    ("partition", "kind"))
_M_SNAPSHOT_CHAIN_LEN = _REG.gauge(
    "snapshot_chain_length",
    "length of the latest snapshot chain (1 = full snapshot)", ("partition",))
_M_REPLAY_DEBT = _REG.gauge(
    "snapshot_replay_debt_records",
    "records appended since the latest snapshot (recovery replay upper "
    "bound)", ("partition",))
_M_ADAPTIVE_SNAPSHOTS = _REG.counter(
    "snapshot_adaptive_triggers_total",
    "snapshots taken early because projected replay debt threatened the "
    "recovery budget", ("partition",))
_M_SNAPSHOT_WRITE_FAILURES = _REG.counter(
    "snapshot_write_failures_total",
    "snapshots aborted by a storage write/fsync fault (ISSUE 14); the "
    "previous valid chain stays the recovery anchor", ("partition",))
# replicated request dedupe (ISSUE 9): ingress consults the materialized
# table before appending — a hit suppresses a duplicate append, a replay
# re-sends the stored reply for an already-answered request
_M_DEDUPE_HITS = _REG.counter(
    "request_dedupe_hits_total",
    "resent requests recognized as in flight or processed (duplicate "
    "append suppressed)", ("partition",))
_M_DEDUPE_REPLAYS = _REG.counter(
    "request_dedupe_replays_total",
    "resent requests answered by replaying the stored reply from the "
    "replicated dedupe table", ("partition",))


class BackpressureExceeded(Exception):
    """Client command rejected by the in-flight limiter (maps to gRPC
    RESOURCE_EXHAUSTED at the gateway)."""


class _RaftWriter:
    """LogStreamWriter-shaped adapter the StreamProcessor writes through:
    follow-ups and scheduled commands replicate via Raft before they become
    readable (reference: AtomixLogStorage.append → LeaderRole.appendEntry)."""

    def __init__(self, partition: "ZeebePartition") -> None:
        self.partition = partition

    def try_write(self, entries, source_position: int = -1) -> int:
        result = self.partition.write_entries(list(entries), source_position)
        return result if result is not None else -1

    def append_prepatched(self, buf: bytearray, pos_offsets, ts_offsets,
                          count: int, has_pending_commands: bool = False) -> int:
        """Burst-template fast path over Raft: patch positions/timestamps into
        the pre-serialized batch, replicate the bytes (mirrors
        LogStreamWriter.append_prepatched; the committed entry materializes
        into the stream journal like any other batch)."""
        p = self.partition
        if p.role != RaftRole.LEADER:
            return -1
        first_position = p._next_position
        timestamp = p.clock_millis()
        patch_prepatched_batch(buf, pos_offsets, ts_offsets,
                               first_position, timestamp)
        if p.raft.append(bytes(buf), asqn=first_position) is None:
            return -1
        # remember the command-scan skip flag until the committed entry
        # materializes into the stream journal
        p._prepatched_flags[first_position] = has_pending_commands
        p._next_position = first_position + count
        return first_position + count - 1


class ZeebePartition:
    def __init__(
        self,
        messaging: MessagingService,
        partition_id: int,
        members: list[str],
        directory: str | Path,
        clock_millis: Callable[[], int],
        partition_count: int = 1,
        exporters_factory: Callable[[], dict[str, Any]] | None = None,
        inter_partition_sender=None,
        response_sink: Callable[[Any], None] | None = None,
        snapshot_period_ms: int = DEFAULT_SNAPSHOT_PERIOD_MS,
        priority: int = 1,
        consistency_checks: bool = True,
        backup_service=None,
        on_checkpoint=None,
        backpressure=None,
        on_jobs_available=None,
        kernel_backend_enabled: bool = True,
        mesh_runner=None,
        durable_state: bool = False,
        health_monitor=None,
        flight_recorder=None,
        recovery_budget_ms: int = DEFAULT_RECOVERY_BUDGET_MS,
        snapshot_chain_length: int = DEFAULT_SNAPSHOT_CHAIN_LENGTH,
        tiering=None,
        log_flush_delay_ms: int = 0,
        log_max_unflushed_bytes: int = 1 << 20,
        scrub=None,
    ) -> None:
        self.partition_id = partition_id
        self.partition_count = partition_count
        self.directory = Path(directory)
        self.clock_millis = clock_millis
        # factory, not instances: each partition (and each transition) gets its
        # own exporter instances — a shared instance's controller would ack
        # positions into whichever partition opened it last
        self.exporters_factory = exporters_factory or (lambda: {})
        self.inter_partition_sender = inter_partition_sender
        self.response_sink = response_sink or (lambda r: None)
        self.snapshot_period_ms = snapshot_period_ms
        self.consistency_checks = consistency_checks
        self.backup_service = backup_service  # BackupService | None
        self.on_checkpoint = on_checkpoint  # broker cache-bump hook
        # jobs-available side effect: (partition_id, {job types}) → broker →
        # gateway hub (long-poll wakeup + job push dispatch)
        self.on_jobs_available = on_jobs_available
        self.kernel_backend_enabled = kernel_backend_enabled
        self.mesh_runner = mesh_runner
        self.durable_state = durable_state
        # state tiering (ISSUE 8): cold parked-instance store config
        # (state/tiering.py TieringCfg | None). Durable state supersedes it —
        # the durable backend has its own hot/cold residency story.
        self.tiering_cfg = tiering if (tiering is not None
                                       and getattr(tiering, "enabled", False)
                                       and not durable_state) else None
        self.tiering = None  # TieringManager | None, built per transition
        # broker health monitor (CriticalComponentsHealthMonitor | None): the
        # exporter director reports per-exporter DEGRADED/HEALTHY through it
        self.health_monitor = health_monitor
        # flight recorder (observability/flight_recorder.py | None): this
        # partition's bounded black-box ring of operational events
        self.flight = flight_recorder
        # latency observatory (ISSUE 19): windowed worst-N ack exemplars +
        # bounded critical_path flight events; built per transition so the
        # hook always points at the live processor
        self.latency_observatory = None
        self._exporter_flight_status: dict[str, Any] = {}
        # client-ingress backpressure (CommandRateLimiter | None) and the
        # disk-monitor pause flag; both gate client_write only — follow-ups,
        # scheduled commands, and inter-partition traffic always pass
        self.limiter = backpressure
        self.paused = False        # admin pause (BrokerAdminService)
        self.disk_paused = False   # disk watermark pause — independent source

        # recovery-budget plane (ISSUE 6): budget knob, incremental-snapshot
        # chain state, and the last recovery's observations (served on
        # /health and asserted by the soak harness)
        self.recovery_budget_ms = recovery_budget_ms
        self.snapshot_chain_length = max(1, snapshot_chain_length)
        self.last_recovery: dict | None = None
        # leader replay barrier: raft position materialization+replay must
        # reach before processing may start (None = no barrier pending);
        # the flag records a budget blown WHILE the barrier was pending so
        # the eventual _record_recovery doesn't double-count it
        self._replay_barrier: int | None = None
        self._barrier_budget_flagged = False
        self._recovery_started = 0.0
        self._snapshot_anchor = None   # chain-tip SnapshotId deltas build on
        self._chain_len = 0
        self._last_snapshot_processed = -1
        self._observed_replay_rate = DEFAULT_REPLAY_RATE_RPS
        self._last_debt_check_ms = 0
        # adaptive snapshot triggers this life (the control plane's
        # snapshot-scheduler loop row reads it without a registry scrape)
        self.adaptive_snapshot_count = 0
        # compaction-bound memo keyed by the newest snapshot id: chain
        # validation re-reads and CRCs every chain member (the base is the
        # whole state), and the guards run several times per snapshot — only
        # a new persist can change the store's answer in-process, so cache
        # until the newest id moves (crash-tampering always restarts the
        # partition, which rebuilds this object)
        self._compact_bound_memo: tuple = (None, -1)

        self.snapshot_store = FileBasedSnapshotStore(self.directory / "snapshots")
        self.raft = RaftNode(
            messaging, partition_id, members, self.directory / "raft",
            clock_millis, priority=priority,
            # group-commit pacing static defaults (ISSUE 12): runtime
            # mutation of raft.flush_interval_s belongs to the journal-flush
            # controller's actuator exclusively
            flush_interval_s=max(log_flush_delay_ms, 0) / 1000.0,
            max_unflushed_bytes=log_max_unflushed_bytes,
        )
        self.raft.commit_listeners.append(self._on_raft_commit)
        self.raft.role_listeners.append(self._on_role_change)
        self.raft.snapshot_provider = self._provide_install_snapshot
        self.raft.snapshot_receiver = self._receive_install_snapshot
        # compaction safety: segment deletion in EITHER journal is clamped to
        # min(latest snapshot position, all exporter container cursors) —
        # enforced below every caller, inside the journals themselves
        self.raft.journal.compact_guard = self._raft_compact_guard

        self._stream_dir = self.directory / "stream"
        self.stream_journal = SegmentedJournal(self._stream_dir)
        self.stream_journal.compact_guard = self._stream_compact_guard
        self.stream = LogStream(self.stream_journal, partition_id, clock=clock_millis)

        self.role = RaftRole.FOLLOWER
        self.db: ZbDb | None = None
        self.engine: Engine | None = None
        self.processor: StreamProcessor | None = None
        self.exporter_director: ExporterDirector | None = None
        self.checkers: DueDateCheckers | None = None
        self.redistributor: CommandRedistributor | None = None
        self._applied_raft_index = 0
        # asqn → has_pending_commands for burst batches appended via
        # append_prepatched (consumed at materialization)
        self._prepatched_flags: dict[int, bool] = {}
        self._latest_checkpoint = 0
        self._next_position = self.stream.last_position + 1
        self._last_snapshot_ms = clock_millis()
        # replicated dedupe, leader-side in-memory half: (stream id, request
        # id) → position of APPENDED-but-unprocessed client commands. The
        # dedupe column family only learns a request at processing time;
        # this map covers the append→process window and is REBUILT from the
        # materialized log on every leader transition, so a restarted leader
        # (or a promoted follower) still refuses to double-append a resend
        # that races recovery.
        self._pending_requests: dict[tuple[int, int], int] = {}
        # storage-fault plane (ISSUE 14): at-rest scrubber + repair seams.
        # ``scrub`` is a ScrubCfg | None; the scrubber survives transitions
        # (cursors and evidence are partition-lifetime) and reads the LIVE
        # journals/stores through `self` each slice.
        self.scrubber = None
        if scrub is not None and getattr(scrub, "enabled", False):
            from zeebe_tpu.broker.scrubber import StorageScrubber

            self.scrubber = StorageScrubber(self, scrub, clock_millis)
        self.raft.storage_listener = self._on_raft_storage_event
        # repair-loop guard: target -> monotonic time of last repair; a
        # second repair of the same target within the window means the
        # fault is not repairable by that seam — fail the processor instead
        # of looping the partition through endless rebuilds
        self._last_storage_repair: dict[str, float] = {}
        self._transition()  # start as follower (replay mode)
        # catch up on whatever the raft log already committed before we wired
        self._materialize_committed()

    # -- raft integration ------------------------------------------------------

    def _on_raft_commit(self, commit_index: int) -> None:
        self._materialize_committed()

    def _materialize_committed(self) -> None:
        """Append newly committed raft entries' payloads to the stream journal."""
        for entry in self.raft.committed_entries(self._applied_raft_index + 1):
            self._applied_raft_index = entry["index"]
            if entry.get("init") or not entry.get("data"):
                continue
            self.stream.append_committed_payload(
                entry["data"], entry["asqn"],
                has_pending_commands=self._prepatched_flags.pop(entry["asqn"], None),
            )
            if self.flight is not None:
                # last-K committed-batch summaries (one ring entry per BATCH,
                # not per record — count is the payload's leading u32)
                import struct as _struct

                self.flight.record(
                    self.partition_id, "records", first=entry["asqn"],
                    count=_struct.unpack_from("<I", entry["data"], 0)[0])
        self._next_position = max(self._next_position, self.stream.last_position + 1)

    def _on_role_change(self, role: RaftRole, term: int) -> None:
        self.role = role
        if self.flight is not None:
            self.flight.record(self.partition_id, "role_change",
                               role=role.value, term=term)
        self._transition()

    # -- transition steps (reference: PartitionTransitionImpl) -----------------

    def _transition(self) -> None:
        """Tear down and rebuild the processing vertical for the current role:
        recover db from the latest snapshot chain, replay the stream journal,
        then process (leader) or keep replaying (follower). The whole
        rebuild is timed against ``recovery_budget_ms`` (ISSUE 6): duration,
        replay length, and snapshot age land in the metrics plane and the
        flight recorder."""
        recovery_start = _perf_counter()
        self._pending_requests.clear()  # rebuilt below for leaders
        self._replay_barrier = None  # a re-transition supersedes any barrier
        # ...and so does its blown-budget flag: left set, it would suppress
        # the exceeded counter for this (distinct) rebuild's own verdict
        self._barrier_budget_flagged = False
        self._recover_db()
        # flags for appends that never committed under the previous role must
        # not leak onto a NEW leader's batch at a reused position (raft may
        # have truncated ours) — wrong flags make the command scan skip real
        # commands
        self._prepatched_flags.clear()
        # state migrations run between snapshot recovery and the stream
        # processor opening (reference: MigrationTransitionStep →
        # DbMigratorImpl.runMigrations)
        from zeebe_tpu.engine.migration import DbMigrator

        DbMigrator(self.db).run_migrations()
        mode = (
            StreamProcessorMode.PROCESSING
            if self.role == RaftRole.LEADER else StreamProcessorMode.REPLAY
        )
        self.engine = Engine(
            self.db, self.partition_id, clock_millis=self.clock_millis,
            partition_count=self.partition_count,
        )
        # per-transition query façade (reference: QueryServiceTransitionStep —
        # closed and replaced with the db on every role change)
        from zeebe_tpu.engine.query import QueryService

        if getattr(self, "query_service", None) is not None:
            self.query_service.close()
        self.query_service = QueryService(self.db, self.engine.state)
        if self.inter_partition_sender is not None:
            self.engine.wire_sender(self.inter_partition_sender)
        kernel_backend = None
        if self.kernel_backend_enabled and mode == StreamProcessorMode.PROCESSING:
            # the partition's batched execution backend (BASELINE.json north
            # star): groups of kernel-eligible commands ride the device;
            # construction is lazy — no device work until a candidate arrives
            from zeebe_tpu.engine.kernel_backend import KernelBackend

            kernel_backend = KernelBackend(self.engine, max_group=2048,
                                           chunk_steps=8,
                                           mesh_runner=self.mesh_runner)
        self.processor = StreamProcessor(
            self.stream, self.db, self.engine, mode=mode,
            response_sink=self.response_sink, clock_millis=self.clock_millis,
            writer=_RaftWriter(self),
            kernel_backend=kernel_backend,
        )
        if kernel_backend is not None and self.flight is not None:
            # bounded per-wave path accounting into the black box (ISSUE
            # 13): ≤1 kernel_wave event/s with the wave-size / chunk /
            # path-split / dominant-fallback aggregate since the last one
            self.processor.wave_listener = (
                lambda event, pid=self.partition_id:
                self.flight.record(pid, "kernel_wave", **event))
            # device health audit sink (ISSUE 15): the process-wide ladder's
            # transitions (control_adjust + device_health events) and typed
            # device_fault evidence land in this broker's flight recorder
            kernel_backend.health.flight_sink = (self.flight,
                                                 self.partition_id)
        if self.on_jobs_available is not None:
            listener = self.on_jobs_available
            self.processor.on_jobs_available = (
                lambda types, pid=self.partition_id: listener(pid, types)
            )
        if self.flight is not None:
            # slow-exemplar capture (ISSUE 19): the N worst acked traces per
            # window dump their span trees through the flight recorder, and
            # a bounded critical_path event carries the window's top stages
            # (→ /cluster/status → `cli top` LATENCY section). Zero cost
            # while tracing is off — the ack hook only fires under the
            # tracer's enabled guard.
            from zeebe_tpu.observability.critical_path import (
                LatencyObservatory,
            )

            self.latency_observatory = LatencyObservatory(
                _TRACER, self.flight, self.partition_id)
            self.processor.on_ack = self.latency_observatory.observe
        self.processor.start()
        self.checkers = DueDateCheckers(
            self.engine.state, self.processor.schedule_service, self.clock_millis
        )
        if self.tiering_cfg is not None:
            # fresh manager per transition over the fresh db (the seams —
            # park_listener/woken_listener — rewire to it); replay feeds it
            # on followers too, so a promoted follower spills immediately
            from zeebe_tpu.state.tiering import TieringManager

            self.tiering = TieringManager(
                self.db, self.clock_millis, self.tiering_cfg,
                partition_id=self.partition_id)
        self.redistributor = CommandRedistributor(
            self.engine.state, self.engine.sender,
            self.processor.schedule_service, self.clock_millis,
        )
        if self.exporter_director is not None:
            self.exporter_director.close()  # flush partial bulks, run Exporter.close
        if self.health_monitor is not None:
            # fresh containers know nothing of the old ones' failures: a
            # stale DEGRADED report must not outlive the director it came
            # from (the new director re-reports on its own first failure)
            self.health_monitor.deregister_matching(
                f"partition-{self.partition_id}.exporter-")
        self.exporter_director = ExporterDirector(
            self.stream, self.db, self.exporters_factory(),
            clock_millis=self.clock_millis,
            on_health=self._report_exporter_health,
        )
        self.engine.checkpoint.listeners.append(self._on_checkpoint_created)
        # lock-free checkpoint-id cache: refreshed here on the owner thread
        # and bumped by the applier hook on BOTH leader processing and
        # follower replay (the cross-partition send path reads it without
        # touching this db)
        self.engine.appliers.on_checkpoint_applied = self._observe_checkpoint_applied
        with self.db.transaction():
            self._latest_checkpoint = self.engine.checkpoint_state.latest_id()
        if self.role == RaftRole.LEADER:
            # leader sequencer continues after the last position in the raft
            # log (committed or not — uncommitted entries still own positions)
            raft_end = self._last_raft_position()
            self._next_position = max(self._next_position, raft_end + 1)
            if (raft_end > self.stream.last_position
                    and self.processor.phase != _Phase.FAILED):
                # (a FAILED processor — poison record contained during
                # start()'s replay — must STAY failed: flipping it to REPLAY
                # here would re-attempt the poison batch on the next pump)
                # REPLAY BARRIER (ISSUE 6): the raft log holds entries not
                # yet re-materialized into the stream journal (a power loss
                # wiped the derived journal's unfsynced bytes, or this
                # leader was elected before its commit index recovered).
                # Processing now would RE-process client commands whose
                # result events only exist in the unmaterialized suffix —
                # duplicating their effects (instances created twice). Hold
                # the processor in REPLAY until materialization + replay
                # reach the barrier (leader completeness guarantees every
                # entry in our log eventually commits); pump() flips to
                # PROCESSING and finalizes the recovery accounting there.
                self._replay_barrier = raft_end
                self._recovery_started = recovery_start
                self._barrier_budget_flagged = False
                self.processor.phase = _Phase.REPLAY
                return
        if self.role == RaftRole.LEADER:
            self._rebuild_pending_requests()
        self._record_recovery(_perf_counter() - recovery_start,
                              self.processor.replayed_records)

    def _finish_leader_recovery(self) -> None:
        """Replay barrier cleared: the stream re-materialized through the
        raft log end known at election and replay applied it. Processing
        starts exactly where an uninterrupted recovery would have — after
        the last command whose events are reflected in state."""
        self._replay_barrier = None
        processor = self.processor
        processor.phase = _Phase.PROCESSING
        # commands between last_processed and the barrier that never got
        # processed pre-crash still need processing: scan from the front of
        # the unreplayed suffix (the command scan skips processed ones)
        processor._reader_position = (
            1 if processor.last_processed_position < 0
            else processor.last_processed_position + 1
        )
        self._rebuild_pending_requests()
        self._record_recovery(_perf_counter() - self._recovery_started,
                              processor.replayed_records)

    # -- recovery accounting (recovery-time budget, ISSUE 6) -------------------

    def _record_recovery(self, duration_s: float, replayed: int) -> None:
        pid = str(self.partition_id)
        age = max(
            self.stream.last_position - max(self._last_snapshot_processed, 0),
            0)
        duration_ms = duration_s * 1000.0
        budget = self.recovery_budget_ms
        within = budget <= 0 or duration_ms <= budget
        _M_RECOVERY_DURATION.labels(pid).observe(duration_s)
        _M_RECOVERY_REPLAYED.labels(pid).inc(replayed)
        _M_RECOVERY_SNAPSHOT_AGE.labels(pid).set(float(age))
        if replayed >= 64 and duration_s > 0:
            # measured replay throughput feeds the adaptive snapshot
            # scheduler's replay-debt projection
            self._observed_replay_rate = max(replayed / duration_s, 1.0)
        info = {
            "role": self.role.value,
            "durationMs": round(duration_ms, 3),
            "replayRecords": replayed,
            "snapshotId": (str(self._snapshot_anchor)
                           if self._snapshot_anchor is not None else None),
            "chainLength": self._chain_len,
            "snapshotAgeRecords": age,
            "budgetMs": budget,
            "withinBudget": within,
            "atMs": self.clock_millis(),
        }
        self.last_recovery = info
        if not within and not self._barrier_budget_flagged:
            # (already counted at the barrier the moment the budget blew)
            _M_RECOVERY_EXCEEDED.labels(pid).inc()
        self._barrier_budget_flagged = False
        if self.flight is not None:
            self.flight.record(self.partition_id, "recovery", **info)
            # every recovery leaves a reviewable artifact while the event is
            # still in the ring (per-batch records evict it fast under
            # load). Leader recoveries (the time-to-leader number the budget
            # is about) and blown budgets always force a dump; follower
            # transitions ride the 5s per-reason-class throttle
            self.flight.dump(
                f"recovery:partition-{pid}",
                force=not within or self.role == RaftRole.LEADER)

    # -- compaction safety gate ------------------------------------------------

    def _compaction_position_bound(self) -> int:
        """Highest stream position whose records may be deleted: covered by
        the latest persisted snapshot AND acknowledged by every exporter
        container (a DEGRADED/backing-off exporter pins this until it
        recovers — its growing ``exporter_container_lag_records`` gauge is
        the observable). -1 = nothing is compactable."""
        latest = self.snapshot_store.latest_snapshot()
        if latest is None:
            return -1
        memo_id, bound = self._compact_bound_memo
        if memo_id != latest.id:
            # the newest VALID chain's tip, not the newest directory: a torn
            # tip (power loss during commit) will be skipped by recovery,
            # which then needs the log back to the chain it actually falls
            # back to
            chain = self.snapshot_store.latest_valid_chain()
            bound = -1 if chain is None else chain[-1].id.processed_position
            self._compact_bound_memo = (latest.id, bound)
        if bound < 0:
            return -1
        director = getattr(self, "exporter_director", None)
        if director is not None:
            bound = min(bound, director.lowest_exporter_position())
        return bound

    def _raft_compact_guard(self) -> int:
        bound = self._compaction_position_bound()
        if bound < 0:
            return 0
        return max(self.raft.journal.seek_to_asqn(bound), 0)

    def _stream_compact_guard(self) -> int:
        bound = self._compaction_position_bound()
        if bound < 0:
            return 0
        return max(self.stream_journal.seek_to_asqn(bound), 0)

    def _recover_db(self) -> None:
        """StateControllerImpl.recover: newest fully-valid snapshot *chain*
        (base + deltas) → runtime db, falling back chain by chain on
        corruption — a torn tip (power loss during commit) recovers from its
        last fully-valid ancestor instead of crashing.

        Durable mode: the on-disk delta log (state/durable.py) recovers to
        its last checkpoint in O(bytes); a snapshot chain from the store only
        overrides it when NEWER (a received raft INSTALL persisted one)."""
        self._snapshot_anchor = None
        self._chain_len = 0
        self._last_snapshot_processed = -1
        from zeebe_tpu.state.tiering import TieredZbDb

        if isinstance(self.db, TieredZbDb):
            # release the previous life's cold segments/fds; the new store
            # wipes the directory on open (cold is a cache tier — durability
            # lives in the chain + log)
            self.db.close()
        if self.durable_state:
            from zeebe_tpu.state import ColumnFamilyCode
            from zeebe_tpu.state.durable import DurableZbDb

            if isinstance(self.db, DurableZbDb):
                self.db.close()
            db = DurableZbDb.open(self.directory / "state",
                                  consistency_checks=self.consistency_checks)
            chain = self.snapshot_store.latest_valid_chain()
            state_bin = None
            if chain is not None and chain[0].has_file("state.bin"):
                # a received raft INSTALL persisted a full snapshot — or the
                # DURABLESTATE flag was just flipped ON over a non-durable
                # delta chain: materialize it so nothing is lost
                try:
                    if len(chain) == 1:
                        state_bin = chain[0].read_file("state.bin")
                    else:
                        state_bin = load_chain_db(chain).to_snapshot_bytes()
                except (OSError, ValueError):
                    state_bin = None
            if state_bin is not None:
                snap_processed = unpackb(
                    chain[-1].read_file("meta.bin")).get("lastProcessed", -1)
                durable_processed = db.committed_get(
                    ColumnFamilyCode.LAST_PROCESSED_POSITION, ("last",))
                if snap_processed > (durable_processed
                                     if durable_processed is not None else -1):
                    db.install_snapshot_bytes(state_bin)
            self.db = db
            return
        for chain in self.snapshot_store.iter_valid_chains():
            base, tip = chain[0], chain[-1]
            if not base.has_file("state.bin"):
                if base.has_file("durable.bin"):
                    # durable-marker snapshot (taken while the DURABLESTATE
                    # flag was on) with the flag now OFF: recover from the
                    # durable disk this once — the next snapshot writes
                    # state.bin and the migration back to in-memory completes
                    # (flag must stay reversible; reference config flags are)
                    from zeebe_tpu.state.durable import DurableZbDb

                    self.db = DurableZbDb.open(
                        self.directory / "state",
                        consistency_checks=self.consistency_checks)
                    return
                continue
            try:
                db = load_chain_db(chain,
                                   consistency_checks=self.consistency_checks,
                                   db=self._new_memory_db())
            except (OSError, ValueError):
                continue  # corruption the manifest missed: next-older chain
            self.db = db
            db.begin_delta_tracking()
            self._snapshot_anchor = tip.id
            self._chain_len = len(chain)
            try:
                self._last_snapshot_processed = unpackb(
                    tip.read_file("meta.bin")).get(
                    "lastProcessed", tip.id.processed_position)
            except (OSError, ValueError):
                self._last_snapshot_processed = tip.id.processed_position
            # the chain we just validated and loaded IS the recovery
            # anchor: prime the compaction-bound memo so the first guard
            # pass doesn't re-CRC it (keyed on the newest DIR — if a
            # newer broken-chain dir exists the key misses and the guard
            # conservatively re-walks)
            self._compact_bound_memo = (tip.id, tip.id.processed_position)
            return
        db = self._new_memory_db()
        db.begin_delta_tracking()
        self.db = db

    def _new_memory_db(self) -> ZbDb:
        """An empty in-memory-rooted store for recovery to install into:
        tiered (cold parked-instance store under ``<partition>/cold``) when
        tiering is on, the plain dict store otherwise."""
        if self.tiering_cfg is not None:
            from zeebe_tpu.state.tiering import TieredZbDb

            return TieredZbDb(
                self.directory / "cold",
                consistency_checks=self.consistency_checks,
                segment_max_bytes=self.tiering_cfg.segment_max_bytes,
                partition_id=self.partition_id)
        return ZbDb(consistency_checks=self.consistency_checks)

    def _last_raft_position(self) -> int:
        """Highest stream position assigned in the raft log (scan the suffix
        after the materialized prefix; usually empty or tiny)."""
        last = self.stream.last_position
        for rec in self.raft.journal.read_from(self._applied_raft_index + 1):
            entry = unpackb(rec.data)
            if entry.get("init") or not entry.get("data"):
                continue
            # count of records in the batch payload is the first u32
            import struct

            count = struct.unpack_from("<I", entry["data"], 0)[0]
            last = max(last, entry["asqn"] + count - 1)
        return last

    # -- command ingress (CommandApiRequestHandler equivalent) -----------------

    def _rebuild_pending_requests(self) -> None:
        """Re-derive the append→process request window from the materialized
        log: unprocessed client commands carrying a request id, scanned from
        the suffix after last-processed. Runs at leader transitions (after
        the replay barrier, when one was pending — the stream is complete
        through the election-time raft end by then)."""
        self._pending_requests.clear()
        if self.processor is None:
            return
        start = max(self.processor.last_processed_position + 1, 1)
        for logged in self.stream.new_reader(start):
            rec = logged.record
            if rec.is_command and not logged.processed and rec.request_id >= 0:
                self._pending_requests[
                    (rec.request_stream_id, rec.request_id)] = logged.position

    def _note_pending_request(self, record: Record, position: int) -> None:
        if record.request_id < 0:
            return
        pending = self._pending_requests
        pending[(record.request_stream_id, record.request_id)] = position
        while len(pending) > 65536:
            # oldest-first eviction keeps dedupe live for recent traffic
            # (an evicted request falls back to the dedupe column family
            # once processed — only its unprocessed window is uncovered)
            del pending[next(iter(pending))]

    @property
    def ready_for_ingress(self) -> bool:
        """Leader actively processing (replay barrier cleared): only then is
        the pending-request window complete enough for exactly-once ingress
        dedupe. A leader mid-recovery answers ``unavailable`` instead — it
        did NOT append, so the gateway may safely retry."""
        return (self.role == RaftRole.LEADER
                and self.processor is not None
                and self.processor.phase == _Phase.PROCESSING)

    def lookup_request(self, stream_id: int, request_id: int):
        """Replicated-dedupe ingress consult (committed-read discipline; the
        worker ingress handler runs on the pump thread between
        transactions). Returns ``("replied", entry)`` when a stored reply
        can be replayed, ``("pending", {"c": position})`` when the request
        is appended or processed-awaiting (do NOT append again; the reply
        arrives from processing), or None (unknown: append)."""
        if request_id < 0:
            return None
        key = (stream_id, request_id)
        position = self._pending_requests.get(key)
        if position is not None:
            if (self.processor is not None
                    and position <= self.processor.last_processed_position):
                # graduated to the dedupe column family at processing time
                del self._pending_requests[key]
            else:
                self._observe_dedupe("hit", request_id, position)
                return ("pending", {"c": position})
        if self.db is None or self.db.in_transaction:
            return None
        from zeebe_tpu.state.request_dedupe import RequestDedupeState

        entry = RequestDedupeState.lookup_committed(self.db, stream_id,
                                                    request_id)
        if entry is None:
            return None
        if entry.get("f"):
            self._observe_dedupe("replay", request_id, entry["c"])
            return ("replied", entry)
        self._observe_dedupe("hit", request_id, entry["c"])
        return ("pending", entry)

    def _observe_dedupe(self, kind: str, request_id: int,
                        position: int) -> None:
        pid = str(self.partition_id)
        if kind == "replay":
            _M_DEDUPE_REPLAYS.labels(pid).inc()
        else:
            _M_DEDUPE_HITS.labels(pid).inc()
        if self.flight is not None:
            self.flight.record(self.partition_id, "request_dedupe",
                               result=kind, requestId=request_id,
                               commandPosition=position)

    def client_write(self, record: Record) -> int | None:
        """Client API ingress: backpressure + pause gate, then the normal
        write path (reference: CommandApiRequestHandler.handleExecuteCommand —
        rate limiter check before LogStreamWriter.tryWrite)."""
        if self.paused or self.disk_paused:
            return None
        tracer = _TRACER
        # capture the enabled flag once: a mid-flight configure_tracing must
        # not pair a real perf_counter with the 0.0 sentinel
        traced = tracer.enabled
        t0 = _perf_counter() if traced else 0.0
        if self.limiter is not None and not self.limiter.try_acquire(record):
            if self.flight is not None:
                # on the rejection (exception) path only — never on admits
                self.flight.record(
                    self.partition_id, "backpressure_reject",
                    limit=self.limiter.limit,
                    valueType=record.value_type.name)
            raise BackpressureExceeded(
                f"partition {self.partition_id} has reached its in-flight "
                f"command limit ({self.limiter.limit})"
            )
        t_acquired = _perf_counter() if traced else 0.0
        position = self.write_commands([record])
        if position is not None:
            self._note_pending_request(record, position)
            if self.limiter is not None:
                self.limiter.on_appended(position)
        if traced and position is not None:
            # the Raft path bypasses the local LogStreamWriter, so the ack
            # stamp is taken here; the trace root is the command's own
            # position — the same id the processor and exporter spans use
            tracer.note_append(self.partition_id, position)
            trace_id = f"{self.partition_id}:{position}"
            if tracer.sampled(trace_id):
                if self.limiter is not None:
                    tracer.emit(trace_id, "broker.backpressure_acquire",
                                t_acquired - t0, self.partition_id,
                                attrs={"position": position})
                tracer.emit(trace_id, "broker.command_append",
                            _perf_counter() - t_acquired, self.partition_id,
                            attrs={"position": position,
                                   "valueType": record.value_type.name,
                                   "intent": record.intent.name})
        return position

    def client_write_batch(self, records: list[Record]
                           ) -> list[tuple[str, int]]:
        """Batched client ingress (the worker's coalescing window, ISSUE
        12): every record passes the SAME backpressure/pause gates as
        :meth:`client_write`, then the admitted ones append as ONE raft
        entry — one fsync, one replication round, positions assigned
        contiguously. Returns per-record ``(status, position)`` where
        status is ``"ok"`` | ``"backpressure"`` | ``"unavailable"``."""
        if self.paused or self.disk_paused:
            return [("unavailable", -1)] * len(records)
        results: list[tuple[str, int]] = [("unavailable", -1)] * len(records)
        admitted: list[tuple[int, Record]] = []
        # provisional count: the limiter's in_flight set only grows at
        # on_appended (after the batch appends), so without it every
        # record in the batch would be admitted against the same stale
        # count and one open window could overshoot the adaptive limit by
        # the whole batch size — exactly under the overload that made the
        # window open
        provisional = 0
        for i, record in enumerate(records):
            if self.limiter is not None and not self.limiter.try_acquire(
                    record, provisional=provisional):
                if self.flight is not None:
                    self.flight.record(
                        self.partition_id, "backpressure_reject",
                        limit=self.limiter.limit,
                        valueType=record.value_type.name)
                results[i] = ("backpressure", -1)
            else:
                provisional += 1
                admitted.append((i, record))
        if not admitted:
            return results
        tracer = _TRACER
        traced = tracer.enabled
        t_append = _perf_counter() if traced else 0.0
        last = self.write_commands([r for _, r in admitted])
        if last is None:
            # role lost between the gate and the append: same evidence as
            # client_write returning None (the gateway retries typed)
            return results
        append_dur = (_perf_counter() - t_append) if traced else 0.0
        first = last - len(admitted) + 1
        for offset, (i, record) in enumerate(admitted):
            position = first + offset
            results[i] = ("ok", position)
            self._note_pending_request(record, position)
            if self.limiter is not None:
                self.limiter.on_appended(position)
            if traced:
                tracer.note_append(self.partition_id, position)
                # PR 17's coalesced ingress made this path span-blind: every
                # record in the batch waited the whole one-raft-entry append,
                # so each sampled trace gets the full window (batched=true
                # marks the shared cost for the throughput-minded reader)
                trace_id = f"{self.partition_id}:{position}"
                if tracer.sampled(trace_id):
                    tracer.emit(trace_id, "broker.command_append",
                                append_dur, self.partition_id,
                                attrs={"position": position,
                                       "valueType": record.value_type.name,
                                       "intent": record.intent.name,
                                       "batched": True,
                                       "batchSize": len(admitted)})
        return results

    def write_commands(self, records: list[Record],
                       source_position: int = -1) -> int | None:
        """Leader-only: sequence the records and append to Raft; they become
        processable once committed. Returns the last assigned position."""
        return self.write_entries([LogAppendEntry(r) for r in records],
                                  source_position)

    def write_entries(self, entries: list[LogAppendEntry],
                      source_position: int = -1) -> int | None:
        if self.role != RaftRole.LEADER or not entries:
            return None
        first_position = self._next_position
        payload = self.stream.serialize_batch(entries, first_position, source_position)
        on_commit = None
        if _TRACER.enabled:
            on_commit = self._replicate_span_cb(first_position, len(entries),
                                                source_position)
        index = self.raft.append(payload, asqn=first_position,
                                 on_commit=on_commit)
        if index is None:
            return None
        self._next_position = first_position + len(entries)
        return first_position + len(entries) - 1

    def _replicate_span_cb(self, first_position: int, count: int,
                           source_position: int):
        """Closure for ``raft.append(on_commit=...)``: fires once at quorum
        and emits one ``raft.replicate`` span per distinct sampled ROOT trace
        covered by the entry (append→quorum wall time — the replication wait
        the PR 17 span set could not see). Capped at 256 records per entry,
        far above any client batch (≤128), so a pathological internal batch
        cannot turn a quorum callback into a span storm."""
        tracer = _TRACER
        partition_id = self.partition_id
        t_append = _perf_counter()

        def _on_commit(_index: int) -> None:
            if not tracer.enabled:
                return
            dur = _perf_counter() - t_append
            emitted: set[str] = set()
            for i in range(min(count, 256)):
                position = first_position + i
                fallback = source_position if source_position >= 0 else position
                root = tracer.resolve_root(partition_id, position, fallback)
                trace_id = f"{partition_id}:{root}"
                if trace_id in emitted or not tracer.sampled(trace_id):
                    continue
                emitted.add(trace_id)
                # `position` names the raft entry (its first record): one
                # root trace legitimately waits on several entries (command
                # append, then its follow-up records), and each wait is a
                # distinct span — the entry position is its identity.
                tracer.emit(trace_id, "raft.replicate", dur, partition_id,
                            parent="processor.ack",
                            attrs={"position": first_position,
                                   "entries": count})

        return _on_commit

    # -- pump (the actor loop, driven by the broker) ---------------------------

    def pump(self) -> int:
        """Advance processing/replay, scheduled work, and exporters.

        Storage-fault containment (ISSUE 14): the typed corruption errors
        the read paths raise — a cold-store CRC mismatch on fault-in, a
        stream-journal checksum mismatch under replay/export — are caught
        HERE, above the stream processor's blanket failure containment, and
        routed to their repair seams instead of poisoning the pump or
        failing the partition."""
        try:
            return self._pump_inner()
        except ColdCorruptionError as exc:
            self.repair_cold_corruption(str(exc))
            return 1
        except CorruptedJournalError as exc:
            if (exc.path is not None
                    and str(exc.path).startswith(str(self.raft.journal.dir))):
                # raft-journal rot surfaced through a pump-side read (e.g.
                # a compaction-guard seek): raft owns that repair, and its
                # storage_listener records the repair evidence
                if self.scrubber is not None:
                    self.scrubber.note_corruption(
                        "raft", {"corruptIndex": exc.index}, source="read")
                self.raft.repair_journal_corruption(exc)
                return 1
            if self.scrubber is not None:
                self.scrubber.note_corruption(
                    "stream", {"corruptIndex": exc.index}, source="read")
            self.repair_stream_corruption(exc.index)
            return 1

    def _pump_inner(self) -> int:
        work = 0
        if self.processor is None:
            return work
        if self.role == RaftRole.LEADER and self.processor.phase.value == "processing":
            work += self.processor.run_until_idle()
            self.checkers.reschedule()
            self.redistributor.reschedule()
            due = self.processor.schedule_service.next_due_millis
            if due is not None and due <= self.clock_millis():
                work += 1  # scheduled commands were written; next pump processes
        else:
            work += self.processor.replay_available()
            if self.checkers is not None:
                # followers never sweep, but their wheel (fed by replay)
                # must still drop spent deadlines or it grows with every
                # due date ever applied; throttled inside maybe_advance
                self.checkers.maybe_advance_wheel(self.clock_millis())
            if (self._replay_barrier is not None
                    and self.role == RaftRole.LEADER
                    and self.processor.phase == _Phase.REPLAY):
                if self.stream.last_position >= self._replay_barrier:
                    self._finish_leader_recovery()
                elif (self.recovery_budget_ms > 0
                      and not self._barrier_budget_flagged
                      and (_perf_counter() - self._recovery_started) * 1000.0
                      > self.recovery_budget_ms):
                    # the WORST recoveries are ones that never finish (a
                    # barrier stuck on a lost quorum): blow the budget the
                    # moment it is blown, not when/if the barrier clears —
                    # the exceeded counter drives the CRITICAL default alert
                    self._barrier_budget_flagged = True
                    _M_RECOVERY_EXCEEDED.labels(str(self.partition_id)).inc()
        work += self.exporter_director.export_available()
        if self.limiter is not None and self.limiter.in_flight:
            processed = self.processor.last_processed_position
            for position in [p for p in self.limiter.in_flight if p <= processed]:
                self.limiter.on_processed(position)
        self._maybe_snapshot()
        if self.tiering is not None:
            # between transactions by construction: processing/replay above
            # has drained, snapshots never hold a transaction open
            self.tiering.maybe_run()
        if self.scrubber is not None:
            # at-rest integrity walk (ISSUE 14): throttled, byte-budgeted,
            # between transactions like tiering
            self.scrubber.maybe_run()
        return work

    # -- storage-fault repair seams (ISSUE 14) ---------------------------------

    def _storage_repair_ok(self, target: str) -> bool:
        """Repair-loop guard: the same target repairing twice inside the
        window means the fault is not repairable by that seam — contain it
        like a poison record (processor FAILED, partition unhealthy) instead
        of looping the partition through endless rebuilds."""
        now = _perf_counter()
        last = self._last_storage_repair.get(target, -60.0)
        self._last_storage_repair[target] = now
        if now - last < 5.0:
            if self.processor is not None:
                self.processor.phase = _Phase.FAILED
            if self.flight is not None:
                self.flight.record(self.partition_id, "storage_repair",
                                   target=target, action="gave-up",
                                   complete=False)
                self.flight.dump(f"storage-giveup:partition-"
                                 f"{self.partition_id}", force=True)
            return False
        return True

    def _on_raft_storage_event(self, event: str, detail: dict) -> None:
        """Raft's storage_listener: corruption repairs and fsync failures
        land in the flight recorder (and the scrubber's evidence, which
        the torture gate reads offline)."""
        if event == "journal_repair":
            if self.scrubber is not None:
                self.scrubber.note_repair("raft", "truncate-reconverge",
                                          detail)
            elif self.flight is not None:
                self.flight.record(self.partition_id, "storage_repair",
                                   target="raft",
                                   action="truncate-reconverge", **detail)
        elif event == "journal_unrepairable":
            # the raft repair seam is looping on a fault it cannot fix:
            # contain like a poison record — raft deliberately never raises
            # (its callers are rpc handlers and tick(), whose escape path
            # is the worker's whole poll loop)
            if self.processor is not None:
                self.processor.phase = _Phase.FAILED
            if self.flight is not None:
                self.flight.record(self.partition_id, "storage_repair",
                                   target="raft", action="gave-up",
                                   complete=False, **detail)
                self.flight.dump(f"storage-giveup:partition-"
                                 f"{self.partition_id}", force=True)
        elif self.flight is not None:
            self.flight.record(self.partition_id, "storage_error", **detail)

    def repair_stream_corruption(self, corrupt_index: int | None = None
                                 ) -> dict:
        """Stream-journal corruption repair: the materialized log is DERIVED
        from the raft log, so the repair is truncate-at-the-corrupt-frame +
        re-materialize. The raft compaction guard keeps every record any
        exporter still needs (and everything above the snapshot) in the
        raft log, so the refill is always sufficient: records that can no
        longer be refilled are exactly the ones snapshot + exporter cursors
        already covered."""
        if not self._storage_repair_ok("stream"):
            return {}
        evidence = self.stream_journal.repair_corruption()
        surviving_asqn = self.stream_journal.last_asqn
        # rebuild the LogStream over the repaired journal (its in-memory
        # position maps still describe the truncated suffix)
        self.stream = LogStream(self.stream_journal, self.partition_id,
                                clock=self.clock_millis)
        self.stream_journal.compact_guard = self._stream_compact_guard
        # rewind the applied raft index to the last surviving batch so
        # materialization re-appends the lost suffix from the raft log
        self._applied_raft_index = (
            self.raft.journal.seek_to_asqn(surviving_asqn)
            if surviving_asqn > 0 else 0)
        self._next_position = self.stream.last_position + 1
        evidence.update({"journal": "stream",
                         "corruptIndex": corrupt_index,
                         "rewoundRaftIndex": self._applied_raft_index})
        self._materialize_committed()
        self._transition()  # rebuild the vertical over the repaired log
        if self.scrubber is not None:
            self.scrubber.note_repair("stream", "truncate-rematerialize",
                                      evidence)
        elif self.flight is not None:
            self.flight.record(self.partition_id, "storage_repair",
                               target="stream",
                               action="truncate-rematerialize", **evidence)
        return evidence

    def repair_snapshot_corruption(self, detail: dict | None = None) -> dict:
        """Snapshot corruption repair (tip or mid-chain): QUARANTINE the
        corrupt member (renamed out of the recovery path — the chain
        validator, queries, and a later recovery all skip it), then
        re-anchor: a leader takes a fresh FULL snapshot from its live
        state; a follower asks the leader to stream an install
        (``receive_snapshot`` persists it). An idle partition that cannot
        produce a newer snapshot id yet re-anchors at its next periodic
        snapshot — recovery meanwhile falls back to the older valid chain
        (single-replica clusters with a compacted log can only truncate;
        docs/durability.md carries that caveat honestly)."""
        from zeebe_tpu.state.snapshot import SnapshotId

        detail = detail or {}
        snap_id_str = detail.get("snapshotId")
        evidence: dict = {"snapshotId": snap_id_str}
        snap_id = SnapshotId.parse(snap_id_str) if snap_id_str else None
        quarantined = None
        snap = (self.snapshot_store.snapshot_at(snap_id)
                if snap_id is not None else None)
        if snap is not None:
            quarantined = self.snapshot_store.quarantine(snap)
            evidence["quarantined"] = (str(quarantined)
                                       if quarantined else None)
        # the corrupt member may sit anywhere in the live chain: drop the
        # anchor so the next snapshot rebases to a FULL one, and invalidate
        # the compaction-bound memo (it may have trusted the dead chain)
        self._snapshot_anchor = None
        self._chain_len = 0
        self._compact_bound_memo = (None, -1)
        action = "pending"
        if self.role == RaftRole.LEADER:
            try:
                if self.take_snapshot(force_full=True):
                    action = "fresh-full-snapshot"
            except OSError:
                pass  # disk still failing; retried on a later scrub pass
        elif self.raft.request_snapshot():
            action = "requested-install"
        evidence["action"] = action
        # "pending" (no leader to ask, or the fresh snapshot itself failed
        # on the still-faulting disk) must keep the DEGRADED latch so the
        # scrubber's per-cycle retry actually fires; quarantine alone is
        # only half the repair
        complete = (snap is None
                    or (quarantined is not None and action != "pending"))
        if self.scrubber is not None:
            self.scrubber.note_repair("snapshot", action, evidence,
                                      complete=complete)
        elif self.flight is not None:
            self.flight.record(self.partition_id, "storage_repair",
                               target="snapshot", action=action, **evidence)
        return evidence

    def repair_cold_corruption(self, reason: str) -> dict:
        """Cold-store corruption repair (read-side parity with PR 9's
        write-side degradation): latch tiering DEGRADED, then TRANSITION —
        the cold tier is a cache, so the rebuild from chain + log (which
        wipes the cold dir) restores every value the rotten frame held.
        The pump survives; nothing is served from the bad frame."""
        if not self._storage_repair_ok("cold"):
            return {}
        from zeebe_tpu.state.tiering import note_cold_read_error

        evidence = {"reason": str(reason)[:300]}
        note_cold_read_error(self.partition_id)
        if self.tiering is not None:
            self.tiering.degraded = True
            self.tiering.degraded_reason = evidence["reason"]
        self._transition()
        if self.scrubber is not None:
            self.scrubber.note_repair("cold", "transition-rebuild", evidence)
        elif self.flight is not None:
            self.flight.record(self.partition_id, "storage_repair",
                               target="cold", action="transition-rebuild",
                               **evidence)
        return evidence

    # -- snapshotting (AsyncSnapshotDirector equivalent) -----------------------

    def _maybe_snapshot(self) -> None:
        now = self.clock_millis()
        if now - self._last_snapshot_ms >= self.snapshot_period_ms:
            self._last_snapshot_ms = now
            self.take_snapshot()
            return
        # adaptive cadence (ISSUE 6): between periodic snapshots, project the
        # replay debt (records a restart would replay) against the recovery
        # budget at the last MEASURED replay rate; snapshot early when the
        # projection passes REPLAY_DEBT_BUDGET_FRACTION of the budget.
        # Throttled to one projection per second — the pump is hot.
        if self.recovery_budget_ms <= 0:
            return
        if now - self._last_debt_check_ms < 1000:
            return
        self._last_debt_check_ms = now
        debt = self.stream.last_position - max(self._last_snapshot_processed, 0)
        pid = str(self.partition_id)
        _M_REPLAY_DEBT.labels(pid).set(float(max(debt, 0)))
        if debt <= 0:
            return
        projected_ms = debt * 1000.0 / self._observed_replay_rate
        if projected_ms <= self.recovery_budget_ms * REPLAY_DEBT_BUDGET_FRACTION:
            return
        if self.take_snapshot():
            # reset the period clock only on success: a transiently-declined
            # attempt (mid-pipeline, not-newer) must not push the next
            # PERIODIC snapshot out a full period while debt keeps growing
            self._last_snapshot_ms = now
            _M_ADAPTIVE_SNAPSHOTS.labels(pid).inc()
            self.adaptive_snapshot_count += 1
            # this pre-dated the control plane but IS a closed feedback
            # loop: its decisions record under the shared control_adjust
            # vocabulary (ISSUE 12) so `cli top` CONTROL shows every loop
            from zeebe_tpu.control.audit import record_adjust

            record_adjust(
                self.flight, self.partition_id,
                controller="snapshot-scheduler", knob="snapshot.cadence",
                before=round(projected_ms, 1), after=0,
                reason="snapshot early: projected replay debt threatened "
                       "recovery_budget_ms",
                signals={"debtRecords": debt,
                         "projectedReplayMs": round(projected_ms, 1),
                         "budgetMs": self.recovery_budget_ms})

    def take_snapshot(self, force_full: bool = False) -> bool:
        """Snapshot the db at lastProcessedPosition, then compact both logs up
        to min(processed, exported) (reference: AsyncSnapshotDirector.java:37 —
        wait for commit, persist, then Raft compacts).

        Incremental mode (non-durable state): when the db's changed-key set
        is anchored on the store's current tip and the chain is short enough,
        the snapshot is a DELTA (changed keys since the tip) — O(delta)
        instead of O(state). The chain rebases to a full snapshot every
        ``snapshot_chain_length`` links, when the delta would approach the
        full state's size, or on ``force_full`` (backups)."""
        if self.processor is None or self.db is None:
            return False
        processed = self.processor.last_processed_position
        if processed < 0:
            return False
        # the reference waits until lastWrittenPosition is committed before
        # persisting (AsyncSnapshotDirector): our materialized stream journal
        # IS the committed prefix, so written-but-unmaterialized means wait
        if self.processor.last_written_position > self.stream.last_position:
            return False
        import time as _time

        from zeebe_tpu.utils.metrics import REGISTRY

        snapshot_started = _time.perf_counter()
        exported = self.exporter_director.lowest_exporter_position()
        term = self.raft.current_term
        raft_index = self.raft.journal.seek_to_asqn(processed)
        if raft_index <= 0:
            raft_index = self.raft.snapshot_index
        try:
            transient = self.snapshot_store.new_transient_snapshot(
                raft_index, term, processed, exported if exported < 2**62 else processed
            )
        except Exception:
            return False  # not newer than the latest snapshot
        return self._write_and_persist_snapshot(
            transient, processed, exported, force_full,
            snapshot_started=snapshot_started)

    def _write_and_persist_snapshot(self, transient, processed: int,
                                    exported: int, force_full: bool,
                                    snapshot_started: float) -> bool:
        import time as _time

        from zeebe_tpu.utils.metrics import REGISTRY

        try:
            return self._write_and_persist_snapshot_inner(
                transient, processed, exported, force_full,
                snapshot_started, _time, REGISTRY)
        except OSError:
            # disk fault mid-snapshot (ISSUE 14): abort the transient (the
            # half-written pending dir must not survive) and decline — the
            # periodic/adaptive scheduler retries; recovery still has the
            # previous valid chain
            transient.abort()
            _M_SNAPSHOT_WRITE_FAILURES.labels(str(self.partition_id)).inc()
            return False

    def _write_and_persist_snapshot_inner(self, transient, processed: int,
                                          exported: int, force_full: bool,
                                          snapshot_started: float,
                                          _time, REGISTRY) -> bool:
        kind = "full"
        if self.durable_state:
            # O(delta): fsync the durable delta log + manifest; the snapshot
            # entry only carries bookkeeping (positions for recovery-ordering
            # and the raft compaction boundary) — reference: RocksDB
            # checkpoints are hard links, not value copies
            kind = "durable"
            manifest = self.db.checkpoint()
            transient.write_file("durable.bin", packb({"manifest": manifest}))
        else:
            anchor = (self.snapshot_store.snapshot_at(self._snapshot_anchor)
                      if self._snapshot_anchor is not None else None)
            dirty = getattr(self.db, "dirty_key_count", 0)
            # a delta at least as large (in entries) as the full state saves
            # nothing — rebase; likewise when the chain is at its length cap,
            # the anchor vanished (purge race / manual cleanup), or the
            # caller wants a self-contained snapshot (backups, installs)
            if (not force_full
                    and anchor is not None
                    and self.db.supports_delta_snapshots
                    and getattr(self.db, "delta_tracking", False)
                    and self._chain_len >= 1
                    and self._chain_len < self.snapshot_chain_length
                    and dirty < max(self.db.key_count, 1)):
                kind = "delta"
                transient.write_file(DELTA_FILE, self.db.to_delta_bytes())
                transient.link_parent(anchor, self._chain_len + 1)
            else:
                transient.write_file(STATE_FILE, self.db.to_snapshot_bytes())
        transient.write_file("meta.bin", packb({
            "lastProcessed": processed,
            "lastPosition": self.stream.last_position,
        }))
        persist_started = _time.perf_counter()
        snapshot = transient.persist()
        # chain bookkeeping only after the snapshot is durably committed: an
        # aborted persist must not clear the changed-key window (those keys
        # would silently fall out of the next delta)
        self._chain_len = self._chain_len + 1 if kind == "delta" else 1
        self._snapshot_anchor = snapshot.id
        self._last_snapshot_processed = processed
        # prime the compaction-bound memo: we just validated this tip by
        # persisting it — without this, every guard invocation after a
        # persist re-reads and CRCs the whole chain (the base is O(state))
        self._compact_bound_memo = (snapshot.id, processed)
        if not self.durable_state and self.db.supports_delta_snapshots:
            # the new tip covers everything up to `processed`; the next delta
            # records exactly the writes after it. The durable store opts
            # out: its _data holds _Packed/memoryview cold values a delta
            # could not serialize (DURABLESTATE-flag-flipped migrations
            # recover a DurableZbDb even with durable_state now False)
            self.db.begin_delta_tracking()
        pid = str(self.partition_id)
        _M_SNAPSHOT_KIND.labels(pid, kind).inc()
        _M_SNAPSHOT_CHAIN_LEN.labels(pid).set(float(self._chain_len))
        _M_REPLAY_DEBT.labels(pid).set(
            float(max(self.stream.last_position - processed, 0)))
        REGISTRY.counter(
            "snapshot_count", "snapshots persisted", ("partition",)
        ).labels(pid).inc()
        elapsed = _time.perf_counter() - snapshot_started
        REGISTRY.histogram(
            "snapshot_duration_seconds", "time to persist a snapshot",
            ("partition",)
        ).labels(pid).observe(elapsed)
        REGISTRY.histogram(
            "snapshot_duration", "time to take+persist a snapshot, seconds",
            ("partition",)).labels(pid).observe(elapsed)
        REGISTRY.histogram(
            "snapshot_persist_duration",
            "time to persist the transient snapshot, seconds",
            ("partition",)).labels(pid).observe(
            _time.perf_counter() - persist_started)
        try:
            size = 0
            chunks = 0
            for f in snapshot.path.rglob("*"):
                if f.is_file():
                    size += f.stat().st_size
                    chunks += 1
            REGISTRY.gauge(
                "snapshot_size_bytes", "bytes of the latest snapshot",
                ("partition",)).labels(pid).set(size)
            REGISTRY.gauge(
                "snapshot_file_size_megabytes",
                "megabytes of the latest snapshot", ("partition",)
            ).labels(pid).set(size / 1e6)
            REGISTRY.gauge(
                "snapshot_chunks_count",
                "files in the latest snapshot", ("partition",)
            ).labels(pid).set(chunks)
        except OSError:
            pass
        # raft log compaction bound: nothing above the snapshot index, nothing
        # unexported, nothing unmaterialized
        compact_position = min(processed, exported)
        compact_index = self.raft.journal.seek_to_asqn(compact_position)
        if compact_index > 1:
            # the snapshot boundary's term is the term of the entry it replaces
            # (not the current term) or _entry_term answers wrongly at the
            # boundary and replication backs up into a needless snapshot install
            boundary_term = self.raft.entry_term(compact_index - 1)
            # durable mode and delta snapshots have no self-contained
            # state.bin to store as a fallback install payload — pass None so
            # installs are served only by the live ``snapshot_provider``
            # (which materializes the chain), and when it declines, nothing
            # is sent (b"" would ship a torn install: journal reset + unpackb
            # crash on the receiver)
            self.raft.set_snapshot(
                compact_index - 1, boundary_term,
                self._install_payload(snapshot)
                if kind == "full" else None,
            )
        # the materialized stream journal compacts to the same bound (whole
        # segments only); its compact_guard re-derives the invariant from the
        # store + exporter cursors below this caller, so a stale `exported`
        # here can never over-delete
        self.stream.compact_to_position(compact_position)
        return True

    # -- snapshot replication (leader → lagging follower) ----------------------

    def _install_payload(self, snapshot) -> bytes:
        return packb({
            "state": snapshot.read_file("state.bin"),
            "meta": snapshot.read_file("meta.bin"),
        })

    def _provide_install_snapshot(self):
        if self.durable_state:
            # build the payload live from the durable store (rare path: a
            # follower fell behind the compacted log). Meta must describe the
            # LIVE state dump, not the last checkpoint — the receiver aligns
            # its stream to meta.lastPosition and the state's own
            # lastProcessed marker
            if self.db is None or self.processor is None or self.db.in_transaction:
                return None
            return (self.raft.snapshot_index, self.raft.snapshot_term, packb({
                "state": self.db.to_snapshot_bytes(),
                "meta": packb({
                    "lastProcessed": self.processor.last_processed_position,
                    "lastPosition": self.stream.last_position,
                }),
            }))
        chain = self.snapshot_store.latest_valid_chain()
        if chain is None or not chain[0].has_file(STATE_FILE):
            return None
        if len(chain) == 1:
            payload = self._install_payload(chain[0])
        else:
            # delta tip: the receiver installs a SELF-CONTAINED state blob
            # (followers know nothing about the leader's local chain), so
            # materialize base+deltas into one state.bin equivalent
            try:
                payload = packb({
                    "state": load_chain_db(chain).to_snapshot_bytes(),
                    "meta": chain[-1].read_file("meta.bin"),
                })
            except (OSError, ValueError):
                return None
        return (self.raft.snapshot_index, self.raft.snapshot_term, payload)

    def _receive_install_snapshot(self, data: bytes) -> None:
        """Follower fell behind the leader's compacted log: replace local state
        wholesale (reference: PassiveRole + FileBasedReceivedSnapshot →
        StateControllerImpl recover)."""
        payload = unpackb(data)
        meta = unpackb(payload["meta"])
        # persist locally so restart recovers from it
        try:
            transient = self.snapshot_store.new_transient_snapshot(
                self.raft.snapshot_index, self.raft.snapshot_term,
                meta["lastProcessed"], meta["lastProcessed"],
            )
            transient.write_file("state.bin", payload["state"])
            transient.write_file("meta.bin", payload["meta"])
            transient.persist()
        except Exception:
            pass  # not newer than what we have
        # reset the stream journal past the snapshot and rebuild the vertical
        self.stream_journal.close()
        shutil.rmtree(self._stream_dir, ignore_errors=True)
        self.stream_journal = SegmentedJournal(self._stream_dir)
        # the rebuilt journal must keep the compaction safety guard — losing
        # it here would leave every later compact() on this node unguarded
        self.stream_journal.compact_guard = self._stream_compact_guard
        self.stream = LogStream(self.stream_journal, self.partition_id,
                                clock=self.clock_millis)
        self.stream._next_position = meta["lastPosition"] + 1
        self._next_position = meta["lastPosition"] + 1
        # re-anchor materialization at the installed snapshot: entries below
        # it are covered by the snapshot, entries above it refill from the
        # (reset) raft log. Without this, a NON-lagging follower that
        # requested an install as a snapshot-corruption repair (ISSUE 14)
        # would skip the refilled entries — its applied index still pointed
        # past them from the pre-install log.
        self._applied_raft_index = self.raft.snapshot_index
        self._transition()

    # -- lifecycle -------------------------------------------------------------

    def tick(self) -> None:
        self.raft.tick()

    def close(self) -> None:
        if self.exporter_director is not None:
            self.exporter_director.close()
        self.raft.close()
        self.stream_journal.close()
        if self.db is not None:
            from zeebe_tpu.state.durable import DurableZbDb
            from zeebe_tpu.state.tiering import TieredZbDb

            if isinstance(self.db, (DurableZbDb, TieredZbDb)):
                self.db.close()

    def hard_crash(self) -> None:
        """Power-loss crash simulation (chaos harness flush-boundary fault):
        unlike ``close``, nothing flushes — both journals discard every byte
        not covered by an fsync (buffered appends AND file bytes written
        since the last flush), exactly what surviving hardware would hold
        after losing power between a buffered append and its covering flush.
        Exporters/state are simply abandoned; recovery rebuilds them."""
        self.raft.journal.simulate_power_loss()
        self.stream_journal.simulate_power_loss()

    def latest_checkpoint_id(self) -> int:
        """Lock-free: read by OTHER partitions' ownership threads on every
        inter-partition send — must never open this partition's db (the owner
        thread may be mid-transaction). The cache refreshes at transition and
        on every checkpoint-created apply."""
        return self._latest_checkpoint

    def _observe_checkpoint_applied(self, checkpoint_id: int) -> None:
        self._latest_checkpoint = max(self._latest_checkpoint, checkpoint_id)
        if self.on_checkpoint is not None:
            # broker-level cache (max over local replicas) follows along —
            # on followers too, which the processing listener never covers
            self.on_checkpoint(checkpoint_id)

    def _on_checkpoint_created(self, checkpoint_id: int, position: int) -> None:
        self._latest_checkpoint = max(self._latest_checkpoint, checkpoint_id)
        if self.on_checkpoint is not None:
            self.on_checkpoint(checkpoint_id)
        if self.backup_service is not None:
            self.backup_service.take_backup(self, checkpoint_id, position)

    def _report_exporter_health(self, exporter_id: str, status,
                                message: str = "") -> None:
        """Per-exporter health sub-component under this partition (a backing-
        off exporter degrades the broker without taking the partition down)."""
        if (self.flight is not None
                and self._exporter_flight_status.get(exporter_id) != status):
            # transitions only: a backing-off exporter re-reports DEGRADED on
            # every retry, which would crowd everything else out of the ring
            self._exporter_flight_status[exporter_id] = status
            self.flight.record(self.partition_id, "exporter_state",
                               exporter=exporter_id, status=status.name,
                               message=message)
        if self.health_monitor is not None:
            self.health_monitor.report(
                f"partition-{self.partition_id}.exporter-{exporter_id}",
                status, message)

    @property
    def is_leader(self) -> bool:
        return self.role == RaftRole.LEADER

    def health(self) -> dict:
        return {
            "partitionId": self.partition_id,
            "role": self.role.value,
            "term": self.raft.current_term,
            "commitIndex": self.raft.commit_index,
            "lastPosition": self.stream.last_position,
            "lastProcessed": self.processor.last_processed_position
            if self.processor else -1,
            # recovery-budget plane: the last rebuild's cost (duration,
            # replay length, chain, budget verdict) — the soak harness and
            # operators read this off /health after every restart
            "lastRecovery": self.last_recovery,
            "snapshotChainLength": self._chain_len,
            # state tiering (ISSUE 8): parked-instance + tier accounting —
            # /cluster/status and `cli top` surface these
            **({"stateTiering": {
                **self.db.tier_stats(),
                "parkedColdInstances": self.tiering.spilled_instances,
                "parkCandidates": self.tiering.pending_candidates,
                # write-error degradation (ISSUE 9 satellite): ENOSPC/EIO
                # during spill stops admissions without killing the pump
                "status": ("DEGRADED" if self.tiering.degraded
                           else "HEALTHY"),
                **({"degradedReason": self.tiering.degraded_reason}
                   if self.tiering.degraded else {}),
            }} if self.tiering is not None and self.db is not None
               and hasattr(self.db, "tier_stats") else {}),
            # kernel-path coverage (ISSUE 13): which records rode the
            # device plane vs host, and why — the ruler ROADMAP item 3's
            # "≥90% on the kernel path" is graded with
            **({"kernelCoverage": {
                **self.processor.kernel_backend.accounting.snapshot(),
                # device-fault defense (ISSUE 15): health ladder state +
                # shadow counters, so a quarantine explains its own
                # coverage drop in the same block
                "device": self.processor.kernel_backend.device_status(),
            }} if self.processor is not None
               and self.processor.kernel_backend is not None else {}),
            # at-rest storage integrity (ISSUE 14): scrub coverage,
            # detections, repairs, and the DEGRADED latch while a repair
            # is still pending
            **({"storageIntegrity": self.scrubber.status()}
               if self.scrubber is not None else {}),
        }
