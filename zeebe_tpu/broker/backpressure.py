"""Request backpressure: adaptive in-flight command limiting.

Reference: broker/src/main/java/io/camunda/zeebe/broker/transport/backpressure/
— PartitionAwareRequestLimiter → CommandRateLimiter.java:26 over Netflix
concurrency-limits (vegas, aimd, fixed, gradient; docs/backpressure.md:1-80).
White-listed intents (job COMPLETE/FAIL) always pass so workers can finish
in-flight work and drain load.

Implemented limiters: fixed, AIMD (additive increase on success below the
limit, multiplicative decrease on timeout), and vegas (latency-gradient:
queue estimate = limit * (1 - minRTT/sampleRTT), grow when small, shrink when
large) — the reference's default.
"""

from __future__ import annotations

import math
from typing import Callable

from zeebe_tpu.protocol import Record, ValueType
from zeebe_tpu.protocol.intent import JobIntent

# intents that bypass backpressure (docs/backpressure.md white list)
WHITELIST: set[tuple[ValueType, int]] = {
    (ValueType.JOB, int(JobIntent.COMPLETE)),
    (ValueType.JOB, int(JobIntent.FAIL)),
}


class FixedLimit:
    def __init__(self, limit: int = 100) -> None:
        self.limit = limit

    def on_sample(self, rtt_ms: float, in_flight: int, dropped: bool) -> None:
        pass


class AimdLimit:
    """Additive-increase / multiplicative-decrease on request timeouts."""

    def __init__(self, initial: int = 100, min_limit: int = 1,
                 max_limit: int = 1000, backoff_ratio: float = 0.9,
                 timeout_ms: float = 200.0) -> None:
        self.limit = initial
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.backoff_ratio = backoff_ratio
        self.timeout_ms = timeout_ms

    def on_sample(self, rtt_ms: float, in_flight: int, dropped: bool) -> None:
        if dropped or rtt_ms > self.timeout_ms:
            self.limit = max(self.min_limit, int(self.limit * self.backoff_ratio))
        elif in_flight * 2 >= self.limit:
            self.limit = min(self.max_limit, self.limit + 1)


class VegasLimit:
    """Latency-gradient limit (the reference default, vegas windowed)."""

    def __init__(self, initial: int = 20, min_limit: int = 1,
                 max_limit: int = 1000) -> None:
        self.limit = initial
        self.min_limit = min_limit
        self.max_limit = max_limit
        self._min_rtt = math.inf

    def on_sample(self, rtt_ms: float, in_flight: int, dropped: bool) -> None:
        if dropped:
            self.limit = max(self.min_limit, int(self.limit * 0.9))
            return
        if rtt_ms <= 0:
            return
        self._min_rtt = min(self._min_rtt, rtt_ms)
        queue = self.limit * (1 - self._min_rtt / rtt_ms)
        alpha = 3 * math.log10(self.limit) + 1
        beta = 6 * math.log10(self.limit) + 1
        if queue < alpha:
            self.limit = min(self.max_limit, self.limit + int(math.log10(self.limit)) + 1)
        elif queue > beta:
            self.limit = max(self.min_limit, self.limit - 1)


LIMITS = {"fixed": FixedLimit, "aimd": AimdLimit, "vegas": VegasLimit}


class CommandRateLimiter:
    """Per-partition in-flight limiter; acquire at ingress, release when the
    command's response/processing completes (reference: CommandRateLimiter
    registered on the command api request path)."""

    def __init__(self, algorithm: str = "vegas", enabled: bool = True,
                 clock_millis: Callable[[], int] | None = None,
                 timeout_ms: int | None = None, **kw) -> None:
        import time

        if algorithm == "aimd" and timeout_ms is not None:
            # one timeout threshold for both the drop-sample gate here and
            # AIMD's internal rtt backoff — not two inconsistent ones
            kw.setdefault("timeout_ms", timeout_ms)
        self.algorithm = LIMITS[algorithm](**kw)
        self.enabled = enabled
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        # default: inherit the algorithm's own threshold (AIMD: 200ms) so an
        # unconfigured limiter keeps its pre-existing sensitivity
        self.timeout_ms = (timeout_ms if timeout_ms is not None
                           else getattr(self.algorithm, "timeout_ms", 10_000))
        self.in_flight: dict[int, int] = {}  # position → acquire time ms
        self.dropped_total = 0
        from zeebe_tpu.utils.metrics import REGISTRY

        self._m_limit = REGISTRY.gauge(
            "backpressure_requests_limit",
            "current adaptive in-flight request limit").labels()
        self._m_received = REGISTRY.counter(
            "received_request_count_total",
            "commands received at the ingress limiter").labels()
        self._m_dropped = REGISTRY.counter(
            "dropped_request_count_total",
            "commands rejected by backpressure").labels()
        # the appender-side limits exist in the reference as a separate flow
        # control; here the sequencer/appender path is synchronous, so the
        # append limit equals the request limit and in-flight appends equal
        # in-flight requests
        self._m_append_limit = REGISTRY.gauge(
            "backpressure_append_limit",
            "current in-flight append limit (synchronous appender: equals "
            "the request limit)").labels()
        self._m_inflight_appends = REGISTRY.gauge(
            "backpressure_inflight_append_count",
            "appends in flight (synchronous appender: equals in-flight "
            "requests)").labels()
        self._m_limit.set(self.algorithm.limit)
        self._m_append_limit.set(self.algorithm.limit)

    @property
    def limit(self) -> int:
        return self.algorithm.limit

    def try_acquire(self, record: Record, provisional: int = 0) -> bool:
        """``provisional``: admissions already granted in the caller's
        current batch but not yet appended (``on_appended`` is what grows
        ``in_flight``) — the coalesced ingress passes its running count so
        one batch cannot overshoot the limit by its own size."""
        if not self.enabled:
            return True
        self._m_received.inc()
        if (record.value_type, int(record.intent)) in WHITELIST:
            return True
        if len(self.in_flight) + provisional >= self.algorithm.limit:
            # gate rejections are NOT fed to the limit algorithm: the Netflix
            # concurrency-limits reference only records drop samples for timed-
            # out in-flight requests, and multiplicative-decrease per rejected
            # request collapses the limit to min under a burst (death spiral)
            self.dropped_total += 1
            self._m_dropped.inc()
            return False
        return True

    def on_appended(self, position: int) -> None:
        self.in_flight[position] = self.clock_millis()
        self._m_inflight_appends.set(len(self.in_flight))

    def on_processed(self, position: int) -> None:
        started = self.in_flight.pop(position, None)
        if started is not None:
            rtt = self.clock_millis() - started
            # drop samples come only from in-flight RTTs exceeding the timeout
            self.algorithm.on_sample(rtt, len(self.in_flight),
                                     dropped=rtt > self.timeout_ms)
            # the adaptive limit only moves on samples — update gauges here,
            # off the per-command ingress path
            self._m_limit.set(self.algorithm.limit)
            self._m_append_limit.set(self.algorithm.limit)
