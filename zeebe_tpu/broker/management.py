"""Management HTTP server: health probes, metrics, admin operations.

Reference: dist/shared/management — actuator endpoints (startup/ready/liveness
probes wired to BrokerHealthCheckService, Prometheus servlet, /actuator/backups
trigger, pause/resume processing via BrokerAdminService).

Endpoints:
  GET  /health    → aggregated component health (liveness)
  GET  /ready     → 200 when every local partition has a role and a processor
  GET  /metrics   → Prometheus text exposition
  GET  /partitions → per-partition health dicts
  GET  /traces    → collected tracing spans (observability subsystem);
                    ?format=chrome returns Chrome-trace-event JSON that opens
                    directly in Perfetto, ?limit=N tails the newest N spans
  GET  /profile   → sampling profiler over all runtime threads
                    (?seconds=N, capped at 30; pump/kernel/io time split)
  POST /backups/<id> → trigger a cluster-consistent checkpoint
  GET  /backups   → backup store listing (when a store is configured)
  POST /pause | /resume → pause/resume stream processing (BrokerAdminService)
  POST /rebalance → transfer partition leadership to the highest-priority
       replicas (reference: dist/…/management/RebalancingEndpoint.java)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from zeebe_tpu.utils.metrics import REGISTRY


class ManagementServer:
    def __init__(self, broker, bind: tuple[str, int] = ("127.0.0.1", 0),
                 registry=None) -> None:
        self.broker = broker
        self.registry = registry or REGISTRY
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: str,
                      content_type: str = "application/json") -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as exc:  # management must not crash the broker
                    self._send(500, json.dumps({"error": str(exc)}))

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as exc:
                    self._send(500, json.dumps({"error": str(exc)}))

        self.server = ThreadingHTTPServer(bind, Handler)
        self.port = self.server.server_address[1]
        self._thread: threading.Thread | None = None

    def _get(self, handler) -> None:
        path = handler.path.split("?", 1)[0]
        if path == "/metrics":
            handler._send(200, self.registry.expose(), "text/plain; version=0.0.4")
        elif path == "/health":
            health = self.broker.health_monitor.to_dict()
            code = 200 if self.broker.health_monitor.is_healthy() else 503
            handler._send(code, json.dumps(health))
        elif path == "/ready":
            ready = all(
                p.processor is not None for p in self.broker.partitions.values()
            )
            handler._send(200 if ready else 503, json.dumps({"ready": ready}))
        elif path == "/partitions":
            handler._send(200, json.dumps(
                [p.health() for p in self.broker.partitions.values()]
            ))
        elif path == "/traces":
            from urllib.parse import parse_qs, urlsplit

            from zeebe_tpu.observability import chrome_trace, get_tracer

            params = parse_qs(urlsplit(handler.path).query)
            tracer = get_tracer()
            spans = tracer.collector.snapshot()
            try:
                limit = int(params.get("limit", ["0"])[0])
            except ValueError:
                limit = 0
            if limit > 0:
                spans = spans[-limit:]
            if params.get("format", ["json"])[0] == "chrome":
                handler._send(200, json.dumps(chrome_trace(spans)))
            else:
                handler._send(200, json.dumps({
                    "enabled": tracer.enabled,
                    "sampleRate": tracer.sampler.rate,
                    "seed": tracer.sampler.seed,
                    "emitted": tracer.collector.emitted,
                    "spans": [s.to_dict() for s in spans],
                }))
        elif path == "/profile":
            from urllib.parse import parse_qs, urlsplit

            params = parse_qs(urlsplit(handler.path).query)
            try:
                seconds = min(float(params.get("seconds", ["2.0"])[0]), 30.0)
            except ValueError:
                seconds = -1.0
            if not 0 < seconds:  # also rejects NaN
                handler._send(400, json.dumps(
                    {"error": "seconds must be a positive number"}))
                return
            handler._send(200, json.dumps(sample_profile(seconds)))
        elif path == "/backups":
            if self.broker.backup_store is None:
                handler._send(404, json.dumps({"error": "no backup store configured"}))
                return
            statuses = [
                {"checkpointId": s.checkpoint_id, "partitionId": s.partition_id,
                 "status": s.status.value}
                for s in self.broker.backup_store.list_backups()
            ]
            handler._send(200, json.dumps(statuses))
        else:
            handler._send(404, json.dumps({"error": f"unknown path {path}"}))

    def _post(self, handler) -> None:
        path = handler.path.split("?", 1)[0]
        if path.startswith("/backups/"):
            checkpoint_id = int(path.rsplit("/", 1)[-1])
            accepted = self.broker.trigger_checkpoint(checkpoint_id)
            handler._send(202, json.dumps(
                {"checkpointId": checkpoint_id, "partitions": accepted}
            ))
        elif path == "/pause":
            self.broker.pause_processing()
            handler._send(200, json.dumps({"paused": True}))
        elif path == "/resume":
            self.broker.resume_processing()
            handler._send(200, json.dumps({"paused": False}))
        elif path == "/rebalance":
            # leadership rebalancing (reference: actuator RebalancingEndpoint)
            transferred = self.broker.rebalance()
            handler._send(202, json.dumps(
                {"transferred": {str(k): v for k, v in transferred.items()}}
            ))
        else:
            handler._send(404, json.dumps({"error": f"unknown path {path}"}))

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="management-server")
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)

def sample_profile(seconds: float, hz: float = 100.0) -> dict:
    """Sampling profiler over every runtime thread (the management
    /profile endpoint — the reference exposes JFR/async-profiler through its
    actuator; this is the in-process equivalent): snapshots all thread
    stacks at ``hz`` for ``seconds`` and aggregates by frame, so hot
    functions and per-thread time split (pump vs kernel vs io) read
    straight off the response without attaching a debugger."""
    import sys
    import time as _time

    names = {t.ident: t.name for t in threading.enumerate()}
    samples = 0
    by_frame: dict[str, int] = {}
    by_thread: dict[str, int] = {}
    deadline = _time.monotonic() + seconds
    interval = 1.0 / hz
    own = threading.get_ident()
    while _time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == own:  # never profile the profiler's own stack
                continue
            name = names.get(ident, str(ident))
            by_thread[name] = by_thread.get(name, 0) + 1
            depth = 0
            seen: set[str] = set()  # recursion must not inflate a frame
            while frame is not None and depth < 40:
                code = frame.f_code
                key = f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno})"
                if key not in seen:
                    seen.add(key)
                    by_frame[key] = by_frame.get(key, 0) + 1
                frame = frame.f_back
                depth += 1
        samples += 1
        _time.sleep(interval)
    top = sorted(by_frame.items(), key=lambda kv: -kv[1])[:50]
    total_stacks = max(sum(by_thread.values()), 1)
    return {
        "seconds": seconds,
        "samples": samples,
        "threads": dict(sorted(by_thread.items(), key=lambda kv: -kv[1])),
        # pct = share of all sampled thread-stacks that contain the frame
        "hot_frames": [
            {"frame": k, "samples": v,
             "pct": round(100.0 * v / total_stacks, 1)}
            for k, v in top
        ],
    }
