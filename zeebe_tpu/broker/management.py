"""Management HTTP server: health probes, metrics, admin operations.

Reference: dist/shared/management — actuator endpoints (startup/ready/liveness
probes wired to BrokerHealthCheckService, Prometheus servlet, /actuator/backups
trigger, pause/resume processing via BrokerAdminService).

Endpoints:
  GET  /health    → aggregated component health (liveness) + firing alerts
  GET  /ready     → 200 when every local partition has a role and a processor
  GET  /metrics   → Prometheus text exposition
  GET  /partitions → per-partition health dicts
  GET  /timeseries → retained metric history from the in-memory store
                    (?name= series or histogram base name — no name lists
                    the names; ?since= unix ms; ?step= ms downsampling)
  GET  /flight    → the flight recorder's live event rings (the same payload
                    a crash dumps to <data-dir>/flight-<ts>.json)
  GET  /alerts    → alert evaluator state (pending + firing)
  GET  /control   → closed-loop control plane state: controllers, actuator
                    bounds/values/audit counters, aggregated loops
                    (404 when ZEEBE_CONTROL_ENABLED=0 or sampling is off)
  GET  /cluster/status → topology + per-broker health/alerts/headline rates,
                    aggregated across all brokers when the server is given
                    the hosting runtime (in-process fan-out), else local
  GET  /traces    → collected tracing spans (observability subsystem);
                    ?format=chrome returns Chrome-trace-event JSON that opens
                    directly in Perfetto, ?limit=N tails the newest N spans
  GET  /profile   → one-shot sampling profiler over all runtime threads
                    (?seconds=N, capped at 30; pump/kernel/io time split;
                    ?format=folded returns flamegraph.pl/speedscope-
                    compatible collapsed stacks as text/plain)
  GET  /profile/continuous → the always-on continuous profiler's retained
                    folded-stack windows (?since= unix ms,
                    ?format=folded|json); 404 when profiling_hz=0
  POST /profile/device → single-flight jax.profiler.trace() capture into
                    <data-dir>/jax-trace-<ts>/ (?seconds=N, capped at 30);
                    202 with the trace dir, 409 while one is in flight
  POST /backups/<id> → trigger a cluster-consistent checkpoint
  GET  /backups   → backup store listing (when a store is configured)
  POST /pause | /resume → pause/resume stream processing (BrokerAdminService)
  POST /rebalance → transfer partition leadership to the highest-priority
       replicas (reference: dist/…/management/RebalancingEndpoint.java)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from zeebe_tpu.utils.metrics import REGISTRY


class ManagementServer:
    def __init__(self, broker, bind: tuple[str, int] = ("127.0.0.1", 0),
                 registry=None, runtime=None) -> None:
        # broker=None: the gateway-process shape (multiproc workers host the
        # brokers) — /metrics, /cluster/status, and /health (aggregated from
        # the runtime) stay up; broker-local endpoints answer 404
        self.broker = broker
        self.registry = registry or REGISTRY
        # hosting ClusterRuntime (optional): enables the /cluster/status
        # all-broker fan-out for the in-process deployment shape
        self.runtime = runtime
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: str,
                      content_type: str = "application/json") -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    outer._get(self)
                except Exception as exc:  # management must not crash the broker
                    self._send(500, json.dumps({"error": str(exc)}))

            def do_POST(self):
                try:
                    outer._post(self)
                except Exception as exc:
                    self._send(500, json.dumps({"error": str(exc)}))

        self.server = ThreadingHTTPServer(bind, Handler)
        self.port = self.server.server_address[1]
        self._thread: threading.Thread | None = None

    def _get(self, handler) -> None:
        path = handler.path.split("?", 1)[0]
        if self.broker is None:
            # no local broker (gateway process, or a broker-free test
            # server): /cluster/status, /health, /ready aggregate from the
            # runtime; broker-independent endpoints (/metrics, /traces,
            # /profile, and the getattr-guarded observability paths) fall
            # through to the shared handlers; true broker-local endpoints
            # answer 404 instead of crashing
            if path == "/health" and self.runtime is not None:
                # LIVENESS of the gateway process: always 200 while it can
                # answer — one crash-looping worker (reported in the payload)
                # must not get the gateway, and with it the supervisor and
                # every healthy worker, liveness-probed to death
                handler._send(200, json.dumps(self.runtime.cluster_status()))
                return
            if path == "/ready" and self.runtime is not None:
                # READINESS aggregates: serving needs a live leader for every
                # partition (the runtime knows; default to the health roll-up)
                ready_fn = getattr(self.runtime, "ready", None)
                status = self.runtime.cluster_status()
                ready = (bool(ready_fn()) if ready_fn is not None
                         else status.get("health") in ("HEALTHY", "DEGRADED"))
                handler._send(200 if ready else 503, json.dumps(
                    {"ready": ready, **status}))
                return
            broker_free = {"/metrics", "/traces", "/profile", "/flight",
                           "/timeseries", "/alerts", "/profile/continuous"}
            if self.runtime is not None:
                # the shared handler below serves it via the runtime fan-out
                broker_free.add("/cluster/status")
            if path not in broker_free:
                handler._send(404, json.dumps(
                    {"error": "no local broker: "
                              f"endpoint {path} unavailable"}))
                return
        if path == "/metrics":
            handler._send(200, self.registry.expose(), "text/plain; version=0.0.4")
        elif path == "/health":
            health = self.broker.health_monitor.to_dict()
            alerts = getattr(self.broker, "alerts", None)
            if alerts is not None:
                # alert details ride the health payload so one probe answers
                # both "is it up" and "is anything on fire"
                health["alerts"] = alerts.snapshot()
                health["alertsFiring"] = len(alerts.firing())
            # recovery-budget plane: the last rebuild's cost per partition
            # (duration, replay length, budget verdict) rides the same probe
            # — after a kill+restart, /health alone answers "what did the
            # recovery cost and did it fit the budget"
            recoveries = {
                str(pid): p.last_recovery
                for pid, p in self.broker.partitions.items()
                if getattr(p, "last_recovery", None) is not None
            }
            if recoveries:
                health["recoveries"] = recoveries
            code = 200 if self.broker.health_monitor.is_healthy() else 503
            handler._send(code, json.dumps(health))
        elif path == "/ready":
            ready = all(
                p.processor is not None for p in self.broker.partitions.values()
            )
            handler._send(200 if ready else 503, json.dumps({"ready": ready}))
        elif path == "/partitions":
            handler._send(200, json.dumps(
                [p.health() for p in self.broker.partitions.values()]
            ))
        elif path == "/timeseries":
            from urllib.parse import parse_qs, urlsplit

            store = getattr(self.broker, "timeseries", None)
            if store is None:
                handler._send(404, json.dumps(
                    {"error": "time-series sampling disabled "
                              "(metrics_sampling_ms=0)"}))
                return
            params = parse_qs(urlsplit(handler.path).query)
            name = params.get("name", [""])[0]
            if not name:
                stats = store.stats()
                stats.pop("series", None)  # the count would shadow the list
                handler._send(200, json.dumps({
                    "series": store.series_names(), **stats,
                    "seriesCount": len(store.series_names())}))
                return
            try:
                since = int(params.get("since", ["0"])[0])
                step = int(params.get("step", ["0"])[0])
            except ValueError:
                handler._send(400, json.dumps(
                    {"error": "since and step must be integers (ms)"}))
                return
            handler._send(200, json.dumps({
                "name": name, "since": since, "step": step,
                "series": store.query(name, since_ms=since, step_ms=step),
            }))
        elif path == "/flight":
            # broker-local recorder, or the gateway runtime's own ring
            # (worker restarts, routing-epoch changes, request re-routes)
            recorder = getattr(self.broker, "flight_recorder", None)
            if recorder is None and self.runtime is not None:
                recorder = getattr(self.runtime, "flight", None)
            if recorder is None:
                handler._send(404, json.dumps(
                    {"error": "no flight recorder"}))
                return
            handler._send(200, json.dumps(recorder.snapshot(), default=str))
        elif path == "/control":
            # closed-loop control plane (ISSUE 12): controllers, actuator
            # bounds/values/audit counters, and the aggregated loops
            # (snapshot scheduler, admission shed ladder)
            plane = getattr(self.broker, "control", None)
            if plane is None:
                handler._send(404, json.dumps(
                    {"error": "control plane disabled "
                              "(ZEEBE_CONTROL_ENABLED=0 or metrics "
                              "sampling off)"}))
                return
            handler._send(200, json.dumps(plane.snapshot(), default=str))
        elif path == "/alerts":
            alerts = getattr(self.broker, "alerts", None)
            if alerts is None:
                handler._send(404, json.dumps(
                    {"error": "alert evaluation disabled"}))
                return
            handler._send(200, json.dumps({
                "alerts": alerts.snapshot(),
                "firing": len(alerts.firing()),
                "rules": [r.describe() for r in alerts.rules],
            }))
        elif path == "/cluster/status":
            if self.runtime is not None:
                status = self.runtime.cluster_status()
            else:
                status = cluster_status([self.broker])
            handler._send(200, json.dumps(status))
        elif path == "/traces":
            from urllib.parse import parse_qs, urlsplit

            from zeebe_tpu.observability import chrome_trace, get_tracer

            params = parse_qs(urlsplit(handler.path).query)
            tracer = get_tracer()
            spans = tracer.collector.snapshot()
            try:
                limit = int(params.get("limit", ["0"])[0])
            except ValueError:
                limit = 0
            if limit > 0:
                spans = spans[-limit:]
            if params.get("format", ["json"])[0] == "chrome":
                handler._send(200, json.dumps(chrome_trace(spans)))
            else:
                handler._send(200, json.dumps({
                    "enabled": tracer.enabled,
                    "sampleRate": tracer.sampler.rate,
                    "seed": tracer.sampler.seed,
                    "emitted": tracer.collector.emitted,
                    "spans": [s.to_dict() for s in spans],
                }))
        elif path == "/profile":
            from urllib.parse import parse_qs, urlsplit

            params = parse_qs(urlsplit(handler.path).query)
            seconds = parse_profile_seconds(params.get("seconds", ["2.0"])[0])
            if seconds is None:
                handler._send(400, json.dumps(
                    {"error": "seconds must be a positive number"}))
                return
            folded = params.get("format", ["json"])[0] == "folded"
            result = sample_profile(seconds, fold=folded)
            if folded:
                from zeebe_tpu.observability.profiler import folded_text

                handler._send(200, folded_text(result["folded"]),
                              "text/plain; charset=utf-8")
            else:
                handler._send(200, json.dumps(result))
        elif path == "/profile/continuous":
            from urllib.parse import parse_qs, urlsplit

            profiler = getattr(self.broker, "profiler", None)
            if profiler is None:
                handler._send(404, json.dumps(
                    {"error": "continuous profiler disabled "
                              "(profiling_hz=0)"}))
                return
            params = parse_qs(urlsplit(handler.path).query)
            try:
                since = int(params.get("since", ["0"])[0])
            except ValueError:
                handler._send(400, json.dumps(
                    {"error": "since must be an integer (unix ms)"}))
                return
            if params.get("format", ["json"])[0] == "folded":
                handler._send(200, profiler.folded(since_ms=since),
                              "text/plain; charset=utf-8")
            else:
                handler._send(200, json.dumps({
                    "hz": profiler.hz,
                    "achievedHz": profiler.achieved_hz,
                    "samples": profiler.samples_taken,
                    "windowMs": profiler.window_ms,
                    "since": since,
                    "windows": profiler.windows(since_ms=since),
                }))
        elif path == "/backups":
            if self.broker.backup_store is None:
                handler._send(404, json.dumps({"error": "no backup store configured"}))
                return
            statuses = [
                {"checkpointId": s.checkpoint_id, "partitionId": s.partition_id,
                 "status": s.status.value}
                for s in self.broker.backup_store.list_backups()
            ]
            handler._send(200, json.dumps(statuses))
        else:
            handler._send(404, json.dumps({"error": f"unknown path {path}"}))

    def _post(self, handler) -> None:
        path = handler.path.split("?", 1)[0]
        if self.broker is None:
            handler._send(404, json.dumps(
                {"error": "gateway-process management: broker-local "
                          f"endpoint {path} unavailable"}))
            return
        if path.startswith("/backups/"):
            checkpoint_id = int(path.rsplit("/", 1)[-1])
            accepted = self.broker.trigger_checkpoint(checkpoint_id)
            handler._send(202, json.dumps(
                {"checkpointId": checkpoint_id, "partitions": accepted}
            ))
        elif path == "/pause":
            self.broker.pause_processing()
            handler._send(200, json.dumps({"paused": True}))
        elif path == "/resume":
            self.broker.resume_processing()
            handler._send(200, json.dumps({"paused": False}))
        elif path == "/rebalance":
            # leadership rebalancing (reference: actuator RebalancingEndpoint)
            transferred = self.broker.rebalance()
            handler._send(202, json.dumps(
                {"transferred": {str(k): v for k, v in transferred.items()}}
            ))
        elif path == "/profile/device":
            from urllib.parse import parse_qs, urlsplit

            from zeebe_tpu.observability.profiler import CaptureInFlight

            capture = getattr(self.broker, "device_capture", None)
            if capture is None:
                handler._send(404, json.dumps(
                    {"error": "no device capture (broker has no data dir)"}))
                return
            params = parse_qs(urlsplit(handler.path).query)
            seconds = parse_profile_seconds(params.get("seconds", ["3.0"])[0])
            if seconds is None:
                handler._send(400, json.dumps(
                    {"error": "seconds must be a positive number"}))
                return
            try:
                trace_dir = capture.start(seconds)
            except CaptureInFlight as exc:
                # single-flight: jax.profiler supports one trace at a time
                handler._send(409, json.dumps({"error": str(exc)}))
                return
            handler._send(202, json.dumps(
                {"traceDir": str(trace_dir), "seconds": seconds}))
        else:
            handler._send(404, json.dumps({"error": f"unknown path {path}"}))

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="management-server")
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)


PROFILE_MAX_SECONDS = 30.0


def parse_profile_seconds(raw: str) -> float | None:
    """``?seconds=`` validation for /profile: positive finite number, capped
    at :data:`PROFILE_MAX_SECONDS` (the profiler blocks a handler thread for
    the whole window — an uncapped value is a free DoS). None = reject 400."""
    try:
        seconds = min(float(raw), PROFILE_MAX_SECONDS)
    except ValueError:
        return None
    if not 0 < seconds:  # also rejects NaN
        return None
    return seconds


# -- cluster status aggregation ------------------------------------------------

_RATE_WINDOW_MS = 10_000


def _kernel_coverage_row(partition) -> dict:
    """The compact kernelCoverage block riding a /cluster/status partition
    row: cumulative path split + ratio + the dominant host reason (the full
    per-definition report lives on the partition's /health)."""
    backend = partition.processor.kernel_backend
    acct = backend.accounting
    top = acct.reasons.most_common(1)
    # one locked snapshot, the canonical key names (the CLI renderer and
    # the /health block read the same surface)
    device = backend.health.status()
    return {
        "kernelRecords": acct.kernel_records,
        "hostRecords": acct.host_records,
        "coverageRatio": round(acct.coverage_ratio(), 4),
        **({"dominantHostReason": top[0][0]} if top else {}),
        # device-fault defense (ISSUE 15): compact ladder state — the full
        # fault/canary detail lives on the partition's /health
        "device": {k: device[k]
                   for k in ("state", "shadowChecks", "shadowMismatches")},
    }


def broker_status(broker) -> dict:
    """One broker's row in /cluster/status: health, roles, alert state, and
    headline rates read from its time-series store (appends/s from the
    counter-as-rate series, processing/s from the processed-position gauge's
    trailing-window increase, export lag from the per-container lag gauge)."""
    node = broker.cfg.node_id
    status: dict = {
        "nodeId": node,
        "health": broker.health_monitor.status().name,
        "partitions": {
            str(pid): {
                "role": p.role.value, "term": p.raft.current_term,
                "lastPosition": p.stream.last_position,
                # state tiering (ISSUE 8): parked-instance accounting when
                # the cold store is on
                **({"parkedCold": p.tiering.spilled_instances,
                    "parkCandidates": p.tiering.pending_candidates,
                    "coldBytes": p.db.tier_stats()["coldBytes"]}
                   if p.tiering is not None and p.db is not None
                   and hasattr(p.db, "tier_stats") else {}),
                # kernel-path coverage (ISSUE 13): the compact split `cli
                # top` renders (full per-definition detail on /health)
                **({"kernelCoverage": _kernel_coverage_row(p)}
                   if p.processor is not None
                   and p.processor.kernel_backend is not None else {}),
                # at-rest storage integrity (ISSUE 14): compact form — the
                # full detection/repair detail lives on /health
                **({"storageIntegrity": {
                    "status": p.scrubber.status()["status"],
                    "corruptions": len(p.scrubber.detections),
                    "repairs": len(p.scrubber.repairs),
                    "fullPasses": p.scrubber.full_passes,
                }} if p.scrubber is not None else {}),
                # latency observatory (ISSUE 19): last window's per-stage
                # critical path — what `cli top` LATENCY renders
                **({"criticalPath": cp}
                   if getattr(p, "latency_observatory", None) is not None
                   and (cp := p.latency_observatory.status()) is not None
                   else {}),
            }
            for pid, p in sorted(broker.partitions.items())
        },
    }
    alerts = getattr(broker, "alerts", None)
    if alerts is not None:
        firing = alerts.firing()
        status["alertsFiring"] = len(firing)
        status["alerts"] = firing
    control = getattr(broker, "control", None)
    if control is not None:
        # control-plane evidence rides the row: knob values, bounds, and
        # adjustment counts per controller (rendered by `cli top` CONTROL)
        status["control"] = control.snapshot()
    auditor = getattr(broker, "auditor", None)
    if auditor is not None:
        # online-audit evidence (ISSUE 20): latched invariant alerts,
        # burn-rate state, leak verdicts, and the replica-CRC checkpoints
        # the harness-side ClusterAuditor joins across workers
        status["audit"] = auditor.snapshot()
    store = getattr(broker, "timeseries", None)
    if store is not None:
        now = broker.clock_millis()
        node_label = f'node="{node}"'
        append_rate = sum(
            e["value"] for e in store.latest(
                "zeebe_log_appender_record_appended_total")
            if node_label in e["labels"])
        status["rates"] = {
            "appendPerSec": round(append_rate, 1),
            "processedPerSec": round(store.rate(
                "zeebe_stream_processor_last_processed_position",
                _RATE_WINDOW_MS, now, labels_contains=node_label), 1),
        }
        lag = [e["value"] for e in store.latest(
            "zeebe_exporter_container_lag_records")]
        if lag:
            status["rates"]["exportLagRecords"] = max(lag)
    return status


def cluster_status(brokers) -> dict:
    """Aggregate /cluster/status over a set of (in-process) brokers: the
    gossiped topology document (cluster-wide by construction — any broker's
    copy serves), per-broker status rows, and the cluster-level headline."""
    brokers = list(brokers)
    rows = [broker_status(b) for b in brokers]
    topology = brokers[0].topology.topology.summary() if brokers else {}
    partition_ids = {
        pid for member in topology.get("members", {}).values()
        for pid in member.get("partitions", {})
    }
    firing = sum(r.get("alertsFiring", 0) for r in rows)
    worst = max((r["health"] for r in rows), default="HEALTHY",
                key=lambda name: ["HEALTHY", "DEGRADED", "UNHEALTHY",
                                  "DEAD"].index(name))
    return {
        "clusterSize": len(rows),
        "partitionsCount": len(partition_ids),
        "health": worst,
        "alertsFiring": firing,
        "appendPerSec": round(sum(
            r.get("rates", {}).get("appendPerSec", 0.0) for r in rows), 1),
        "processedPerSec": round(sum(
            r.get("rates", {}).get("processedPerSec", 0.0) for r in rows), 1),
        "topology": topology,
        "brokers": rows,
    }

def sample_profile(seconds: float, hz: float = 100.0,
                   fold: bool = False) -> dict:
    """One-shot sampling profiler over every runtime thread (the management
    /profile endpoint — the reference exposes JFR/async-profiler through its
    actuator; this is the in-process equivalent): snapshots all thread
    stacks at ``hz`` for ``seconds`` and aggregates by frame, so hot
    functions and per-thread time split (pump vs kernel vs io) read
    straight off the response without attaching a debugger.

    Sampling rides the shared :mod:`zeebe_tpu.observability.profiler`
    helper, so the thread-name map refreshes every tick (threads spawned
    mid-profile report by name, not raw ident), and pacing is deadline-based
    (sleep-only pacing undershoots ``hz`` by the per-tick work — the
    response carries the *achieved* rate either way). ``fold=True``
    additionally aggregates folded stacks (the same collapsed-stack format
    the continuous profiler serves), so both endpoints feed the same
    flamegraph tooling."""
    import time as _time

    from zeebe_tpu.observability.profiler import (
        PROFILER_THREAD_NAME,
        fold_stacks,
        sample_threads,
    )

    samples = 0
    by_frame: dict[str, int] = {}
    by_thread: dict[str, int] = {}
    folded: dict[str, int] = {}
    start = _time.monotonic()
    deadline = start + seconds
    interval = 1.0 / hz
    next_tick = start + interval
    own = threading.get_ident()
    while _time.monotonic() < deadline:
        # never profile the profilers: not this handler's own stack, and
        # not the continuous sampler's wait loop (default-on — it would
        # otherwise show in ~100% of samples); names refresh inside
        # sample_threads each tick, and so does this ident set
        skip = {own} | {t.ident for t in threading.enumerate()
                        if t.name == PROFILER_THREAD_NAME}
        stacks = sample_threads(exclude_idents=skip, max_depth=40)
        for name, frames in stacks:
            by_thread[name] = by_thread.get(name, 0) + 1
            for key in set(frames):  # recursion must not inflate a frame
                by_frame[key] = by_frame.get(key, 0) + 1
        if fold:
            for key, count in fold_stacks(stacks).items():
                folded[key] = folded.get(key, 0) + count
        samples += 1
        delay = next_tick - _time.monotonic()
        if delay > 0:
            _time.sleep(delay)
            next_tick += interval
        else:
            next_tick = _time.monotonic() + interval  # overran: no burst
    elapsed = max(_time.monotonic() - start, 1e-9)
    top = sorted(by_frame.items(), key=lambda kv: -kv[1])[:50]
    total_stacks = max(sum(by_thread.values()), 1)
    out = {
        "seconds": seconds,
        "samples": samples,
        # sleep/walk overhead means the requested hz is an upper bound;
        # report what the window actually achieved so pct math is honest
        "achievedHz": round(samples / elapsed, 1),
        "threads": dict(sorted(by_thread.items(), key=lambda kv: -kv[1])),
        # pct = share of all sampled thread-stacks that contain the frame
        "hot_frames": [
            {"frame": k, "samples": v,
             "pct": round(100.0 * v / total_stacks, 1)}
            for k, v in top
        ],
    }
    if fold:
        out["folded"] = folded
    return out
