"""Broker composition: partitions over Raft, snapshotting, command ingress.

Reference: broker/ (SURVEY §2.10) — Broker.java:34, BrokerStartupProcess,
ZeebePartition + PartitionTransitionImpl (role-driven transition steps),
AsyncSnapshotDirector, CommandApiRequestHandler, InterPartitionCommandSender.
"""

from zeebe_tpu.broker.partition import ZeebePartition
from zeebe_tpu.broker.broker import (
    Broker,
    BrokerCfg,
    InProcessCluster,
    partition_distribution,
)

__all__ = ["ZeebePartition", "Broker", "BrokerCfg", "InProcessCluster",
           "partition_distribution"]
