"""Broker configuration tree with relaxed environment binding.

Reference: broker/src/main/java/io/camunda/zeebe/broker/system/configuration/
BrokerCfg.java tree (ClusterCfg, DataCfg/DiskCfg, BackpressureCfg,
ProcessingCfg, FeatureFlagsCfg) bound by Spring Boot relaxed binding from
``zeebe.broker.*`` properties / ``ZEEBE_BROKER_*`` env vars
(docs/backpressure.md:23-37 shows the env naming scheme).

``load_broker_cfg`` binds, in precedence order: explicit overrides > env vars >
defaults — e.g. ``ZEEBE_BROKER_CLUSTER_PARTITIONSCOUNT=3`` sets
``cluster.partitions_count``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

from zeebe_tpu.broker.broker import BrokerCfg


@dataclasses.dataclass
class DiskCfg:
    # pause ingestion below the free-space watermark (reference: DiskCfg
    # freeSpace.processing / replication)
    min_free_bytes: int = 128 * 1024 * 1024
    monitoring_interval_ms: int = 10_000
    enable_monitoring: bool = True


@dataclasses.dataclass
class BackpressureCfg:
    enabled: bool = True
    algorithm: str = "vegas"  # vegas | aimd | fixed


@dataclasses.dataclass
class ProcessingCfg:
    max_commands_in_batch: int = 100
    # ingress batch-coalescing window (ms, multiproc worker): commands
    # arriving within the window append as ONE raft batch (one fsync, one
    # replication round). 0 = append per command (the legacy byte path);
    # at runtime the ingress-coalescing controller owns this knob.
    coalesce_window_ms: float = 0.0


@dataclasses.dataclass
class ExtendedBrokerCfg:
    """BrokerCfg + the operational sub-configs."""

    base: BrokerCfg = dataclasses.field(default_factory=BrokerCfg)
    disk: DiskCfg = dataclasses.field(default_factory=DiskCfg)
    backpressure: BackpressureCfg = dataclasses.field(default_factory=BackpressureCfg)
    processing: ProcessingCfg = dataclasses.field(default_factory=ProcessingCfg)

    def validate(self) -> None:
        if self.base.partition_count < 1:
            raise ValueError("partitionsCount must be >= 1")
        if self.base.replication_factor < 1:
            raise ValueError("replicationFactor must be >= 1")
        if self.base.node_id not in self.base.cluster_members:
            raise ValueError(
                f"nodeId {self.base.node_id!r} not in clusterMembers "
                f"{self.base.cluster_members!r}"
            )
        if self.backpressure.algorithm not in ("vegas", "aimd", "fixed"):
            raise ValueError(f"unknown backpressure algorithm "
                             f"{self.backpressure.algorithm!r}")
        if self.processing.max_commands_in_batch < 1:
            raise ValueError("maxCommandsInBatch must be >= 1")
        if self.processing.coalesce_window_ms < 0:
            raise ValueError("coalesceWindowMs must be >= 0")
        if self.base.log_flush_delay_ms < 0:
            raise ValueError("logFlushDelayMs must be >= 0")
        if self.base.log_max_unflushed_bytes < 1:
            raise ValueError("logMaxUnflushedBytes must be >= 1")
        if self.base.snapshot_chain_length < 1:
            raise ValueError("snapshotChainLength must be >= 1")
        if self.base.tiering_park_after_ms < 0:
            raise ValueError("tiering parkAfterMs must be >= 0")
        if self.base.tiering_spill_batch < 1:
            raise ValueError("tiering spillBatch must be >= 1")
        if self.base.scrub_interval_ms < 0:
            raise ValueError("scrub intervalMs must be >= 0")
        if self.base.scrub_bytes_per_pass < 1:
            raise ValueError("scrub bytesPerPass must be >= 1")


# env var → (section, field, type); relaxed-binding names follow the
# reference's ZEEBE_BROKER_<SECTION>_<FIELD> scheme
_ENV_BINDINGS: dict[str, tuple[str, str, Any]] = {
    "ZEEBE_BROKER_CLUSTER_NODEID": ("base", "node_id", str),
    "ZEEBE_BROKER_CLUSTER_PARTITIONSCOUNT": ("base", "partition_count", int),
    "ZEEBE_BROKER_CLUSTER_REPLICATIONFACTOR": ("base", "replication_factor", int),
    "ZEEBE_BROKER_CLUSTER_INITIALCONTACTPOINTS": (
        "base", "cluster_members", lambda v: [m.strip() for m in v.split(",")]),
    "ZEEBE_BROKER_DATA_SNAPSHOTPERIOD": ("base", "snapshot_period_ms", int),
    "ZEEBE_BROKER_DATA_DISK_MINFREEBYTES": ("disk", "min_free_bytes", int),
    "ZEEBE_BROKER_DATA_DISK_ENABLEMONITORING": (
        "disk", "enable_monitoring", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_BACKPRESSURE_ENABLED": (
        "backpressure", "enabled", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_BACKPRESSURE_ALGORITHM": ("backpressure", "algorithm", str),
    "ZEEBE_BROKER_PROCESSING_MAXCOMMANDSINBATCH": (
        "processing", "max_commands_in_batch", int),
    "ZEEBE_BROKER_EXPERIMENTAL_CONSISTENCYCHECKS": (
        "base", "consistency_checks", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_EXPERIMENTAL_KERNELBACKEND": (
        "base", "kernel_backend", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_EXPERIMENTAL_KERNELMESHSHARDS": (
        "base", "kernel_mesh_shards", int),
    "ZEEBE_BROKER_EXPERIMENTAL_DURABLESTATE": (
        "base", "durable_state", lambda v: v.lower() in ("1", "true", "yes")),
    # metrics plane: registry→time-series sampling cadence (0 disables the
    # store, the sampler, and alert evaluation)
    "ZEEBE_BROKER_METRICS_SAMPLINGINTERVALMS": (
        "base", "metrics_sampling_ms", int),
    # continuous profiler: stack sampling rate (0 disables the plane)
    "ZEEBE_BROKER_PROFILING_HZ": ("base", "profiling_hz", float),
    # recovery-time budget: recoveries slower than this fire the
    # recovery_budget_exceeded alert; the snapshot scheduler adapts its
    # cadence to keep projected replay debt under it (<= 0 disables)
    "ZEEBE_BROKER_DATA_RECOVERYBUDGETMS": ("base", "recovery_budget_ms", int),
    # incremental snapshots: base+delta chain length before a full rebase
    # (1 = every snapshot is a full snapshot)
    "ZEEBE_BROKER_DATA_SNAPSHOTCHAINLENGTH": (
        "base", "snapshot_chain_length", int),
    # state tiering (ISSUE 8): cold parked-instance store — spill instances
    # parked past PARKAFTERMS to disk, SPILLBATCH instances per pump pass
    "ZEEBE_BROKER_DATA_TIERING_ENABLED": (
        "base", "tiering", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_DATA_TIERING_PARKAFTERMS": (
        "base", "tiering_park_after_ms", int),
    "ZEEBE_BROKER_DATA_TIERING_SPILLBATCH": (
        "base", "tiering_spill_batch", int),
    # raft journal group-commit pacing (ISSUE 12): 0 = fsync before every
    # ack; > 0 = defer the fsync up to this many ms (acks wait for it)
    "ZEEBE_BROKER_DATA_LOGFLUSHDELAYMS": ("base", "log_flush_delay_ms", int),
    "ZEEBE_BROKER_DATA_LOGMAXUNFLUSHEDBYTES": (
        "base", "log_max_unflushed_bytes", int),
    # ingress batch-coalescing window (multiproc worker ingress)
    "ZEEBE_BROKER_PROCESSING_COALESCEWINDOWMS": (
        "processing", "coalesce_window_ms", float),
    # at-rest storage scrubber (ISSUE 14): pump-throttled background CRC
    # walk over journals, snapshot chains, and cold segments
    "ZEEBE_BROKER_DATA_SCRUB_ENABLED": (
        "base", "scrub", lambda v: v.lower() in ("1", "true", "yes")),
    "ZEEBE_BROKER_DATA_SCRUB_INTERVALMS": (
        "base", "scrub_interval_ms", int),
    "ZEEBE_BROKER_DATA_SCRUB_BYTESPERPASS": (
        "base", "scrub_bytes_per_pass", int),
}


def load_broker_cfg(env: dict[str, str] | None = None,
                    overrides: dict[str, Any] | None = None) -> ExtendedBrokerCfg:
    env = os.environ if env is None else env
    cfg = ExtendedBrokerCfg()
    for var, (section, field, convert) in _ENV_BINDINGS.items():
        if var in env:
            setattr(getattr(cfg, section), field, convert(env[var]))
    for dotted, value in (overrides or {}).items():
        section, field = dotted.split(".", 1)
        setattr(getattr(cfg, section), field, value)
    if cfg.base.node_id not in cfg.base.cluster_members and \
            cfg.base.cluster_members == ["broker-0"]:
        # single-node default: the node is its own cluster
        cfg.base.cluster_members = [cfg.base.node_id]
    cfg.validate()
    return cfg
