"""Disk-space monitor: pause ingestion when the data volume runs low.

Reference: broker/src/main/java/io/camunda/zeebe/broker/system/monitoring/
DiskSpaceUsageMonitorActor.java:22,57-72 — periodic free-space check against
the configured watermark; listeners pause command ingestion (and exporting)
while below it and resume once space frees up. Processing of already-committed
work continues so the log can compact itself back under the watermark.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Callable


class DiskSpaceMonitor:
    def __init__(self, directory: str | Path, min_free_bytes: int,
                 interval_ms: int = 10_000,
                 clock_millis: Callable[[], int] | None = None) -> None:
        import time

        self.directory = Path(directory)
        self.min_free_bytes = min_free_bytes
        self.interval_ms = interval_ms
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self.out_of_space = False
        self._last_check_ms = 0
        self.listeners: list[Callable[[bool], None]] = []

    def free_bytes(self) -> int:
        return shutil.disk_usage(self.directory).free

    def check(self, now_millis: int | None = None) -> bool:
        """Returns True when ingestion must pause. Rate-limited by interval."""
        now = self.clock_millis() if now_millis is None else now_millis
        if now - self._last_check_ms < self.interval_ms:
            return self.out_of_space
        self._last_check_ms = now
        below = self.free_bytes() < self.min_free_bytes
        if below != self.out_of_space:
            self.out_of_space = below
            for listener in self.listeners:
                listener(below)
        return self.out_of_space
