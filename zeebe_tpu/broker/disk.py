"""Disk-space monitor: pause ingestion when the data volume runs low.

Reference: broker/src/main/java/io/camunda/zeebe/broker/system/monitoring/
DiskSpaceUsageMonitorActor.java:22,57-72 — periodic free-space check against
the configured watermark; listeners pause command ingestion (and exporting)
while below it and resume once space frees up. Processing of already-committed
work continues so the log can compact itself back under the watermark.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Callable

from zeebe_tpu.utils.zlogging import Loggers


class DiskSpaceMonitor:
    def __init__(self, directory: str | Path, min_free_bytes: int,
                 interval_ms: int = 10_000,
                 clock_millis: Callable[[], int] | None = None) -> None:
        import time

        self.directory = Path(directory)
        self.min_free_bytes = min_free_bytes
        self.interval_ms = interval_ms
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self.out_of_space = False
        self._last_check_ms = 0
        self.listeners: list[Callable[[bool], None]] = []

    def free_bytes(self) -> int:
        """Free bytes on the data volume; -1 when the directory cannot be
        statted (vanished / unmounted mid-run) — the caller must treat that
        as out-of-space, not crash the tick loop."""
        try:
            return shutil.disk_usage(self.directory).free
        except OSError:
            Loggers.SYSTEM.exception(
                "disk usage check failed for %s — treating as out of space",
                self.directory)
            return -1

    def check(self, now_millis: int | None = None) -> bool:
        """Returns True when ingestion must pause. Rate-limited by interval."""
        now = self.clock_millis() if now_millis is None else now_millis
        if now - self._last_check_ms < self.interval_ms:
            return self.out_of_space
        self._last_check_ms = now
        below = self.free_bytes() < self.min_free_bytes
        if below != self.out_of_space:
            # flip the flag BEFORE notifying: a throwing listener must not
            # leave the monitor claiming the old state
            self.out_of_space = below
            for listener in self.listeners:
                try:
                    listener(below)
                except Exception:  # noqa: BLE001 — pause/resume must reach
                    # every remaining listener even if one throws
                    Loggers.SYSTEM.exception(
                        "disk-space listener failed (out_of_space=%s)", below)
        return self.out_of_space
