"""Broker: one node hosting a set of partition replicas.

Reference: broker/src/main/java/io/camunda/zeebe/broker/Broker.java:34 and
BrokerStartupProcess.java:49-67 (ordered steps: cluster services → command API
→ partition manager), PartitionManagerImpl + RoundRobinPartitionDistributor
(topology/util/RoundRobinPartitionDistributor.java), and the command API
ingress CommandApiRequestHandler.java:77-132.

``InProcessCluster`` is the ClusteringRule equivalent (qa/integration-tests
ClusteringRule.java:105): N brokers in one process over the loopback network,
with a deterministic pump — the primary multi-node test harness.
"""

from __future__ import annotations

import dataclasses
import tempfile
from pathlib import Path
from typing import Any, Callable

from zeebe_tpu.broker.partition import ZeebePartition
from zeebe_tpu.cluster.membership import MembershipService
from zeebe_tpu.cluster.messaging import LoopbackNetwork, MessagingService
from zeebe_tpu.cluster.raft import ELECTION_TIMEOUT_MS
from zeebe_tpu.protocol import Record
from zeebe_tpu.protocol.msgpack import packb, unpackb

INTER_PARTITION_TOPIC = "inter-partition"  # + "-<partition id>"
COMMAND_API_TOPIC = "command-api"  # + "-<partition id>"


@dataclasses.dataclass
class BrokerCfg:
    """The `zeebe.broker.*` configuration subset that shapes the cluster
    (reference: system/configuration/BrokerCfg.java, ClusterCfg)."""

    node_id: str = "broker-0"
    partition_count: int = 1
    replication_factor: int = 1
    cluster_members: list[str] = dataclasses.field(default_factory=lambda: ["broker-0"])
    snapshot_period_ms: int = 5 * 60 * 1000
    consistency_checks: bool = True
    # the device-kernel batched execution backend behind the stream processor
    # (reference: FeatureFlagsCfg-style gate). ON by default: the serving
    # path IS the kernel path; eligible commands batch onto the device,
    # everything else falls through to the sequential engine unchanged.
    kernel_backend: bool = True
    # > 0: the partitions' kernel groups run as shards of ONE device mesh
    # (parallel/mesh_runner.py) — partition = shard of the device batch.
    # -1 (default) = auto: shard over jax.devices() when more than one
    # device is attached, single-device otherwise. 0 = explicitly off.
    # A shared MeshKernelRunner may also be injected by the hosting runtime
    # (ClusterRuntime) so in-process brokers share a single mesh.
    kernel_mesh_shards: int = -1
    # disk-backed state with O(delta) checkpoints (state/durable.py) — the
    # large-state backend (reference: RocksDB zb-db + its checkpoint story).
    # Off by default: the in-memory store wins below ~100 MB of state.
    durable_state: bool = False
    # metrics plane (observability/timeseries.py): registry sampling cadence
    # for the in-memory time-series store + alert evaluation. 0 disables the
    # whole plane — no store, no sampler, one is-None check per control pump.
    metrics_sampling_ms: int = 250
    # continuous profiling plane (observability/profiler.py): stack sampling
    # rate of the always-on folded-stack profiler. 0 disables it (one is-None
    # check); the ~19 Hz default is a prime rate (GWP-style: cannot alias
    # against millisecond-periodic work) cheap enough to leave on.
    profiling_hz: float = 19.0
    # recovery-time budget (ISSUE 6): a partition rebuild (snapshot install +
    # replay) slower than this increments recovery_budget_exceeded_total
    # (default alert rule recovery_budget_exceeded) and the snapshot
    # scheduler snapshots early when projected replay debt threatens the
    # budget. <= 0 disables budget enforcement (metrics still emit).
    recovery_budget_ms: int = 60_000
    # max incremental-snapshot chain length (base + deltas) before the next
    # snapshot rebases to a full one; 1 = every snapshot is full
    snapshot_chain_length: int = 8
    # state tiering (ISSUE 8): spill parked process instances (waiting on
    # timers/messages/jobs past tiering_park_after_ms) from the hot dict to
    # a disk-backed cold store, faulting back in transparently on wake —
    # bounded RSS under a million-instance parked backlog. Off by default;
    # DURABLESTATE supersedes it (the durable backend has its own tiers).
    tiering: bool = False
    tiering_park_after_ms: int = 30_000
    tiering_spill_batch: int = 256
    # raft journal group-commit pacing (ISSUE 12): 0 = fsync before every
    # ack (the reference default); > 0 = defer the fsync up to this many ms
    # (or max_unflushed_bytes), with acks strictly AFTER the covering fsync
    # — the journal-flush controller's knob surface
    log_flush_delay_ms: int = 0
    log_max_unflushed_bytes: int = 1 << 20
    # closed-loop control plane (ISSUE 12): controllers tick off the pump
    # and drive the knob surface from the time-series store; requires the
    # metrics plane (its sensor). Off = the plane is not constructed.
    control: bool = True
    # at-rest storage scrubber (ISSUE 14): pump-throttled background CRC
    # walk over sealed journal bytes, snapshot chain files, and cold-store
    # segments — bit rot is detected (and repaired) before a read serves
    # it. ON by default: the budget bounds the pump cost per slice.
    scrub: bool = True
    scrub_interval_ms: int = 1_000
    scrub_bytes_per_pass: int = 4 << 20


_AUTO_DEVICE_COUNT: int | None = None


def _auto_device_count() -> int:
    """Device count for kernel_mesh_shards auto mode, resolved ONCE per
    process. When the platform is already pinned to cpu (tests, drive
    scripts) the in-process query is safe; otherwise the default backend is
    probed in a killable subprocess — on this host class a wedged TPU
    tunnel can hang jax.devices() forever (see utils/backend_probe.py), and
    broker startup must never block on it. Probe failure = 0 (no mesh)."""
    global _AUTO_DEVICE_COUNT
    if _AUTO_DEVICE_COUNT is None:
        import jax

        if str(jax.config.jax_platforms or "").startswith("cpu"):
            _AUTO_DEVICE_COUNT = len(jax.devices())
        else:
            from zeebe_tpu.utils.backend_probe import probe_default_backend

            probed = probe_default_backend()
            _AUTO_DEVICE_COUNT = 0 if probed is None else probed[1]
    return _AUTO_DEVICE_COUNT


def partition_distribution(cfg: BrokerCfg) -> dict[int, list[str]]:
    """Round-robin partition→members assignment (reference:
    RoundRobinPartitionDistributor): partition p starts at member
    (p-1) % n and takes the next replication_factor members."""
    n = len(cfg.cluster_members)
    members = sorted(cfg.cluster_members)
    out: dict[int, list[str]] = {}
    for p in range(1, cfg.partition_count + 1):
        start = (p - 1) % n
        out[p] = [members[(start + i) % n] for i in range(min(cfg.replication_factor, n))]
    return out


class ClusterInterPartitionSender:
    """InterPartitionCommandSenderImpl equivalent: resolve the partition leader
    and ship the command over cluster messaging (topic inter-partition-<id>,
    reference: broker/…/partitionapi/InterPartitionCommandSenderImpl.java:27-80)."""

    def __init__(self, broker: "Broker") -> None:
        self.broker = broker

    def send_command(self, receiver_partition_id: int, record: Record) -> None:
        leader = self.broker.known_leader(receiver_partition_id)
        if leader is None:
            return  # no known leader: the redistributor/checker will retry
        # piggyback the checkpoint id: the receiver creates the checkpoint
        # BEFORE processing, keeping cluster-wide backups consistent
        # (reference: InterPartitionCommandSenderImpl checkpoint-id prefix)
        payload = {"record": record.to_bytes(), "key": record.key,
                   "checkpointId": self.broker.latest_checkpoint_id()}
        self.broker.messaging.send(
            leader, f"{INTER_PARTITION_TOPIC}-{receiver_partition_id}", payload
        )


def resolve_leader_partition(brokers, partition_id: int):
    """The partition replica that currently owns leadership: during failover a
    deposed-but-isolated leader may still claim the role; the highest term wins
    (the gateway resolves the same way via gossiped topology)."""
    best, best_term = None, -1
    for b in brokers:
        p = b.partitions.get(partition_id)
        if p is not None and p.is_leader and p.raft.current_term > best_term:
            best, best_term = p, p.raft.current_term
    return best


class Broker:
    def __init__(self, cfg: BrokerCfg, messaging: MessagingService,
                 directory: str | Path | None = None,
                 clock_millis: Callable[[], int] | None = None,
                 exporters_factory: Callable[[], dict[str, Any]] | None = None,
                 response_sink: Callable[[Any], None] | None = None,
                 backup_store: Any | None = None,
                 backup_store_directory: str | Path | None = None,
                 backpressure_algorithm: str = "vegas",
                 backpressure_enabled: bool = True,
                 disk_min_free_bytes: int = 0,
                 mesh_runner=None) -> None:
        import time

        from zeebe_tpu.broker.disk import DiskSpaceMonitor
        from zeebe_tpu.utils.health import CriticalComponentsHealthMonitor
        from zeebe_tpu.utils.metrics import REGISTRY

        self.cfg = cfg
        self.messaging = messaging
        self._injected_mesh_runner = mesh_runner
        self._owned_mesh_runner = None
        self._tmp = None
        if directory is None:
            self._tmp = tempfile.TemporaryDirectory()
            directory = self._tmp.name
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.clock_millis = clock_millis or (lambda: int(time.time() * 1000))
        self.disk_monitor = (
            DiskSpaceMonitor(self.directory, disk_min_free_bytes,
                             clock_millis=self.clock_millis)
            if disk_min_free_bytes > 0 else None
        )
        self.membership = MembershipService(
            messaging, cfg.cluster_members, self.clock_millis
        )
        self.health_monitor = CriticalComponentsHealthMonitor(cfg.node_id)
        # metrics plane: flight recorder always on (recording is O(1) deque
        # appends); time-series store + sampler + alerts gated by cfg
        from zeebe_tpu.observability.flight_recorder import (
            FlightRecorder,
            install_journal_stall_listener,
        )

        self.flight_recorder = FlightRecorder(
            cfg.node_id, self.directory, clock_millis=self.clock_millis)
        install_journal_stall_listener(self.flight_recorder)
        # continuous profiling plane: always-on folded-stack sampler (gated
        # by cfg like the metrics plane), alert-triggered capture into the
        # flight recorder, and the single-flight on-demand device capture.
        # Importing the module also registers the xla-compile / device-memory
        # metric families the kernel seam and the pump sampler feed.
        from zeebe_tpu.observability import profiler as profiler_mod

        self._profiler_mod = profiler_mod
        if cfg.profiling_hz > 0:
            # process-global sampler, leased: an in-process multi-broker
            # cluster shares ONE sampling daemon instead of stacking N
            self.profiler, self._profiler_lease = (
                profiler_mod.acquire_profiler(
                    hz=cfg.profiling_hz, clock_millis=self.clock_millis))
            # dumps carry the recent hot stacks alongside the event rings
            self.flight_recorder.add_context_provider(
                lambda: {"profile": self.profiler.snapshot_summary()})
        else:
            self.profiler: profiler_mod.ContinuousProfiler | None = None
            self._profiler_lease: object | None = None
        self._alert_profile_capture = profiler_mod.AlertProfileCapture(
            self.flight_recorder, self.profiler,
            clock_millis=self.clock_millis)
        self.device_capture = profiler_mod.DeviceTraceCapture(self.directory)
        if cfg.metrics_sampling_ms > 0:
            from zeebe_tpu.observability.alerts import AlertEvaluator
            from zeebe_tpu.observability.timeseries import (
                MetricsSampler,
                TimeSeriesStore,
            )
            from zeebe_tpu.utils.metrics import install_process_metrics

            # the rss_watermark default rule reads the process self-metrics
            # gauge: make sure it exists wherever the alert plane runs
            # (idempotent; refresh rides the sampler's collect hooks)
            install_process_metrics(REGISTRY)
            self.timeseries: TimeSeriesStore | None = TimeSeriesStore()
            self.sampler: MetricsSampler | None = MetricsSampler(
                REGISTRY, self.timeseries,
                interval_ms=cfg.metrics_sampling_ms,
                clock_millis=self.clock_millis)
            self.alerts: AlertEvaluator | None = AlertEvaluator(
                self.timeseries, node_id=cfg.node_id,
                on_transition=self._on_alert_transition)
            # dumps carry the alert state alongside the event rings
            self.flight_recorder.add_context_provider(
                lambda: {"alerts": self.alerts.snapshot()})
            # the fleet auditor (ISSUE 20) rides the same cadence: online
            # invariant monitors + burn-rate + leak trends; its burn-rate
            # rules append onto self.alerts, so it constructs after it
            from zeebe_tpu.observability.auditor import BrokerAuditor

            self.auditor: BrokerAuditor | None = BrokerAuditor(self)
        else:
            self.timeseries = None
            self.sampler = None
            self.alerts = None
            self.auditor = None
        self.health_monitor.add_listener(self._on_health_transition)
        self._metrics = {
            "written": REGISTRY.counter(
                "log_appender_record_appended_total",
                "records appended to partition logs", ("node", "partition")),
            "dropped": REGISTRY.counter(
                "backpressure_requests_dropped_total",
                "client commands rejected by backpressure", ("node", "partition")),
            "inflight": REGISTRY.gauge(
                "backpressure_inflight_requests_count",
                "commands appended but not yet processed", ("node", "partition")),
            "role": REGISTRY.gauge(
                "raft_role", "1=leader 0=follower", ("node", "partition")),
            "term": REGISTRY.gauge(
                "raft_term", "current raft term", ("node", "partition")),
            "commit": REGISTRY.gauge(
                "raft_commit_index", "raft commit index", ("node", "partition")),
            "processed": REGISTRY.gauge(
                "stream_processor_last_processed_position",
                "last processed record position", ("node", "partition")),
            "exported": REGISTRY.gauge(
                "exporter_last_exported_position",
                "lowest acked exporter position (lag = appended - this)",
                ("node", "partition")),
            "snapshot": REGISTRY.gauge(
                "snapshot_index", "raft index of the latest snapshot",
                ("node", "partition")),
            "health": REGISTRY.gauge(
                "health", "0=healthy 1=degraded 2=unhealthy 3=dead", ("node",)),
            "join_time": REGISTRY.histogram(
                "partition_server_join_time",
                "seconds to join a partition at runtime", ("partition",)),
            # state-tiering plane (ISSUE 8)
            "state_keys": REGISTRY.gauge(
                "state_keys",
                "committed state keys per column family",
                ("node", "partition", "cf")),
            "tier_bytes": REGISTRY.gauge(
                "state_tier_bytes",
                "state bytes per tier (hot = estimated packed size of "
                "resident values, cold = exact live cold-store bytes)",
                ("node", "partition", "tier")),
            "parked": REGISTRY.gauge(
                "state_parked_instances",
                "process instances parked in a wait state and spilled cold, "
                "plus pending park candidates",
                ("node", "partition", "kind")),
        }
        # cf-gauge children already emitted per partition: a CF that empties
        # must drop to 0, not freeze at its last count
        self._state_cf_seen: dict[int, set[str]] = {}
        self._state_gauges_ms = 0
        self.responses: list = []
        # per-partition ownership guard (set by ClusterRuntime): topology-
        # driven partition lifecycle must not close journals under a pump
        # running on that partition's ownership thread
        self._partition_guard: Callable[[int], Any] | None = None
        sink = response_sink if response_sink is not None else self.responses.append
        backup_service = None
        if backup_store is not None:
            # remote store instance (S3BackupStore / GcsBackupStore) supplied
            # by the operator shell (reference: backup-stores selection via
            # zeebe.broker.data.backup.store config)
            from zeebe_tpu.backup import BackupService

            self.backup_store = backup_store
            backup_service = BackupService(self.backup_store, cfg.node_id)
        elif backup_store_directory is not None:
            from zeebe_tpu.backup import BackupService, FileSystemBackupStore

            self.backup_store = FileSystemBackupStore(backup_store_directory)
            backup_service = BackupService(self.backup_store, cfg.node_id)
        else:
            self.backup_store = None
        self.partitions: dict[int, ZeebePartition] = {}
        # one TieringCfg shared by every partition (control-plane actuated)
        self._shared_tiering_cfg = None
        # closed-loop control plane (ISSUE 12) — built AFTER the partitions
        # exist, at the end of __init__; None = disabled (one is-None check
        # per control pump is the whole disabled cost)
        self.control = None
        # gateway-facing jobs-available listener (runtime hub); assignable
        # after construction — partitions route through the indirection below
        self.jobs_listener: Callable[[int, set], None] | None = None
        self._sender = ClusterInterPartitionSender(self)
        self._exporters_factory = exporters_factory
        self._response_sink = sink
        self._backup_service = backup_service
        self._backpressure_algorithm = backpressure_algorithm
        self._backpressure_enabled = backpressure_enabled
        # dynamic topology: gossiped versioned document + change plans
        # (reference: topology/ClusterTopologyManager); bootstrapped from the
        # static distribution on first start, RESTORED from disk afterwards —
        # a restart must not forget partitions that were moved here at runtime
        from zeebe_tpu.cluster.topology import TopologyManager

        self._topology_path = self.directory / "topology.json"
        self.topology = TopologyManager(
            cfg.node_id, self.membership,
            start_replica=self._create_partition_for_join,
            stop_replica=self._stop_partition,
            raft_of=lambda pid: (
                self.partitions[pid].raft if pid in self.partitions else None
            ),
            request_reconfigure=self._request_reconfigure,
            persist=self._persist_topology,
        )
        start_steps = REGISTRY.histogram(
            "broker_start_step_latency",
            "seconds per broker startup step", ("step",))
        step_start = time.perf_counter()
        saved = self._load_topology()
        if saved is not None:
            self.topology.restore(saved)
            start_steps.labels("topology-restore").observe(
                time.perf_counter() - step_start)
            step_start = time.perf_counter()
            for pid, (members, priority) in self.topology.own_partitions().items():
                self._create_partition(pid, members, priority)
        else:
            distribution = partition_distribution(cfg)
            for partition_id, members in distribution.items():
                if cfg.node_id in members:
                    self._create_partition(partition_id, members)
            self.topology.bootstrap(distribution, sorted(cfg.cluster_members))
        start_steps.labels("partition-manager").observe(
            time.perf_counter() - step_start)
        if cfg.control:
            # the plane needs the time-series store (its sensor) and the
            # partitions (its knob surface): last startup step by design
            from zeebe_tpu.control import maybe_build_plane

            self.control = maybe_build_plane(self)

    # -- metrics plane ---------------------------------------------------------

    def _on_health_transition(self, report) -> None:
        """Health changes land in the flight recorder; a transition to
        UNHEALTHY/DEAD dumps the rings to disk — the postmortem must exist
        BEFORE anyone asks for it."""
        from zeebe_tpu.utils.health import HealthStatus

        component = report.component
        partition_id = 0
        if component.startswith("partition-"):
            try:
                partition_id = int(component[len("partition-"):].split(".")[0])
            except ValueError:
                pass
        self.flight_recorder.record(
            partition_id, "health", component=component,
            status=report.status.name, message=report.message)
        if report.status >= HealthStatus.UNHEALTHY:
            self.flight_recorder.dump(f"unhealthy:{component}")

    def _on_alert_transition(self, rule, labels: str, old: str,
                             new: str) -> None:
        self.flight_recorder.record(
            0, "alert", rule=rule.name, labels=labels, state=new,
            previous=old, expr=rule.describe())
        if new == "firing":
            # attach what the threads were doing when the rule fired (short
            # folded-stack profile, throttled per rule) — a dump then
            # explains the *why* next to the *what*
            self._alert_profile_capture.on_firing(rule.name, labels)

    def hard_crash(self) -> None:
        """Power-loss crash for the whole broker (chaos harness): dump the
        flight rings FIRST — the dump is the black box a real crash handler
        would flush — then lose every unfsynced byte."""
        for pid in self.partitions:
            self.flight_recorder.record(
                pid, "crash", detail="power-loss (hard crash)")
        self.flight_recorder.dump("hard-crash", force=True)
        self._remove_journal_listener()
        self._profiler_mod.release_profiler(self._profiler_lease)
        self._profiler_lease = None
        self.device_capture.cancel()
        for partition in self.partitions.values():
            partition.hard_crash()

    def _remove_journal_listener(self) -> None:
        from zeebe_tpu.observability.flight_recorder import (
            remove_journal_stall_listener,
        )

        remove_journal_stall_listener(self.flight_recorder)

    def _persist_topology(self, doc: dict) -> None:
        import json

        tmp = self._topology_path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(doc))
        tmp.replace(self._topology_path)

    def _load_topology(self) -> dict | None:
        import json

        if not self._topology_path.exists():
            return None
        try:
            return json.loads(self._topology_path.read_text())
        except (OSError, ValueError):
            return None

    def _partition_lifecycle_guard(self, partition_id: int):
        from contextlib import nullcontext

        if self.partition_guard is None:
            return nullcontext()
        return self.partition_guard(partition_id)

    @property
    def partition_guard(self):
        return self._partition_guard

    @partition_guard.setter
    def partition_guard(self, guard) -> None:
        # the topology manager applies partition-scoped operations
        # (reconfigure, replica lifecycle) under the same ownership guard
        self._partition_guard = guard
        self.topology.partition_guard = guard

    def _mesh_runner(self):
        """The shared kernel mesh runner: injected by the hosting runtime
        (one mesh per process), or lazily created from
        ``cfg.kernel_mesh_shards`` for a standalone broker. None = the
        kernel runs single-device."""
        if not self.cfg.kernel_backend:
            return None  # the kernel backend is the runner's only consumer
        if self._injected_mesh_runner is not None:
            return self._injected_mesh_runner
        shards = self.cfg.kernel_mesh_shards
        if shards < 0:
            # auto: shard over the attached devices, capped at the broker's
            # partition count (extra shards would be permanent dummy-block
            # padding and larger per-chunk transfers); below 2 the direct
            # single-device dispatch path wins (no runner indirection)
            shards = min(_auto_device_count(), self.cfg.partition_count)
            if shards < 2:
                shards = 0
        if shards > 0 and self._owned_mesh_runner is None:
            from zeebe_tpu.parallel.mesh_runner import MeshKernelRunner

            self._owned_mesh_runner = MeshKernelRunner(n_shards=shards)
        return self._owned_mesh_runner

    def _tiering_cfg(self):
        """The partition-facing TieringCfg, or None when tiering is off.
        ONE shared instance per broker: every partition's manager reads the
        same object, so the state-tiering controller's actuator (the single
        runtime write path for park_after_ms/spill_batch) steers them all."""
        if not self.cfg.tiering:
            return None
        if self._shared_tiering_cfg is None:
            from zeebe_tpu.state.tiering import TieringCfg

            self._shared_tiering_cfg = TieringCfg(
                enabled=True,
                park_after_ms=self.cfg.tiering_park_after_ms,
                spill_batch=self.cfg.tiering_spill_batch,
            )
        return self._shared_tiering_cfg

    def _scrub_cfg(self):
        """The partition-facing ScrubCfg, or None when scrubbing is off."""
        if not self.cfg.scrub:
            return None
        from zeebe_tpu.broker.scrubber import ScrubCfg

        return ScrubCfg(enabled=True,
                        interval_ms=self.cfg.scrub_interval_ms,
                        bytes_per_pass=self.cfg.scrub_bytes_per_pass)

    def _create_partition(self, partition_id: int, members: list[str],
                          priority: int = 1) -> None:
        import time as _time

        from zeebe_tpu.broker.backpressure import CommandRateLimiter

        bootstrap_start = _time.perf_counter()

        limiter = CommandRateLimiter(
            self._backpressure_algorithm, clock_millis=self.clock_millis,
        ) if self._backpressure_enabled else None
        self.partitions[partition_id] = ZeebePartition(
            self.messaging, partition_id, members,
            self.directory / f"partition-{partition_id}",
            self.clock_millis,
            partition_count=self.cfg.partition_count,
            exporters_factory=self._exporters_factory,
            inter_partition_sender=self._sender,
            response_sink=self._response_sink,
            snapshot_period_ms=self.cfg.snapshot_period_ms,
            consistency_checks=self.cfg.consistency_checks,
            backup_service=self._backup_service,
            on_checkpoint=self._observe_checkpoint,
            backpressure=limiter,
            priority=priority,
            on_jobs_available=self._on_jobs_available,
            kernel_backend_enabled=self.cfg.kernel_backend,
            mesh_runner=self._mesh_runner(),
            durable_state=self.cfg.durable_state,
            health_monitor=self.health_monitor,
            flight_recorder=self.flight_recorder,
            recovery_budget_ms=self.cfg.recovery_budget_ms,
            snapshot_chain_length=self.cfg.snapshot_chain_length,
            tiering=self._tiering_cfg(),
            log_flush_delay_ms=self.cfg.log_flush_delay_ms,
            log_max_unflushed_bytes=self.cfg.log_max_unflushed_bytes,
            scrub=self._scrub_cfg(),
        )
        self.health_monitor.register(f"partition-{partition_id}")
        from zeebe_tpu.utils.metrics import REGISTRY as _REG

        _REG.histogram(
            "partition_server_bootstrap_time",
            "seconds to bootstrap a partition server", ("partition",)
        ).labels(str(partition_id)).observe(
            _time.perf_counter() - bootstrap_start)
        self.messaging.subscribe(
            f"{INTER_PARTITION_TOPIC}-{partition_id}",
            lambda s, p, pid=partition_id: self._on_inter_partition_command(pid, s, p),
        )
        self.messaging.subscribe(
            f"{COMMAND_API_TOPIC}-{partition_id}",
            lambda s, p, pid=partition_id: self._on_client_command(pid, s, p),
        )
        self.messaging.subscribe(
            f"raft-reconfigure-{partition_id}",
            lambda s, p, pid=partition_id: self._on_reconfigure_request(pid, s, p),
        )
        self.messaging.subscribe(
            f"raft-reconfigure-done-{partition_id}",
            lambda s, p, pid=partition_id: self._on_reconfigure_confirmed(pid, s, p),
        )

    def _create_partition_for_join(self, partition_id: int, members: list[str],
                                   priority: int = 1) -> None:
        """Topology PARTITION_JOIN: bootstrap a replica that is not yet part
        of the raft group (it syncs via append/snapshot once the leader adds
        it through reconfiguration)."""
        if partition_id not in self.partitions:
            import time as _time

            join_start = _time.perf_counter()
            self._create_partition(partition_id, members, priority)
            self._metrics["join_time"].labels(str(partition_id)).observe(
                _time.perf_counter() - join_start)

    _PARTITION_TOPICS = (
        "{t}-vote", "{t}-vote-resp", "{t}-append", "{t}-append-resp",
        "{t}-snapshot",
    )

    def _stop_partition(self, partition_id: int) -> None:
        with self._partition_lifecycle_guard(partition_id):
            self._stop_partition_locked(partition_id)

    def _stop_partition_locked(self, partition_id: int) -> None:
        partition = self.partitions.pop(partition_id, None)
        if partition is None:
            return
        # drop every handler first: a straggler raft message must never
        # dispatch into a replica whose journals are closed
        raft_topic = f"raft-{partition_id}"
        for template in self._PARTITION_TOPICS:
            self.messaging.unsubscribe(template.format(t=raft_topic))
        for topic in (f"{INTER_PARTITION_TOPIC}-{partition_id}",
                      f"{COMMAND_API_TOPIC}-{partition_id}",
                      f"raft-reconfigure-{partition_id}",
                      f"raft-reconfigure-done-{partition_id}"):
            self.messaging.unsubscribe(topic)
        self.health_monitor.deregister(f"partition-{partition_id}")
        # per-exporter sub-components ("partition-N.exporter-…") go with it
        self.health_monitor.deregister_matching(f"partition-{partition_id}.")
        partition.close()

    def _request_reconfigure(self, partition_id: int, change: dict) -> None:
        leader = self.known_leader(partition_id)
        payload = {**change, "from": self.cfg.node_id}
        if leader is not None and leader != self.cfg.node_id:
            self.messaging.send(leader, f"raft-reconfigure-{partition_id}", payload)
        elif leader == self.cfg.node_id:
            self._on_reconfigure_request(partition_id, self.cfg.node_id, payload)

    def _on_reconfigure_request(self, partition_id: int, sender: str,
                                payload: dict) -> None:
        """Reconfigure INTENT ({"add": m} / {"remove": m}): the leader derives
        the new member list from its OWN configuration — a requester with a
        stale view must never shrink the group past its intent."""
        partition = self.partitions.get(partition_id)
        if partition is None or not partition.is_leader:
            return
        raft = partition.raft
        members = set(raft.members)
        if payload.get("add"):
            members.add(payload["add"])
        if payload.get("remove"):
            members.discard(payload["remove"])
        if len(members) >= 1:
            raft.reconfigure(sorted(members))
        # confirm with the authoritative post-change membership so the
        # requester can complete its topology operation even if the raft
        # config entry never reaches it (e.g. it was the removed member)
        requester = payload.get("from", sender)
        if requester != self.cfg.node_id:
            self.messaging.send(
                requester, f"raft-reconfigure-done-{partition_id}",
                {"members": raft.members},
            )

    def _on_reconfigure_confirmed(self, partition_id: int, sender: str,
                                  payload: dict) -> None:
        self.topology.on_reconfigure_confirmed(partition_id, payload["members"])

    # -- command ingress -------------------------------------------------------

    def _on_inter_partition_command(self, partition_id: int, sender: str,
                                    payload: dict) -> None:
        record = Record.from_bytes(payload["record"])
        record = record.replace(key=payload.get("key", record.key))
        partition = self.partitions.get(partition_id)
        if partition is None or not partition.is_leader:
            return
        incoming_checkpoint = payload.get("checkpointId", 0)
        if incoming_checkpoint > partition.latest_checkpoint_id():
            from zeebe_tpu.protocol import ValueType as _VT
            from zeebe_tpu.protocol import command as _command
            from zeebe_tpu.protocol.intent import CheckpointIntent as _CI

            partition.write_commands([_command(
                _VT.CHECKPOINT, _CI.CREATE, {"checkpointId": incoming_checkpoint},
            )])
        partition.write_commands([record])

    def _on_client_command(self, partition_id: int, sender: str,
                           payload: dict) -> None:
        record = Record.from_bytes(payload["record"])
        partition = self.partitions.get(partition_id)
        if partition is not None and partition.is_leader:
            partition.write_commands([record])

    def write_command(self, partition_id: int, record: Record) -> int | None:
        """Local API ingress (the gateway talks to the leader broker):
        backpressure + disk-pause gated, unlike internal write paths."""
        partition = self.partitions.get(partition_id)
        if partition is None or not partition.is_leader:
            return None
        return partition.client_write(record)

    def _on_jobs_available(self, partition_id: int, job_types: set) -> None:
        if self.jobs_listener is not None:
            self.jobs_listener(partition_id, job_types)

    # -- topology --------------------------------------------------------------

    def preferred_leader(self, partition_id: int) -> str | None:
        """The replica with the highest topology priority for a partition —
        the target leadership rebalancing converges to (reference:
        PartitionLeaderElection priorities; priorities are assigned
        round-robin at bootstrap, ClusterTopology.initial)."""
        from zeebe_tpu.cluster.topology import ACTIVE

        best: str | None = None
        best_prio = -1
        for member_id, mstate in self.topology.topology.members.items():
            if mstate.get("state") != ACTIVE:
                continue  # leaving/left members must not attract leadership
            p = mstate.get("partitions", {}).get(str(partition_id))
            if p is None or p.get("state", ACTIVE) != ACTIVE:
                continue  # joining replicas may still be catching up
            prio = p.get("priority", 1)
            if prio > best_prio or (prio == best_prio and (best is None or member_id < best)):
                best, best_prio = member_id, prio
        return best

    def rebalance(self) -> dict[int, str]:
        """Leadership rebalancing (reference: dist/…/management/
        RebalancingEndpoint.java): for every LOCAL partition this broker
        leads whose preferred (highest-priority) replica is someone else,
        transfer raft leadership there. Returns partition → transfer target
        for the transfers actually initiated (best-effort, like the
        reference's actuator)."""
        transferred: dict[int, str] = {}
        # list(): served on the management HTTP thread while topology changes
        # may add/remove partitions concurrently
        for pid, partition in list(self.partitions.items()):
            if not partition.is_leader:
                continue
            preferred = self.preferred_leader(pid)
            if (preferred is not None and preferred != self.cfg.node_id
                    and partition.raft.transfer_leadership(preferred)):
                transferred[pid] = preferred
        return transferred

    def known_leader(self, partition_id: int) -> str | None:
        """Leader member for a partition: local raft knowledge first, then
        gossiped broker info (reference: BrokerTopologyManager)."""
        local = self.partitions.get(partition_id)
        if local is not None:
            if local.is_leader:
                return self.cfg.node_id
            if local.raft.leader_id is not None:
                return local.raft.leader_id
        for member in list(self.membership.members.values()):
            roles = member.properties.get("partitions", {})
            if roles.get(str(partition_id)) == "leader":
                return member.member_id
        return None

    def _gossip_roles(self) -> None:
        roles = {
            str(pid): ("leader" if p.is_leader else "follower")
            for pid, p in self.partitions.items()
        }
        current = self.membership.properties.get("partitions")
        if current != roles:
            self.membership.set_property("partitions", roles)

    # -- pump ------------------------------------------------------------------

    def pump(self) -> int:
        """One scheduling round: raft timers, membership, partition work."""
        work = self.pump_control()
        for pid in list(self.partitions):
            work += self.pump_partition(pid)
        return work

    def pump_partition(self, partition_id: int) -> int:
        """Advance ONE partition replica (raft timers + processing) — the
        per-partition ownership thread's slice of pump(). The partition may
        disappear mid-call under a concurrent topology change; the owning
        runtime's pump guard absorbs the resulting error for one tick."""
        partition = self.partitions.get(partition_id)
        if partition is None:
            return 0
        partition.tick()
        return partition.pump()

    def pump_control(self) -> int:
        """Advance the broker-level services (membership, topology, disk
        monitor, observability, role gossip) — the control thread's slice.
        Reads of partition state here are lock-free attribute reads; they may
        lag a partition thread by a tick, which gossip tolerates by design."""
        self.membership.tick()
        self.topology.tick()
        if self.disk_monitor is not None:
            disk_paused = self.disk_monitor.check()
            for partition in list(self.partitions.values()):
                partition.disk_paused = disk_paused
        self._update_observability()
        if self.sampler is not None and self.sampler.maybe_sample():
            # device memory rides the metrics cadence: stats read straight
            # off already-initialized devices (profiler._resolve_devices
            # never touches an unpinned, uninitialized accelerator backend)
            self._profiler_mod.sample_device_memory()
            if self.auditor is not None:
                # audit BEFORE the alert sweep so the burn-rate series this
                # tick publishes is what the evaluator judges
                self.auditor.tick(self.clock_millis())
            self.alerts.evaluate(self.clock_millis())
        if self.control is not None:
            # control ticks AFTER the sampler: decisions see telemetry at
            # most one sampling interval old
            self.control.maybe_tick(self.clock_millis())
        self._gossip_roles()
        return 0

    def _update_observability(self) -> None:
        from zeebe_tpu.utils.health import HealthStatus

        node = self.cfg.node_id
        # the per-CF key-count gauges bisect the whole key index: 1s cadence,
        # not every pump round
        now_ms = self.clock_millis()
        for pid, partition in self.partitions.items():
            label = str(pid)
            self._metrics["role"].labels(node, label).set(
                1 if partition.is_leader else 0)
            if partition.limiter is not None:
                self._metrics["inflight"].labels(node, label).set(
                    len(partition.limiter.in_flight))
                dropped = self._metrics["dropped"].labels(node, label)
                dropped.value = float(partition.limiter.dropped_total)
            self._metrics["written"].labels(node, label).value = float(
                partition.stream.last_position)
            self._metrics["term"].labels(node, label).set(
                float(partition.raft.current_term))
            self._metrics["commit"].labels(node, label).set(
                float(partition.raft.commit_index))
            self._metrics["snapshot"].labels(node, label).set(
                float(partition.raft.snapshot_index))
            if partition.processor is not None:
                self._metrics["processed"].labels(node, label).set(
                    float(partition.processor.last_processed_position))
            if partition.exporter_director is not None:
                exported = partition.exporter_director.lowest_exporter_position()
                if exported < 2**62:
                    self._metrics["exported"].labels(node, label).set(
                        float(exported))
            db = partition.db
            if db is not None and not db.in_transaction \
                    and now_ms - self._state_gauges_ms >= 1000:
                counts = db.key_counts_by_cf()
                seen = self._state_cf_seen.setdefault(pid, set())
                for cf_name in seen - counts.keys():
                    self._metrics["state_keys"].labels(
                        node, label, cf_name).set(0.0)
                for cf_name, count in counts.items():
                    self._metrics["state_keys"].labels(
                        node, label, cf_name).set(float(count))
                seen.update(counts)
                stats = (db.tier_stats() if hasattr(db, "tier_stats")
                         else None)
                if stats is not None:
                    self._metrics["tier_bytes"].labels(node, label, "hot").set(
                        float(stats["hotBytesEstimate"]))
                    self._metrics["tier_bytes"].labels(node, label, "cold").set(
                        float(stats["coldBytes"]))
                if partition.tiering is not None:
                    self._metrics["parked"].labels(node, label, "cold").set(
                        float(partition.tiering.spilled_instances))
                    self._metrics["parked"].labels(
                        node, label, "candidate").set(
                        float(partition.tiering.pending_candidates))
            failed = (
                partition.processor is not None
                and partition.processor.phase.value == "failed"
            )
            self.health_monitor.report(
                f"partition-{pid}",
                HealthStatus.UNHEALTHY if failed else HealthStatus.HEALTHY,
            )
        if now_ms - self._state_gauges_ms >= 1000:
            self._state_gauges_ms = now_ms
        self._metrics["health"].labels(node).set(
            float(self.health_monitor.status()))

    def close(self) -> None:
        import time as _time

        from zeebe_tpu.utils.metrics import REGISTRY as _REG

        close_latency = _REG.histogram(
            "broker_close_step_latency",
            "seconds per broker shutdown step", ("step",))
        self._remove_journal_listener()
        self._profiler_mod.release_profiler(self._profiler_lease)
        self._profiler_lease = None
        # an in-flight device trace would otherwise keep jax's global
        # profiler occupied and write into a directory about to disappear
        self.device_capture.cancel()
        for pid, partition in self.partitions.items():
            step_start = _time.perf_counter()
            partition.close()
            close_latency.labels(f"partition-{pid}").observe(
                _time.perf_counter() - step_start)
        if self._tmp is not None:
            self._tmp.cleanup()

    def health(self) -> dict:
        return {
            "nodeId": self.cfg.node_id,
            "partitions": [p.health() for p in self.partitions.values()],
        }

    def pause_processing(self) -> None:
        """BrokerAdminService pause: stop accepting client commands."""
        for partition in self.partitions.values():
            partition.paused = True

    def resume_processing(self) -> None:
        for partition in self.partitions.values():
            partition.paused = False

    # -- backup ----------------------------------------------------------------

    _checkpoint_cache = 0

    def latest_checkpoint_id(self) -> int:
        """Hot path (piggybacked on every inter-partition send): cached, and
        bumped by the partitions' checkpoint-created listeners."""
        if self._checkpoint_cache == 0:
            self._checkpoint_cache = max(
                (p.latest_checkpoint_id() for p in list(self.partitions.values())),
                default=0,
            )
        return self._checkpoint_cache

    def _observe_checkpoint(self, checkpoint_id: int) -> None:
        if checkpoint_id > self._checkpoint_cache:
            self._checkpoint_cache = checkpoint_id

    def trigger_checkpoint(self, checkpoint_id: int) -> int:
        """Write CHECKPOINT CREATE to every local leader partition (the admin
        BackupRequest fan-out, reference: BackupApiRequestHandler). Returns how
        many partitions accepted the trigger."""
        from zeebe_tpu.protocol import ValueType as _VT
        from zeebe_tpu.protocol import command as _command
        from zeebe_tpu.protocol.intent import CheckpointIntent as _CI

        count = 0
        for partition in self.partitions.values():
            if partition.is_leader and partition.write_commands([_command(
                _VT.CHECKPOINT, _CI.CREATE, {"checkpointId": checkpoint_id},
            )]) is not None:
                count += 1
        return count


class InProcessCluster:
    """N brokers over the loopback network with a shared controlled clock —
    the ClusteringRule equivalent for multi-broker tests."""

    def __init__(self, broker_count: int = 3, partition_count: int = 3,
                 replication_factor: int = 3,
                 directory: str | Path | None = None,
                 exporters_factory: Callable[[], dict[str, Any]] | None = None,
                 snapshot_period_ms: int = 5 * 60 * 1000,
                 durable_state: bool = False,
                 network: LoopbackNetwork | None = None,
                 recovery_budget_ms: int = 60_000,
                 snapshot_chain_length: int = 8,
                 tiering: bool = False,
                 tiering_park_after_ms: int = 30_000,
                 tiering_spill_batch: int = 256) -> None:
        from zeebe_tpu.testing import ControlledClock

        self._tmp = None
        if directory is None:
            self._tmp = tempfile.TemporaryDirectory()
            directory = self._tmp.name
        self.directory = Path(directory)
        self.clock = ControlledClock()
        # injectable network: the chaos harness passes a fault-injecting
        # ChaosNetwork; default stays the plain deterministic loopback
        self.net = network if network is not None else LoopbackNetwork()
        members = [f"broker-{i}" for i in range(broker_count)]
        self.brokers: dict[str, Broker] = {}
        self._exporters_factory = exporters_factory
        # crashed brokers' configs, kept for restart_broker (snapshot period
        # and durable-state settings ride along inside the BrokerCfg)
        self._stopped_cfgs: dict[str, BrokerCfg] = {}
        for m in members:
            cfg = BrokerCfg(
                node_id=m, partition_count=partition_count,
                replication_factor=replication_factor, cluster_members=members,
                snapshot_period_ms=snapshot_period_ms,
                durable_state=durable_state,
                recovery_budget_ms=recovery_budget_ms,
                snapshot_chain_length=snapshot_chain_length,
                tiering=tiering,
                tiering_park_after_ms=tiering_park_after_ms,
                tiering_spill_batch=tiering_spill_batch,
            )
            self.brokers[m] = Broker(
                cfg, self.net.join(m), directory=self.directory / m,
                clock_millis=self.clock,
                exporters_factory=exporters_factory,
            )

    def run(self, millis: int, step: int = 50) -> None:
        for _ in range(max(millis // step, 1)):
            self.clock.advance(step)
            for broker in self.brokers.values():
                broker.pump()
            self.net.deliver_all()
            # drain work produced by delivered messages (commits → processing)
            for _ in range(20):
                moved = sum(b.pump() for b in self.brokers.values())
                self.net.deliver_all()
                if moved == 0 and not self.net.queue:
                    break

    def await_leaders(self) -> None:
        """Run until every partition has an elected leader."""
        for _ in range(40):
            self.run(ELECTION_TIMEOUT_MS)
            if all(
                self.leader(p) is not None
                for p in range(1, next(iter(self.brokers.values())).cfg.partition_count + 1)
            ):
                return
        raise RuntimeError("leaders not elected")

    def leader(self, partition_id: int) -> ZeebePartition | None:
        leaders = [
            b.partitions[partition_id]
            for b in self.brokers.values()
            if partition_id in b.partitions and b.partitions[partition_id].is_leader
        ]
        return leaders[0] if len(leaders) == 1 else None

    def leader_broker(self, partition_id: int) -> Broker | None:
        """During failover a deposed-but-isolated leader may still claim the
        role; the highest term wins (the gateway resolves the same way via
        gossiped topology, which always carries the newest term's claim)."""
        leader = resolve_leader_partition(self.brokers.values(), partition_id)
        if leader is None:
            return None
        for b in self.brokers.values():
            if b.partitions.get(partition_id) is leader:
                return b
        return None

    def write_command(self, partition_id: int, record: Record) -> int | None:
        broker = self.leader_broker(partition_id)
        if broker is None:
            return None
        position = broker.write_command(partition_id, record)
        self.run(300)
        return position

    def stop_broker(self, node_id: str) -> None:
        """Crash a broker mid-run: close its journals (durable state stays on
        disk), drop it from the network so in-flight traffic to it is lost,
        and forget it until ``restart_broker``."""
        broker = self.brokers.pop(node_id, None)
        if broker is None:
            raise KeyError(f"unknown broker {node_id}")
        self._stopped_cfgs[node_id] = broker.cfg
        self.net.leave(node_id)
        broker.close()

    def hard_crash_broker(self, node_id: str) -> None:
        """Power-loss crash: like ``stop_broker`` but journals lose every
        byte not covered by an fsync (the chaos suite's flush-boundary fault
        — a crash between a buffered append and its covering flush). Raft's
        ack barrier fsyncs before acknowledging, so acked entries survive;
        the unacked buffered suffix is legitimately gone."""
        broker = self.brokers.pop(node_id, None)
        if broker is None:
            raise KeyError(f"unknown broker {node_id}")
        self._stopped_cfgs[node_id] = broker.cfg
        self.net.leave(node_id)
        # dumps the flight rings (the black box), then loses unfsynced bytes
        broker.hard_crash()
        # the data directory stays intact (cluster brokers always get one
        # from the cluster): restart_broker recovers the fsynced prefix

    def restart_broker(self, node_id: str) -> Broker:
        """Rebuild a crashed broker over its on-disk directory: raft journal,
        stream journal, and snapshots recover exactly as a real process
        restart would (reference: ClusteringRule.restartBroker)."""
        cfg = self._stopped_cfgs.pop(node_id, None)
        if cfg is None:
            raise KeyError(f"broker {node_id} was not stopped")
        broker = Broker(
            cfg, self.net.join(node_id), directory=self.directory / node_id,
            clock_millis=self.clock, exporters_factory=self._exporters_factory,
        )
        self.brokers[node_id] = broker
        return broker

    def add_broker(self, node_id: str) -> Broker:
        """Start a NEW broker that joins the running cluster with no
        partitions of its own (the dynamic-topology entry point: move
        partitions onto it with topology change operations afterwards)."""
        seeds = sorted(self.brokers)
        cfg = BrokerCfg(
            node_id=node_id,
            partition_count=next(iter(self.brokers.values())).cfg.partition_count,
            replication_factor=next(iter(self.brokers.values())).cfg.replication_factor,
            cluster_members=seeds,  # not itself: hosts nothing at bootstrap
        )
        broker = Broker(cfg, self.net.join(node_id),
                        directory=self.directory / node_id,
                        clock_millis=self.clock)
        self.brokers[node_id] = broker
        return broker

    def close(self) -> None:
        for broker in self.brokers.values():
            broker.close()
        if self._tmp is not None:
            self._tmp.cleanup()
