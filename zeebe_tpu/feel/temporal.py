"""FEEL temporal types: date, time, date-and-time, and the two durations.

Reference: expression-language/src/main/java/io/camunda/zeebe/el/impl/
FeelExpressionLanguage.java:22-36 wires the camunda FEEL Scala engine, whose
temporal semantics follow the DMN FEEL spec: four temporal value types
(``date``, ``time``, ``date and time``) plus two duration types
(days-and-time ``duration`` and ``years and months duration``), ISO-8601
literal syntax behind ``@"..."``, calendar arithmetic, and component
properties. This module implements that surface from scratch on top of
Python ``datetime``/``zoneinfo``.

Values serialize back to ISO-8601 strings at the variable-store boundary
(the reference's MessagePackValueMapper.scala writes FEEL temporals as
msgpack strings), so device/host variable documents never carry rich
objects.

FEEL-lite extension kept for engine ergonomics: plain numbers interoperate
with temporals as *milliseconds* (``now() + 1000``), matching the engine's
epoch-millis clock plumbing.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import re
from typing import Any

try:  # zoneinfo is stdlib ≥3.9; @"…@Zone" literals need it
    import zoneinfo as _zoneinfo
except ImportError:  # pragma: no cover
    _zoneinfo = None


class TemporalParseError(ValueError):
    pass


_UTC = _dt.timezone.utc

# ---------------------------------------------------------------------------
# Durations


@dataclasses.dataclass(frozen=True, slots=True, order=True)
class Duration:
    """Days-and-time duration: a fixed span in milliseconds (``P1DT2H``)."""

    millis: int

    # -- components (FEEL properties: days/hours/minutes/seconds) -----------
    @property
    def days(self) -> int:
        return int(abs(self.millis) // 86_400_000) * (1 if self.millis >= 0 else -1)

    @property
    def hours(self) -> int:
        return int((abs(self.millis) % 86_400_000) // 3_600_000) * (1 if self.millis >= 0 else -1)

    @property
    def minutes(self) -> int:
        return int((abs(self.millis) % 3_600_000) // 60_000) * (1 if self.millis >= 0 else -1)

    @property
    def seconds(self) -> float:
        s = (abs(self.millis) % 60_000) / 1000.0
        return s if self.millis >= 0 else -s

    def __str__(self) -> str:
        ms = abs(self.millis)
        sign = "-" if self.millis < 0 else ""
        days, ms = divmod(ms, 86_400_000)
        hours, ms = divmod(ms, 3_600_000)
        minutes, ms = divmod(ms, 60_000)
        seconds = ms / 1000.0
        out = f"{sign}P"
        if days:
            out += f"{days}D"
        time_part = ""
        if hours:
            time_part += f"{hours}H"
        if minutes:
            time_part += f"{minutes}M"
        if seconds:
            text = f"{seconds:.3f}".rstrip("0").rstrip(".")
            time_part += f"{text}S"
        if time_part:
            out += "T" + time_part
        if out in ("P", "-P"):
            out = sign + "PT0S"
        return out

    def __neg__(self) -> "Duration":
        return Duration(-self.millis)

    def __abs__(self) -> "Duration":
        return Duration(abs(self.millis))


@dataclasses.dataclass(frozen=True, slots=True, order=True)
class YearMonthDuration:
    """Years-and-months duration: a calendar span in months (``P1Y2M``)."""

    months: int

    @property
    def years(self) -> int:
        return int(abs(self.months) // 12) * (1 if self.months >= 0 else -1)

    # FEEL property is "months" = remainder after years; expose via accessor
    # name "months_part" internally, property lookup maps it.
    @property
    def months_part(self) -> int:
        return int(abs(self.months) % 12) * (1 if self.months >= 0 else -1)

    def __str__(self) -> str:
        m = abs(self.months)
        sign = "-" if self.months < 0 else ""
        years, months = divmod(m, 12)
        out = f"{sign}P"
        if years:
            out += f"{years}Y"
        if months or not years:
            out += f"{months}M"
        return out

    def __neg__(self) -> "YearMonthDuration":
        return YearMonthDuration(-self.months)

    def __abs__(self) -> "YearMonthDuration":
        return YearMonthDuration(abs(self.months))


# ---------------------------------------------------------------------------
# Date / time / date-and-time


def _fmt_offset(offset: _dt.timedelta | None) -> str:
    if offset is None:
        return ""
    total = int(offset.total_seconds())
    if total == 0:
        return "Z"
    sign = "+" if total >= 0 else "-"
    total = abs(total)
    hh, rem = divmod(total, 3600)
    mm = rem // 60
    return f"{sign}{hh:02d}:{mm:02d}"


@dataclasses.dataclass(frozen=True, slots=True, order=True)
class FeelDate:
    """Calendar date (``date("2026-07-31")``)."""

    d: _dt.date

    @property
    def year(self) -> int:
        return self.d.year

    @property
    def month(self) -> int:
        return self.d.month

    @property
    def day(self) -> int:
        return self.d.day

    @property
    def weekday(self) -> int:
        return self.d.isoweekday()

    def __str__(self) -> str:
        return self.d.isoformat()


@dataclasses.dataclass(frozen=True, slots=True, order=True)
class FeelTime:
    """Wall-clock time, optionally zoned (``time("14:30:00+02:00")``)."""

    t: _dt.time  # tzinfo carries the offset when zoned
    # display-only zone name: equal instants must compare equal regardless
    # of whether the zone came from an offset or an @Zone name
    zone: str | None = dataclasses.field(default=None, compare=False)

    @property
    def hour(self) -> int:
        return self.t.hour

    @property
    def minute(self) -> int:
        return self.t.minute

    @property
    def second(self) -> int:
        return self.t.second

    @property
    def time_offset(self) -> Duration | None:
        off = self.t.utcoffset()
        return None if off is None else Duration(int(off.total_seconds() * 1000))

    def __str__(self) -> str:
        base = self.t.replace(tzinfo=None).isoformat()
        if self.t.microsecond == 0:
            base = base[:8]
        if self.zone:
            return f"{base}@{self.zone}"
        return base + _fmt_offset(self.t.utcoffset())


@dataclasses.dataclass(frozen=True, slots=True, order=True)
class FeelDateTime:
    """Date-and-time, optionally zoned (``date and time("…T…Z")``)."""

    dt: _dt.datetime
    zone: str | None = dataclasses.field(default=None, compare=False)

    @classmethod
    def from_epoch_millis(cls, millis: int) -> "FeelDateTime":
        return cls(_dt.datetime.fromtimestamp(millis / 1000.0, tz=_UTC))

    @property
    def epoch_millis(self) -> int:
        if self.dt.tzinfo is None:
            # local (unzoned) datetimes anchor to UTC for engine arithmetic
            return int(self.dt.replace(tzinfo=_UTC).timestamp() * 1000)
        return int(self.dt.timestamp() * 1000)

    @property
    def year(self) -> int:
        return self.dt.year

    @property
    def month(self) -> int:
        return self.dt.month

    @property
    def day(self) -> int:
        return self.dt.day

    @property
    def weekday(self) -> int:
        return self.dt.isoweekday()

    @property
    def hour(self) -> int:
        return self.dt.hour

    @property
    def minute(self) -> int:
        return self.dt.minute

    @property
    def second(self) -> int:
        return self.dt.second

    @property
    def time_offset(self) -> Duration | None:
        off = self.dt.utcoffset()
        return None if off is None else Duration(int(off.total_seconds() * 1000))

    def date(self) -> FeelDate:
        return FeelDate(self.dt.date())

    def time(self) -> FeelTime:
        return FeelTime(self.dt.timetz(), zone=self.zone)

    def __str__(self) -> str:
        base = self.dt.replace(tzinfo=None).isoformat()
        if self.dt.microsecond == 0:
            base = base[:19]  # seconds always printed (reference format)
        if self.zone:
            return f"{base}@{self.zone}"
        return base + _fmt_offset(self.dt.utcoffset())


# ---------------------------------------------------------------------------
# Parsing

_DATE_RE = re.compile(r"^(\d{4})-(\d{2})-(\d{2})$")
_TIME_RE = re.compile(
    r"^(\d{2}):(\d{2})(?::(\d{2})(?:\.(\d{1,9}))?)?"
    r"(Z|[+-]\d{2}:\d{2}|@[A-Za-z_][A-Za-z0-9_/+\-]*)?$"
)
_DT_DURATION_RE = re.compile(
    r"^(?P<sign>-)?P(?:(?P<days>\d+(?:\.\d+)?)D)?"
    r"(?:T(?:(?P<hours>\d+(?:\.\d+)?)H)?(?:(?P<minutes>\d+(?:\.\d+)?)M)?"
    r"(?:(?P<seconds>\d+(?:\.\d+)?)S)?)?$"
)
_YM_DURATION_RE = re.compile(r"^(?P<sign>-)?P(?:(?P<years>\d+)Y)?(?:(?P<months>\d+)M)?$")


def _tz_from_suffix(suffix: str) -> tuple[_dt.tzinfo | None, str | None]:
    """'Z' / '+02:00' / '@Europe/Berlin' → (tzinfo, zone-name-or-None)."""
    if not suffix:
        return None, None
    if suffix == "Z":
        return _UTC, None
    if suffix.startswith("@"):
        name = suffix[1:]
        if _zoneinfo is None:
            raise TemporalParseError(f"zone literals unsupported: {suffix!r}")
        try:
            return _zoneinfo.ZoneInfo(name), name
        except Exception as exc:
            raise TemporalParseError(f"unknown zone {name!r}") from exc
    sign = 1 if suffix[0] == "+" else -1
    hh, mm = int(suffix[1:3]), int(suffix[4:6])
    return _dt.timezone(sign * _dt.timedelta(hours=hh, minutes=mm)), None


def parse_date(text: str) -> FeelDate:
    m = _DATE_RE.match(text.strip())
    if not m:
        raise TemporalParseError(f"invalid date: {text!r}")
    try:
        return FeelDate(_dt.date(int(m.group(1)), int(m.group(2)), int(m.group(3))))
    except ValueError as exc:
        raise TemporalParseError(f"invalid date: {text!r}") from exc


def parse_time(text: str) -> FeelTime:
    m = _TIME_RE.match(text.strip())
    if not m:
        raise TemporalParseError(f"invalid time: {text!r}")
    hh, mm = int(m.group(1)), int(m.group(2))
    ss = int(m.group(3) or 0)
    frac = m.group(4) or ""
    micros = int((frac + "000000")[:6]) if frac else 0
    tz, zone = _tz_from_suffix(m.group(5) or "")
    if zone is not None and tz is not None:
        # a bare time has no date for DST resolution: pin the named zone's
        # offset at a fixed anchor date so utcoffset()/comparisons work
        # (times are instant-compared on a shared anchor day anyway)
        anchor = _dt.datetime(2000, 1, 1, hh, mm, ss, tzinfo=tz)
        tz = _dt.timezone(anchor.utcoffset() or _dt.timedelta())
    try:
        return FeelTime(_dt.time(hh, mm, ss, micros, tzinfo=tz), zone=zone)
    except ValueError as exc:
        raise TemporalParseError(f"invalid time: {text!r}") from exc


def parse_date_time(text: str) -> FeelDateTime:
    text = text.strip()
    if not _DT_PREFIX_RE.match(text):
        # a bare date is a valid date-and-time at midnight (camunda-feel)
        d = parse_date(text)
        return FeelDateTime(_dt.datetime.combine(d.d, _dt.time(0, 0, 0)))
    date_part, time_part = text.split("T", 1)
    d = parse_date(date_part)
    t = parse_time(time_part)
    tzinfo = t.t.tzinfo
    if t.zone is not None:
        # named zone: resolve DST at the actual date, not parse_time's
        # fixed anchor day
        tz, _ = _tz_from_suffix("@" + t.zone)
        tzinfo = tz
    return FeelDateTime(
        _dt.datetime.combine(d.d, t.t.replace(tzinfo=tzinfo)), zone=t.zone
    )


def parse_duration(text: str) -> Duration | YearMonthDuration:
    text = text.strip()
    ym = _YM_DURATION_RE.match(text)
    if ym and (ym.group("years") or ym.group("months")):
        months = int(ym.group("years") or 0) * 12 + int(ym.group("months") or 0)
        return YearMonthDuration(-months if ym.group("sign") else months)
    m = _DT_DURATION_RE.match(text)
    if m and text not in ("P", "-P", "PT", "-PT"):
        days = float(m.group("days") or 0)
        hours = float(m.group("hours") or 0)
        minutes = float(m.group("minutes") or 0)
        seconds = float(m.group("seconds") or 0)
        if days == hours == minutes == seconds == 0 and "0" not in text:
            raise TemporalParseError(f"empty duration: {text!r}")
        millis = int(((days * 24 + hours) * 60 + minutes) * 60_000 + seconds * 1000)
        return Duration(-millis if m.group("sign") else millis)
    raise TemporalParseError(f"invalid duration: {text!r}")


_DT_PREFIX_RE = re.compile(r"^\d{4}-\d{2}-\d{2}T")


def parse_temporal_literal(text: str) -> Any:
    """Classify an ``@"…"`` literal body by shape (the four FEEL kinds).
    Date-and-time is recognized by its ``YYYY-MM-DDT`` prefix, not by a bare
    'T' search — zone names like Asia/Tokyo contain a T."""
    s = text.strip()
    if s.startswith("P") or s.startswith("-P"):
        return parse_duration(s)
    if _DT_PREFIX_RE.match(s):
        return parse_date_time(s)
    if _DATE_RE.match(s):
        return parse_date(s)
    if _TIME_RE.match(s):
        return parse_time(s)
    raise TemporalParseError(f"unrecognized temporal literal: {text!r}")


# ---------------------------------------------------------------------------
# Calendar arithmetic


def _add_months(d: _dt.date, months: int) -> _dt.date:
    month0 = d.month - 1 + months
    year = d.year + month0 // 12
    month = month0 % 12 + 1
    # clamp to end of month (ISO semantics: Jan 31 + P1M = Feb 28/29)
    day = min(d.day, _days_in_month(year, month))
    return _dt.date(year, month, day)


def _days_in_month(year: int, month: int) -> int:
    if month == 12:
        return 31
    return (_dt.date(year, month + 1, 1) - _dt.timedelta(days=1)).day


def is_temporal(v: Any) -> bool:
    return isinstance(v, (FeelDate, FeelTime, FeelDateTime, Duration, YearMonthDuration))


def temporal_add(left: Any, right: Any) -> Any:
    """FEEL '+' over temporal operands; returns NotImplemented when the pair
    has no defined sum (caller falls through to its numeric path)."""
    # numbers interoperate as milliseconds (FEEL-lite extension)
    if isinstance(left, (int, float)) and not isinstance(left, bool):
        left = Duration(int(left))
    if isinstance(right, (int, float)) and not isinstance(right, bool):
        right = Duration(int(right))
    if isinstance(left, Duration) and isinstance(right, Duration):
        return Duration(left.millis + right.millis)
    if isinstance(left, YearMonthDuration) and isinstance(right, YearMonthDuration):
        return YearMonthDuration(left.months + right.months)
    if isinstance(left, Duration) and isinstance(right, (FeelDateTime, FeelDate, FeelTime)):
        return temporal_add(right, left)
    if isinstance(left, YearMonthDuration) and isinstance(right, (FeelDateTime, FeelDate)):
        return temporal_add(right, left)
    if isinstance(left, FeelDateTime) and isinstance(right, Duration):
        return FeelDateTime(left.dt + _dt.timedelta(milliseconds=right.millis), zone=left.zone)
    if isinstance(left, FeelDateTime) and isinstance(right, YearMonthDuration):
        new_date = _add_months(left.dt.date(), right.months)
        return FeelDateTime(_dt.datetime.combine(new_date, left.dt.timetz()), zone=left.zone)
    if isinstance(left, FeelDate) and isinstance(right, Duration):
        return FeelDate(left.d + _dt.timedelta(milliseconds=right.millis))
    if isinstance(left, FeelDate) and isinstance(right, YearMonthDuration):
        return FeelDate(_add_months(left.d, right.months))
    if isinstance(left, FeelTime) and isinstance(right, Duration):
        anchor = _dt.datetime.combine(_dt.date(2000, 1, 1), left.t)
        moved = anchor + _dt.timedelta(milliseconds=right.millis)
        return FeelTime(moved.timetz(), zone=left.zone)
    return NotImplemented


def temporal_sub(left: Any, right: Any) -> Any:
    """FEEL '-' over temporal operands; NotImplemented when undefined."""
    if isinstance(right, (int, float)) and not isinstance(right, bool):
        right = Duration(int(right))
    if isinstance(left, (int, float)) and not isinstance(left, bool):
        left = Duration(int(left))
    if isinstance(left, FeelDateTime) and isinstance(right, FeelDateTime):
        return Duration(left.epoch_millis - right.epoch_millis)
    if isinstance(left, FeelDate) and isinstance(right, FeelDate):
        return Duration((left.d - right.d).days * 86_400_000)
    if isinstance(left, FeelTime) and isinstance(right, FeelTime):
        anchor = _dt.date(2000, 1, 1)
        a = _dt.datetime.combine(anchor, left.t)
        b = _dt.datetime.combine(anchor, right.t)
        if (a.tzinfo is None) != (b.tzinfo is None):
            return NotImplemented
        return Duration(int((a - b).total_seconds() * 1000))
    if isinstance(left, (FeelDateTime, FeelDate, FeelTime)) and isinstance(
        right, (Duration, YearMonthDuration)
    ):
        return temporal_add(left, -right)
    if isinstance(left, Duration) and isinstance(right, Duration):
        return Duration(left.millis - right.millis)
    if isinstance(left, YearMonthDuration) and isinstance(right, YearMonthDuration):
        return YearMonthDuration(left.months - right.months)
    return NotImplemented


def temporal_mul(left: Any, right: Any) -> Any:
    if isinstance(left, (int, float)) and not isinstance(left, bool):
        left, right = right, left
    if isinstance(right, (int, float)) and not isinstance(right, bool):
        if isinstance(left, Duration):
            return Duration(int(left.millis * right))
        if isinstance(left, YearMonthDuration):
            return YearMonthDuration(int(left.months * right))
    return NotImplemented


def temporal_div(left: Any, right: Any) -> Any:
    if isinstance(left, Duration) and isinstance(right, Duration):
        return None if right.millis == 0 else left.millis / right.millis
    if isinstance(left, YearMonthDuration) and isinstance(right, YearMonthDuration):
        return None if right.months == 0 else left.months / right.months
    if isinstance(right, (int, float)) and not isinstance(right, bool):
        if right == 0:
            return None
        if isinstance(left, Duration):
            return Duration(int(left.millis / right))
        if isinstance(left, YearMonthDuration):
            return YearMonthDuration(int(left.months / right))
    return NotImplemented


# FEEL property names → python attribute (shared by date/time/datetime/durations)
_PROPERTIES = {
    "year": "year",
    "month": "month",
    "day": "day",
    "weekday": "weekday",
    "hour": "hour",
    "minute": "minute",
    "second": "second",
    "time offset": "time_offset",
    "days": "days",
    "hours": "hours",
    "minutes": "minutes",
    "seconds": "seconds",
    "years": "years",
    "months": "months_part",
}


def temporal_property(value: Any, name: str) -> Any:
    attr = _PROPERTIES.get(name)
    if attr is None or not hasattr(type(value), attr):
        return None
    return getattr(value, attr)


def _contains_temporal(v: Any) -> bool:
    if is_temporal(v):
        return True
    if isinstance(v, list):
        return any(_contains_temporal(x) for x in v)
    if isinstance(v, dict):
        return any(_contains_temporal(x) for x in v.values())
    return False


def normalize_value(v: Any) -> Any:
    """Temporal values → ISO strings for the variable store (recursively);
    everything else passes through UNTOUCHED — the common all-plain case must
    not pay a copy on the per-variable hot path. The variable document
    boundary is where rich FEEL values become msgpack-representable
    (reference: feel/src/main/scala/…/MessagePackValueMapper.scala)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if not _contains_temporal(v):
        return v
    if is_temporal(v):
        return str(v)
    if isinstance(v, list):
        return [normalize_value(x) for x in v]
    if isinstance(v, dict):
        return {k: normalize_value(x) for k, x in v.items()}
    return v
